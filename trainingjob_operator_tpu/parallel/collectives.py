"""Mesh-aware collective helpers: topology introspection + ring primitives.

XLA compiles named-axis collectives onto ICI (intra-slice) or DCN (across
slices); there is no NCCL-style backend to manage (SURVEY.md §5.8) -- what IS
ours to get right is *which* link a collective rides.  This module owns that:
it can tell whether a mesh axis crosses slice boundaries (DCN), validates
latency-sensitive patterns (the ring) against it, and orders hierarchical
reductions ICI-first so the narrow DCN hop moves pre-reduced data.
"""

from __future__ import annotations

import os
from typing import Any, Sequence, Tuple


# -- mesh topology introspection (host-side, outside jit) --------------------

def device_slice_id(device: Any) -> int:
    """Which TPU slice a device belongs to.

    On TPU this is the PJRT ``slice_index``.  On platforms with no slice
    notion (CPU test meshes), ``TRAININGJOB_VIRTUAL_DEVICES_PER_SLICE=k``
    assigns ``device.id // k`` -- the virtual-multislice geometry used by the
    dryrun/tests to exercise the DCN-aware paths (hierarchical reduce, ICI
    validation) end-to-end on a forced-host-device mesh, with real device
    objects rather than mocks."""
    sid = getattr(device, "slice_index", None)
    if sid is not None:
        return int(sid)
    from trainingjob_operator_tpu.api import constants

    per = os.environ.get(constants.VIRTUAL_DEVICES_PER_SLICE_ENV, "")
    if per and per.isdigit() and int(per) > 0:
        return int(getattr(device, "id", 0)) // int(per)
    return 0


def axis_crosses_dcn(mesh: Any, axis: str) -> bool:
    """True iff moving along ``axis`` (holding the others fixed) ever crosses
    a slice boundary -- i.e. collectives on this axis ride DCN."""
    import numpy as np

    if axis not in mesh.axis_names:
        raise ValueError(f"axis {axis!r} not in mesh {mesh.axis_names}")
    idx = mesh.axis_names.index(axis)
    devs = np.asarray(mesh.devices)
    moved = np.moveaxis(devs, idx, 0)
    columns = moved.reshape(moved.shape[0], -1)
    for col in columns.T:
        ids = {device_slice_id(d) for d in col}
        if len(ids) > 1:
            return True
    return False


def require_axis(mesh: Any, axis: str) -> int:
    """Validate ``axis`` exists on ``mesh``; return its size."""
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh has axes {mesh.axis_names}, no {axis!r}; build the mesh "
            f"with MeshSpec.of(..., {axis}=n) (parallel/mesh.py)")
    return int(mesh.shape[axis])


def require_ici_axis(mesh: Any, axis: str) -> int:
    """Validate ``axis`` exists AND stays inside a slice (ICI).  Ring
    attention and per-layer fsdp gathers are latency/bandwidth-bound; letting
    them silently ride DCN is the classic multislice perf bug."""
    size = require_axis(mesh, axis)
    if axis_crosses_dcn(mesh, axis):
        raise ValueError(
            f"mesh axis {axis!r} crosses slice boundaries (DCN); ring/"
            f"per-layer collectives must ride ICI -- put the DCN hop on the "
            f"leading dp axis instead (parallel/mesh.py axis convention)")
    return size


# -- in-shard_map collectives ------------------------------------------------

def psum(x: Any, axis: str):
    import jax

    return jax.lax.psum(x, axis)


def pmean(x: Any, axis: str):
    import jax

    return jax.lax.pmean(x, axis)


def axis_index(axis: str):
    import jax

    return jax.lax.axis_index(axis)


def axis_size(axis: str):
    """Size of a named axis from inside shard_map (compile-time constant)."""
    import jax

    try:
        return jax.lax.axis_size(axis)
    except (AttributeError, TypeError):  # older jax
        return jax.lax.psum(1, axis)


def ring_permutation(axis_size: int,
                     reverse: bool = False) -> Tuple[Tuple[int, int], ...]:
    """Source->destination pairs rotating one hop around the ring.  On TPU a
    ring permutation maps onto neighbor ICI links, so each hop is
    contention-free and overlaps with compute."""
    if reverse:
        return tuple((i, (i - 1) % axis_size) for i in range(axis_size))
    return tuple((i, (i + 1) % axis_size) for i in range(axis_size))


def ppermute_next(x: Any, axis: str, axis_size: int):
    """Rotate a block one step around the ring (shard i -> i+1)."""
    import jax

    return jax.lax.ppermute(x, axis, ring_permutation(axis_size))


def ppermute_prev(x: Any, axis: str, axis_size: int):
    """Rotate one step the other way (shard i -> i-1); a bidirectional ring
    halves the hop count for non-causal exchanges."""
    import jax

    return jax.lax.ppermute(x, axis, ring_permutation(axis_size,
                                                      reverse=True))


def hierarchical_psum(x: Any, mesh: Any, axes: Sequence[str]):
    """All-reduce over several mesh axes, ICI axes first.

    Reducing intra-slice before the DCN hop means the slow link carries data
    already reduced by the ICI axes' width -- the standard two-stage
    multislice all-reduce.  With a single axis (or all-ICI axes) this is just
    psum; call inside shard_map.
    """
    import jax

    ordered = sorted(axes, key=lambda a: axis_crosses_dcn(mesh, a))
    for axis in ordered:
        x = jax.lax.psum(x, axis)
    return x


def all_gather(x: Any, axis: str, *, tiled: bool = True):
    import jax

    return jax.lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dimension: int = 0):
    import jax

    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                                tiled=True)
