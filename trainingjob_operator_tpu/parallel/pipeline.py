"""Pipeline parallelism over the ``pp`` mesh axis: GPipe on GSPMD terms.

The reference has no parallelism strategies of its own (SURVEY.md §0 -- the
operator provisions pods; the in-container framework decides).  The TPU
build owns the workload layer, so pipeline parallelism is implemented here
the XLA-native way, as pure GSPMD (no shard_map, no manual collectives):

- The layer stack (already stacked [L, ...] for ``lax.scan``) is reshaped to
  [S, L/S, ...] -- a real STAGE array dimension, sharded on ``pp`` via
  ``with_sharding_constraint``.  Each pp shard owns one stage's contiguous
  layer block.
- The in-flight activations are one array [S, mb, ...], stage dim sharded on
  ``pp``.  Each tick ``jax.vmap``s the stage body over the stage dim (every
  stage's compute lands on its own pp shard), then ``jnp.roll``s the state
  one slot along the stage dim -- which GSPMD lowers to a collective-permute
  on the ``pp`` axis, the stage hand-off.
- A static ``lax.scan`` over ``M + S - 1`` ticks implements the GPipe
  schedule; the bubble (S - 1 idle ticks) amortizes with microbatch count M.

Because everything is ordinary sharded XLA, the stage body composes with
dp/fsdp/tp exactly like the dense path -- GSPMD partitions the microbatch
over the data axes and the per-stage weights over fsdp/tp with the same
rules as unpipelined layers.  (An earlier shard_map-manual-over-pp
formulation tripped an XLA partitioner check-failure when stage weights
were also fsdp/tp-sharded; the GSPMD form avoids manual/auto mixing
entirely.)  Attention inside the stage body still takes the pure-XLA path:
a Pallas custom call is opaque to GSPMD's vmapped-stage partitioning.

DCN note: stage hand-offs are point-to-point and once per tick, so ``pp``
is the one compute axis besides ``dp`` that tolerates crossing slices
(scaling-book layout: dp/pp on DCN, fsdp/tp/sp/ep on ICI).

Everything is static-shape and differentiable (scan + roll + one-hot
selects), so ``jax.grad`` through the pipeline just works.
"""

from __future__ import annotations

from typing import Any, Callable


def gpipe(block_fn: Callable, stacked_layers: Any, h, mesh,
          n_microbatches: int, axis: str = "pp"):
    """Apply a stacked layer pytree to ``h`` [B, ...] as a ``pp``-stage
    pipeline; numerically equivalent to scanning ``block_fn`` over the
    stacked layers.

    ``block_fn(h, layer) -> h`` applies ONE layer.  ``stacked_layers``
    leaves have leading dim L (divisible by the pp size); ``h``'s leading
    batch dim must be divisible by ``n_microbatches``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from trainingjob_operator_tpu.parallel.collectives import require_axis

    S = require_axis(mesh, axis)
    if S == 1:
        def one(hh, layer):
            return block_fn(hh, layer), None

        return jax.lax.scan(one, h, stacked_layers)[0]

    L = int(jax.tree.leaves(stacked_layers)[0].shape[0])
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by pp={S}")
    M = n_microbatches
    B = h.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches={M}")
    mb = B // M

    def pp_spec(ndim: int) -> P:
        # Pin ONLY the stage dim; every other dim stays UNCONSTRAINED so
        # GSPMD keeps the weights' fsdp/tp sharding and the activations'
        # dp batch sharding.  (A short PartitionSpec would mark the
        # remaining dims REPLICATED -- silently erasing FSDP and
        # duplicating dp compute.)
        return P(axis, *([P.UNCONSTRAINED] * (ndim - 1)))

    def stage_shard(x):
        # [L, ...] -> [S, L/S, ...], stage dim on pp.
        y = x.reshape(S, L // S, *x.shape[1:])
        return jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, pp_spec(y.ndim)))

    layers_staged = jax.tree.map(stage_shard, stacked_layers)

    def stage_apply(stage_layers, hh):
        def one(acc, layer):
            return block_fn(acc, layer), None

        return jax.lax.scan(one, hh, stage_layers)[0]

    def pin(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, pp_spec(x.ndim)))

    x_mb = h.reshape(M, mb, *h.shape[1:])

    def tick(carry, t):
        state, outs = carry
        # Inject microbatch t into stage slot 0 (clamped reads past M feed
        # garbage that is never stored).
        t_in = jnp.clip(t, 0, M - 1)
        inj = jax.lax.dynamic_index_in_dim(x_mb, t_in, 0, keepdims=False)
        state = state.at[0].set(jnp.where(t < M, inj, state[0]))
        # Every stage advances its resident microbatch by one stage block;
        # vmap over the stage dim keeps each stage's compute on its shard.
        state = jax.vmap(stage_apply)(layers_staged, state)
        state = pin(state)
        # Stage S-1 just finished microbatch t - (S - 1).
        t_out = t - (S - 1)
        valid = jnp.logical_and(t_out >= 0, t_out < M)
        stored = jax.lax.dynamic_update_index_in_dim(
            outs, state[-1], jnp.clip(t_out, 0, M - 1), 0)
        outs = jnp.where(valid, stored, outs)
        # Hand off: stage s's output becomes stage s+1's input.  A roll
        # along a pp-sharded dim lowers to a collective-permute on pp.
        state = jnp.roll(state, 1, axis=0)
        state = pin(state)
        return (state, outs), None

    state0 = pin(jnp.zeros((S, mb, *h.shape[1:]), h.dtype))
    outs0 = jnp.zeros_like(x_mb)
    (_, outs), _ = jax.lax.scan(tick, (state0, outs0),
                                jnp.arange(M + S - 1))
    return outs.reshape(B, *h.shape[1:])
