"""Pipeline parallelism over the ``pp`` mesh axis: GPipe on GSPMD terms.

The reference has no parallelism strategies of its own (SURVEY.md §0 -- the
operator provisions pods; the in-container framework decides).  The TPU
build owns the workload layer, so pipeline parallelism is implemented here
the XLA-native way, as pure GSPMD (no shard_map, no manual collectives):

- The layer stack (already stacked [L, ...] for ``lax.scan``) is reshaped to
  [S, L/S, ...] -- a real STAGE array dimension, sharded on ``pp`` via
  ``with_sharding_constraint``.  Each pp shard owns one stage's contiguous
  layer block.
- The in-flight activations are one array [S, mb, ...], stage dim sharded on
  ``pp``.  Each tick ``jax.vmap``s the stage body over the stage dim (every
  stage's compute lands on its own pp shard), then ``jnp.roll``s the state
  one slot along the stage dim -- which GSPMD lowers to a collective-permute
  on the ``pp`` axis, the stage hand-off.
- A static ``lax.scan`` over ``M + S - 1`` ticks implements the GPipe
  schedule; the bubble (S - 1 idle ticks) amortizes with microbatch count M.

Because everything is ordinary sharded XLA, the stage body composes with
dp/fsdp/tp exactly like the dense path -- GSPMD partitions the microbatch
over the data axes and the per-stage weights over fsdp/tp with the same
rules as unpipelined layers.

The per-tick stage advance runs under a PARTIAL-MANUAL ``shard_map``:
manual over ONLY ``pp`` (each shard sees its local stage, stage dim 1),
auto over everything else -- dp/fsdp/tp einsums inside the body are still
partitioned by GSPMD exactly like the dense path.  This is what lets the
Pallas flash-attention kernel run inside the pipeline: the stage body can
nest a second partial-manual shard_map over the data/tp axes
(ops/flash_attention.py ``flash_attention_pp``) so the custom call --
which GSPMD cannot partition -- executes per-shard.  (A full-manual
formulation tripped an XLA partitioner check-failure when stage weights
were also fsdp/tp-sharded; a pure vmap-over-the-stage-dim GSPMD
formulation worked but forced XLA attention, since the vmapped custom
call is opaque to the pp partitioning.  Partial-manual keeps both.)
Runtimes without partial-manual shard_map (``jax.shard_map`` lacking
``axis_names``) fall back to the vmap formulation + XLA attention.

GPipe bubble: stage S-1 idles the first S-1 ticks and stage 0 the last
S-1, so the idle fraction is (S-1)/(M+S-1) with M microbatches
(``bubble_fraction``).  Callers amortize it by raising M; models/llama.py
defaults to M ~ 8*(S-1) (bubble ~= 11%) bounded by the batch.

DCN note: stage hand-offs are point-to-point and once per tick, so ``pp``
is the one compute axis besides ``dp`` that tolerates crossing slices
(scaling-book layout: dp/pp on DCN, fsdp/tp/sp/ep on ICI).

Everything is static-shape and differentiable (scan + roll + one-hot
selects), so ``jax.grad`` through the pipeline just works.
"""

from __future__ import annotations

import functools
from typing import Any, Callable


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Idle fraction of the GPipe schedule: (S-1)/(M+S-1)."""
    return (n_stages - 1) / (n_microbatches + n_stages - 1)


@functools.cache
def partial_manual_shard_map():
    """``jax.shard_map`` with partial-manual mode (``axis_names=``), or None.

    Partial-manual (manual over a SUBSET of mesh axes, auto over the rest)
    landed in jax 0.8+; on older runtimes gpipe falls back to the pure-GSPMD
    vmap formulation (correct, but the stage body cannot host Pallas calls).

    The replication-check kwarg was renamed ``check_rep`` -> ``check_vma``
    across jax versions, so the same ``inspect.signature`` probe that gates
    on ``axis_names`` also decides how to spell it: callers always pass
    ``check_vma=`` and the returned wrapper translates (or drops) it, so a
    version skew downgrades to the documented fallback instead of surfacing
    as a trace-time TypeError.
    """
    try:
        import inspect

        from jax import shard_map
    except ImportError:
        return None
    params = inspect.signature(shard_map).parameters
    if "axis_names" not in params:
        return None
    return _adapt_check_kwarg(shard_map, params)


def _adapt_check_kwarg(shard_map, params):
    """Wrap ``shard_map`` so callers can always spell ``check_vma=``."""
    if "check_vma" in params:
        return shard_map

    @functools.wraps(shard_map)
    def compat(*args, **kwargs):
        if "check_vma" in kwargs:
            val = kwargs.pop("check_vma")
            if "check_rep" in params:
                kwargs["check_rep"] = val
        return shard_map(*args, **kwargs)

    return compat


def gpipe(block_fn: Callable, stacked_layers: Any, h, mesh,
          n_microbatches: int, axis: str = "pp"):
    """Apply a stacked layer pytree to ``h`` [B, ...] as a ``pp``-stage
    pipeline; numerically equivalent to scanning ``block_fn`` over the
    stacked layers.

    ``block_fn(h, layer) -> h`` applies ONE layer.  ``stacked_layers``
    leaves have leading dim L (divisible by the pp size); ``h``'s leading
    batch dim must be divisible by ``n_microbatches``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from trainingjob_operator_tpu.parallel.collectives import require_axis

    S = require_axis(mesh, axis)
    if S == 1:
        def one(hh, layer):
            return block_fn(hh, layer), None

        return jax.lax.scan(one, h, stacked_layers)[0]

    L = int(jax.tree.leaves(stacked_layers)[0].shape[0])
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by pp={S}")
    M = n_microbatches
    B = h.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches={M}")
    mb = B // M

    def pp_spec(ndim: int) -> P:
        # Pin ONLY the stage dim; every other dim stays UNCONSTRAINED so
        # GSPMD keeps the weights' fsdp/tp sharding and the activations'
        # dp batch sharding.  (A short PartitionSpec would mark the
        # remaining dims REPLICATED -- silently erasing FSDP and
        # duplicating dp compute.)
        return P(axis, *([P.UNCONSTRAINED] * (ndim - 1)))

    def stage_shard(x):
        # [L, ...] -> [S, L/S, ...], stage dim on pp.
        y = x.reshape(S, L // S, *x.shape[1:])
        return jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, pp_spec(y.ndim)))

    layers_staged = jax.tree.map(stage_shard, stacked_layers)

    def stage_apply(stage_layers, hh):
        def one(acc, layer):
            return block_fn(acc, layer), None

        return jax.lax.scan(one, hh, stage_layers)[0]

    shmap = partial_manual_shard_map()
    if shmap is not None:
        # Partial-manual advance: manual over ONLY pp (local stage dim 1),
        # auto over dp/fsdp/tp -- GSPMD partitions the body's einsums as
        # usual, and the body may nest Pallas kernels under a second
        # partial-manual shard_map (flash_attention_pp).  P(axis) pins just
        # the leading (stage) dim; unmentioned dims stay auto.
        def advance(layers_staged, state):
            def body(local_layers, local_state):
                hh = stage_apply(
                    jax.tree.map(lambda x: x[0], local_layers),
                    local_state[0])
                return hh[None]

            return shmap(body, mesh=mesh,
                         in_specs=(jax.tree.map(lambda _: P(axis),
                                                layers_staged), P(axis)),
                         out_specs=P(axis),
                         axis_names=frozenset({axis}),
                         check_vma=False)(layers_staged, state)
    else:
        def advance(layers_staged, state):
            # Every stage advances its resident microbatch; vmap over the
            # stage dim keeps each stage's compute on its pp shard.
            return jax.vmap(stage_apply)(layers_staged, state)

    data_axes = tuple(a for a in ("dp", "fsdp")
                      if a in mesh.axis_names and mesh.shape[a] > 1)
    batch_axes = (data_axes if len(data_axes) > 1
                  else (data_axes[0] if data_axes else None))

    def pin(x):
        # State arrays are [S, mb, ...]: pin the stage dim to pp AND the
        # microbatch dim to the data axes.  Leaving mb unconstrained lets
        # GSPMD pick clashing layouts for the state's producer vs the
        # stage body (observed: an involuntary full rematerialization of
        # the [S, mb, T, D] carry at the scan boundary).
        spec = P(axis, batch_axes, *([P.UNCONSTRAINED] * (x.ndim - 2)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))

    x_mb = h.reshape(M, mb, *h.shape[1:])

    def tick(carry, t):
        state, outs = carry
        # Inject microbatch t into stage slot 0 (clamped reads past M feed
        # garbage that is never stored).
        t_in = jnp.clip(t, 0, M - 1)
        inj = jax.lax.dynamic_index_in_dim(x_mb, t_in, 0, keepdims=False)
        state = state.at[0].set(jnp.where(t < M, inj, state[0]))
        # Every stage advances its resident microbatch by one stage block.
        state = advance(layers_staged, state)
        state = pin(state)
        # Stage S-1 just finished microbatch t - (S - 1).
        t_out = t - (S - 1)
        valid = jnp.logical_and(t_out >= 0, t_out < M)
        stored = jax.lax.dynamic_update_index_in_dim(
            outs, state[-1], jnp.clip(t_out, 0, M - 1), 0)
        outs = jnp.where(valid, stored, outs)
        # Hand off: stage s's output becomes stage s+1's input.  A roll
        # along a pp-sharded dim lowers to a collective-permute on pp.
        state = jnp.roll(state, 1, axis=0)
        state = pin(state)
        return (state, outs), None

    state0 = pin(jnp.zeros((S, mb, *h.shape[1:]), h.dtype))
    outs0 = jnp.zeros_like(x_mb)
    (_, outs), _ = jax.lax.scan(tick, (state0, outs0),
                                jnp.arange(M + S - 1))
    return outs.reshape(B, *h.shape[1:])
