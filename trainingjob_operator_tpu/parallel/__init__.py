"""Mesh/sharding/collective layer: how workloads scale on TPU.

The reference has no in-repo parallelism (SURVEY.md §2.7) -- its replicas
self-assemble via env and bring their own collectives.  TPU-native, the
equivalent layer is explicit: a ``jax.sharding.Mesh`` over the slice topology
the operator provisioned, parameter/batch shardings expressed as
``PartitionSpec`` rules, XLA-inserted collectives over ICI/DCN, and
sequence-parallel ring attention for long context.
"""

from trainingjob_operator_tpu.parallel.mesh import (
    MeshSpec,
    make_mesh,
    mesh_from_rendezvous,
)
from trainingjob_operator_tpu.parallel.sharding import (
    batch_spec,
    shard_pytree,
    spec_for_path,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "mesh_from_rendezvous",
    "batch_spec",
    "shard_pytree",
    "spec_for_path",
]
