"""tpu-trainingjob: a TPU-native elastic training-job framework.

Built from scratch with the capabilities of
``elasticdeeplearning/trainingjob-operator`` (reference layout: ``cmd/``,
``pkg/apis/aitrainingjob``, ``pkg/controller``, ``pkg/client``, ``pkg/signals``),
re-designed TPU-first:

- ``api``        -- the ``TPUTrainingJob`` resource model (reference: pkg/apis/).
- ``core``       -- the minimal pod/service/node object model the control plane
                    reconciles over (reference: k8s.io/api/core/v1 subset).
- ``client``     -- object tracker, typed clients, informers, listers, workqueue,
                    expectations (reference: pkg/client/ + client-go machinery).
- ``controller`` -- the reconcile engine / fault-tolerance state machine
                    (reference: pkg/controller/).
- ``runtime``    -- cluster backends: in-memory sim, local subprocess, gated k8s.
- ``workloads``  -- JAX/XLA training entrypoints exercised by the operator.
- ``parallel``   -- mesh/sharding/collective layer (dp/fsdp/tp/sp, ring attention).
- ``ops``        -- TPU kernels (Pallas) with XLA fallbacks.
"""

__version__ = "0.1.0"
