"""ResNet-50 -- BASELINE config 3 (JAX/Flax-class ResNet, data-parallel
v5e-8).

Plain-JAX pytree implementation: convs via ``lax.conv_general_dilated`` in
NHWC (TPU-native layout; the MXU consumes convs as implicit GEMMs), batch norm
with running stats carried in a separate state tree, bottleneck blocks
[3, 4, 6, 3].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    width: int = 64
    dtype: str = "bfloat16"

    @classmethod
    def resnet50(cls) -> "ResNetConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ResNetConfig":
        return cls(num_classes=10, stage_sizes=(1, 1), width=8)


#: DP sharding: params replicated (pure data parallel); batch sharded.
SHARDING_RULES = [(r".*", ())]


def _conv_init(key, kh, kw, cin, cout):
    import jax
    import jax.numpy as jnp

    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * (
        (2.0 / fan_in) ** 0.5)


def _bn_init(c):
    import jax.numpy as jnp

    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _bn_state(c):
    import jax.numpy as jnp

    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def init_params(config: ResNetConfig, key) -> Tuple[Dict, Dict]:
    """Returns (params, batch_stats)."""
    import jax

    c = config
    keys = iter(jax.random.split(key, 200))
    params: Dict[str, Any] = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, c.width),
                                       "bn": _bn_init(c.width)}}
    stats: Dict[str, Any] = {"stem": _bn_state(c.width)}

    cin = c.width
    for s, blocks in enumerate(c.stage_sizes):
        cout = c.width * (2 ** s)
        stage_p, stage_s = [], []
        for b in range(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            p = {
                "conv1": _conv_init(next(keys), 1, 1, cin, cout),
                "bn1": _bn_init(cout),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout),
                "bn2": _bn_init(cout),
                "conv3": _conv_init(next(keys), 1, 1, cout, cout * 4),
                "bn3": _bn_init(cout * 4),
            }
            st = {"bn1": _bn_state(cout), "bn2": _bn_state(cout),
                  "bn3": _bn_state(cout * 4)}
            if b == 0:
                p["proj"] = _conv_init(next(keys), 1, 1, cin, cout * 4)
                p["proj_bn"] = _bn_init(cout * 4)
                st["proj_bn"] = _bn_state(cout * 4)
            stage_p.append(p)
            stage_s.append(st)
            cin = cout * 4
        params[f"stage{s}"] = stage_p
        stats[f"stage{s}"] = stage_s

    import jax.numpy as jnp

    params["head"] = {"w": jax.random.normal(next(keys), (cin, c.num_classes),
                                             jnp.float32) * 0.01,
                      "b": jnp.zeros((c.num_classes,), jnp.float32)}
    return params, stats


def _conv(x, w, stride=1):
    import jax

    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, s, train: bool, momentum=0.9, eps=1e-5):
    """Returns (y, new_state)."""
    import jax.numpy as jnp

    if train:
        mean = jnp.mean(x.astype(jnp.float32), axis=(0, 1, 2))
        var = jnp.var(x.astype(jnp.float32), axis=(0, 1, 2))
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jnp.reciprocal(jnp.sqrt(var + eps))
    y = (x.astype(jnp.float32) - mean) * inv * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_s


def forward(params, stats, images, config: ResNetConfig, train: bool = True):
    """images [B, H, W, 3] -> (logits [B, classes], new_stats)."""
    import jax
    import jax.numpy as jnp

    x = images.astype(jnp.dtype(config.dtype))
    new_stats: Dict[str, Any] = {}

    x = _conv(x, params["stem"]["conv"], stride=2)
    x, new_stats["stem"] = _bn(x, params["stem"]["bn"], stats["stem"], train)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")

    for s in range(len(config.stage_sizes)):
        stage_stats = []
        for b, p in enumerate(params[f"stage{s}"]):
            st = stats[f"stage{s}"][b]
            stride = 2 if (s > 0 and b == 0) else 1
            residual = x
            y = _conv(x, p["conv1"])
            y, st1 = _bn(y, p["bn1"], st["bn1"], train)
            y = jax.nn.relu(y)
            y = _conv(y, p["conv2"], stride=stride)
            y, st2 = _bn(y, p["bn2"], st["bn2"], train)
            y = jax.nn.relu(y)
            y = _conv(y, p["conv3"])
            y, st3 = _bn(y, p["bn3"], st["bn3"], train)
            new_st = {"bn1": st1, "bn2": st2, "bn3": st3}
            if "proj" in p:
                residual = _conv(x, p["proj"], stride=stride)
                residual, stp = _bn(residual, p["proj_bn"], st["proj_bn"], train)
                new_st["proj_bn"] = stp
            x = jax.nn.relu(y + residual)
            stage_stats.append(new_st)
        new_stats[f"stage{s}"] = stage_stats

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_stats


def loss_fn(params, stats, batch, config: ResNetConfig):
    import optax

    logits, new_stats = forward(params, stats, batch["images"], config,
                                train=True)
    loss = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["labels"]).mean()
    return loss, new_stats
