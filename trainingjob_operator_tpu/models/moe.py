"""Mixtral-style sparse-MoE decoder, TPU-first expert parallelism.

Same idiom as models/llama.py (plain pytree, stacked layers under
``lax.scan``), with the dense MLP replaced by a top-k routed mixture of
experts.  The reference operator has no model layer at all (it delegates to
in-container frameworks, SURVEY.md §2.7); this module exists because the TPU
build owns the workload layer, and MoE is the model family that exercises the
``ep`` mesh axis (parallel/mesh.py AXIS_ORDER) end-to-end.

TPU mapping:
- Routing is the GShard/Switch dense-dispatch formulation: static-shape
  one-hot dispatch/combine tensors and einsums, NO dynamic gather/scatter --
  data-dependent shapes would break XLA tiling; the MXU sees batched matmuls.
- Expert weights carry a leading expert dim sharded on ``ep``
  (SHARDING_RULES); the dispatch einsum's [tokens x experts] contraction is
  where GSPMD inserts the all-to-all when ep > 1.
- Expert capacity bounds per-expert work (static): tokens over capacity are
  dropped (their combine weight is zero), the standard trade for fixed
  shapes.  The auxiliary load-balancing loss keeps the router near-uniform
  so drops stay rare.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

from trainingjob_operator_tpu.models import llama as _llama


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    #: Sliding-window attention span (Mixtral uses 4096); 0 = full causal.
    sliding_window: int = 0
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"

    @classmethod
    def mixtral_8x7b(cls) -> "MoEConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab_size: int = 256, dim: int = 64, n_layers: int = 2,
             n_heads: int = 4, n_kv_heads: int = 2, ffn_dim: int = 128,
             n_experts: int = 4, experts_per_token: int = 2) -> "MoEConfig":
        return cls(vocab_size=vocab_size, dim=dim, n_layers=n_layers,
                   n_heads=n_heads, n_kv_heads=n_kv_heads, ffn_dim=ffn_dim,
                   n_experts=n_experts, experts_per_token=experts_per_token,
                   max_seq_len=128)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


#: Expert dim rides ``ep``; within an expert the matmul dims keep the
#: Megatron fsdp/tp layout.  Attention/embedding rules match llama's.
SHARDING_RULES = [
    # Vocab over tp x fsdp, D replicated (models/llama.py sharding_rules:
    # a D-sharded table forces an involuntary full remat of every lookup).
    (r"tok_embed", (("tp", "fsdp"), None)),
    (r"lm_head", ("fsdp", "tp")),
    (r"attn/w[qkv]$", (None, "fsdp", "tp")),
    (r"attn/wo$", (None, "tp", "fsdp")),
    (r"moe/router$", (None, "fsdp", None)),
    (r"moe/w_(gate|up)$", (None, "ep", "fsdp", "tp")),
    (r"moe/w_down$", (None, "ep", "tp", "fsdp")),
    (r"norm", (None,)),
]


def init_params(config: MoEConfig, key) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    c = config
    k_emb, k_layers, k_head = jax.random.split(key, 3)

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else shape[-2] ** -0.5
        return jax.random.normal(k, shape, jnp.float32) * scale

    kv_dim = c.n_kv_heads * c.head_dim
    keys = jax.random.split(k_layers, 8)

    def stacked(k, shape, scale=None):
        return dense(k, (c.n_layers,) + shape, scale)

    return {
        "tok_embed": dense(k_emb, (c.vocab_size, c.dim), 0.02),
        "layers": {
            "attn": {
                "wq": stacked(keys[0], (c.dim, c.dim)),
                "wk": stacked(keys[1], (c.dim, kv_dim)),
                "wv": stacked(keys[2], (c.dim, kv_dim)),
                "wo": stacked(keys[3], (c.dim, c.dim)),
            },
            "moe": {
                "router": stacked(keys[4], (c.dim, c.n_experts)),
                "w_gate": stacked(keys[5], (c.n_experts, c.dim, c.ffn_dim)),
                "w_up": stacked(keys[6], (c.n_experts, c.dim, c.ffn_dim)),
                "w_down": stacked(keys[7], (c.n_experts, c.ffn_dim, c.dim)),
            },
            "attn_norm": jnp.ones((c.n_layers, c.dim), jnp.float32),
            "moe_norm": jnp.ones((c.n_layers, c.dim), jnp.float32),
        },
        "final_norm": jnp.ones((c.dim,), jnp.float32),
        "lm_head": dense(k_head, (c.dim, c.vocab_size), 0.02),
    }


def expert_capacity(config: MoEConfig, seq_len: int) -> int:
    """Static per-expert token budget for one [T] row."""
    c = config
    cap = int(c.capacity_factor * c.experts_per_token * seq_len
              / c.n_experts)
    return max(cap, 1)


def _dispatch_combine(probs, k: int, capacity: int):
    """GShard-style routing tensors from router probabilities.

    probs: [B, T, E] float32.  Returns (dispatch [B,T,E,C] bool-ish,
    combine [B,T,E,C] float32): ``combine`` carries the renormalized top-k
    gate for each (token, expert, capacity-slot) assignment, ``dispatch`` its
    0/1 mask.  Assignment order is choice-rank-major then token-major, so
    when an expert overflows its capacity the lowest-priority tokens drop
    (combine weight 0) -- static shapes, no data-dependent control flow.
    """
    import jax
    import jax.numpy as jnp

    B, T, E = probs.shape
    gates, idx = jax.lax.top_k(probs, k)                   # [B,T,k]
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((B, T, E, capacity), probs.dtype)
    combine = jnp.zeros((B, T, E, capacity), probs.dtype)
    used = jnp.zeros((B, E), probs.dtype)                  # slots taken
    for choice in range(k):
        onehot = jax.nn.one_hot(idx[:, :, choice], E,
                                dtype=probs.dtype)         # [B,T,E]
        # Position of each token within its chosen expert's capacity:
        # tokens already assigned by earlier choices + earlier tokens of
        # this choice.
        pos = (jnp.cumsum(onehot, axis=1) - onehot
               + used[:, None, :]) * onehot                # [B,T,E]
        within = (pos < capacity) * onehot
        slot = jax.nn.one_hot(pos.sum(-1).astype(jnp.int32), capacity,
                              dtype=probs.dtype)           # [B,T,C]
        assign = within[..., None] * slot[:, :, None, :]   # [B,T,E,C]
        dispatch = dispatch + assign
        combine = combine + assign * gates[:, :, choice, None, None]
        used = used + within.sum(axis=1)
    return dispatch, combine


def _moe_mlp(h, layer, config: MoEConfig, compute):
    """Routed expert MLP for h [B, T, D] -> ([B, T, D], aux_loss).

    Routing is WHOLE-sequence by design.  A GShard-style ``router_group``
    knob (fold T into the batch dim, route in g-token groups to bound
    capacity and make dispatch linear in T) was tried and measured at
    0.994x the whole-sequence step time at bench shapes, T <= 2048
    (BENCH_r05 ``group_speedup``): XLA fuses the dense dispatch/combine
    einsums well enough that the asymptotic win never materialized, while
    per-group capacity drops tokens a whole-sequence budget would have
    kept.  Decode never uses grouping at all (``moe_decode`` routes per
    token, dropless), so the knob was a measured no-op and was removed
    (docs/MIGRATION.md).  Revisit only with T >> 2048 training sequences.
    """
    import jax
    import jax.numpy as jnp

    c = config
    B, T, D = h.shape
    cap = expert_capacity(c, T)

    # Router in float32: tiny matmul, and routing decisions are precision-
    # sensitive (bf16 ties reorder top_k).
    logits = h.astype(jnp.float32) @ layer["moe"]["router"]  # [B,T,E]
    probs = jax.nn.softmax(logits, axis=-1)
    dispatch, combine = _dispatch_combine(probs, c.experts_per_token, cap)

    # Switch-transformer load-balancing auxiliary loss: E * sum_e
    # (fraction of tokens routed to e) * (mean router prob of e).
    frac = dispatch.sum(axis=(1, 3)) / max(
        T * c.experts_per_token / c.n_experts, 1e-9) / c.n_experts  # [B,E]
    mean_prob = probs.mean(axis=1)                                  # [B,E]
    aux = (frac * mean_prob).sum(-1).mean() * c.n_experts

    # Dense dispatch: [B,T,D] x [B,T,E,C] -> [B,E,C,D]; the [E] dim is
    # ep-sharded, so this contraction is where the all-to-all lands.
    x_e = jnp.einsum("btd,btec->becd", h, dispatch.astype(compute))
    gate = jax.nn.silu(jnp.einsum(
        "becd,edf->becf", x_e, layer["moe"]["w_gate"].astype(compute)))
    up = jnp.einsum("becd,edf->becf", x_e,
                    layer["moe"]["w_up"].astype(compute))
    y_e = jnp.einsum("becf,efd->becd", gate * up,
                     layer["moe"]["w_down"].astype(compute))
    y = jnp.einsum("becd,btec->btd", y_e, combine.astype(compute))
    return y, aux.astype(jnp.float32)


def forward(params: Dict[str, Any], tokens, config: MoEConfig, *,
            mesh=None, remat=False, return_hidden: bool = False,
            return_kv: bool = False):
    """Logits [B, T, vocab] plus the mean auxiliary load-balancing loss.

    With ``return_hidden`` returns the final-norm hidden states [B, T, D]
    instead of logits (the chunked cross-entropy path; mirrors
    models/llama.py).  With ``return_kv`` returns ``(logits, aux, (k, v))``
    where k/v are post-rope per-layer projections stacked
    [L, B, T, Hkv, Dh] -- the decode prefill contract (models/moe_decode.py
    reuses THIS forward so sampling cannot desynchronize from training).
    """
    import jax
    import jax.numpy as jnp

    if return_hidden and return_kv:
        raise ValueError("return_hidden and return_kv are mutually "
                         "exclusive (the hidden path drops the kv stack)")
    c = config
    compute = jnp.dtype(c.dtype)
    B, T = tokens.shape
    h = params["tok_embed"].astype(compute)[tokens]
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

    # Same partitioner hygiene as the Llama family (measured there to
    # eliminate involuntary full rematerializations on many-axis meshes):
    # pre-cast matmul weights with sharding anchors and pin normed
    # activations + their cotangents to the batch sharding.  The router
    # stays f32 (routing decisions are precision-sensitive).
    layers = params["layers"]
    if mesh is not None:
        from trainingjob_operator_tpu.parallel.sharding import (
            pin_batch_act,
            precast_weights,
        )

        layers = precast_weights(layers, SHARDING_RULES, mesh, compute,
                                 r"attn/w|moe/w_(gate|up|down)")

        def pin_act(y):
            return pin_batch_act(y, mesh)
    else:
        def pin_act(y):
            return y
    # Pin the embedding output to the activation layout (the gather
    # inherits the table's (tp, fsdp) sharding; see models/llama.py).
    h = pin_act(h)

    def attn(h, layer):
        q = h @ layer["attn"]["wq"].astype(compute)
        k = h @ layer["attn"]["wk"].astype(compute)
        v = h @ layer["attn"]["wv"].astype(compute)
        q = q.reshape(B, T, c.n_heads, c.head_dim)
        k = k.reshape(B, T, c.n_kv_heads, c.head_dim)
        v = v.reshape(B, T, c.n_kv_heads, c.head_dim)
        q = _llama._rope(q, positions, c.rope_theta)
        k = _llama._rope(k, positions, c.rope_theta)
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.ops.flash_attention import (
            flash_attention_sharded)

        if mesh is not None and mesh.devices.size > 1:
            o = flash_attention_sharded(q, k, v, mesh, causal=True,
                                        window=c.sliding_window)
        else:
            o = flash_attention(q, k, v, causal=True,
                                window=c.sliding_window)
        # "attn" remat anchors are on the flash kernel's residuals
        # (ops/flash_attention.py _flash_fwd).
        o = o.reshape(B, T, c.dim) @ layer["attn"]["wo"].astype(compute)
        return o, (k, v)

    def block(carry, layer):
        h, aux = carry
        a, kv = attn(pin_act(_llama._rmsnorm(h, layer["attn_norm"],
                                             c.norm_eps)), layer)
        h = h + a
        y, layer_aux = _moe_mlp(
            pin_act(_llama._rmsnorm(h, layer["moe_norm"], c.norm_eps)),
            layer, c, compute)
        return (h + y, aux + layer_aux), (kv if return_kv else None)

    # Same policy surface as the Llama family (bool or "full"/"attn"/
    # "dots"/"none"; _remat_wrap docs the trade-offs).
    block = _llama._remat_wrap(block, remat)
    (h, aux), kv = jax.lax.scan(block, (h, jnp.float32(0.0)), layers)
    h = _llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    if return_hidden:
        return h, aux / c.n_layers
    logits = (h @ params["lm_head"].astype(compute)).astype(jnp.float32)
    if return_kv:
        # Post-rope per-layer K/V stacked [L, B, T, Hkv, Dh] -- the decode
        # cache layout (models/moe_decode.py prefill).
        return logits, aux / c.n_layers, kv
    return logits, aux / c.n_layers


def loss_fn(params, batch, config: MoEConfig, *, mesh=None,
            remat: bool = False, ce_chunk: int = 0):
    """Next-token cross-entropy + weighted load-balancing auxiliary.

    ``ce_chunk`` > 0 (dividing T) computes the head + CE in sequence chunks
    so the full [B, T, vocab] logits never materialize (llama's
    ``_chunked_ce``; exact, HBM-only change)."""
    import jax.numpy as jnp
    import optax

    tokens = batch["tokens"]
    T = tokens.shape[1] - 1
    if ce_chunk:
        if T % ce_chunk != 0:
            raise ValueError(f"ce_chunk={ce_chunk} does not divide seq {T}")
        h, aux = forward(params, tokens[:, :-1], config, mesh=mesh,
                         remat=remat, return_hidden=True)
        ce = _llama._chunked_ce(h, params["lm_head"], tokens[:, 1:],
                                ce_chunk, jnp.dtype(config.dtype))
        return ce + config.aux_loss_weight * aux
    logits, aux = forward(params, tokens[:, :-1], config, mesh=mesh,
                          remat=remat)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:]).mean()
    return ce + config.aux_loss_weight * aux


def num_params(config: MoEConfig) -> int:
    c = config
    kv_dim = c.n_kv_heads * c.head_dim
    per_layer = (c.dim * c.dim * 2 + c.dim * kv_dim * 2
                 + c.dim * c.n_experts
                 + c.n_experts * c.dim * c.ffn_dim * 3 + 2 * c.dim)
    return c.vocab_size * c.dim * 2 + c.n_layers * per_layer + c.dim


def active_params(config: MoEConfig) -> int:
    """Params touched per token (top-k of the experts): the FLOPs basis."""
    c = config
    kv_dim = c.n_kv_heads * c.head_dim
    per_layer = (c.dim * c.dim * 2 + c.dim * kv_dim * 2
                 + c.dim * c.n_experts
                 + c.experts_per_token * c.dim * c.ffn_dim * 3 + 2 * c.dim)
    return c.vocab_size * c.dim * 2 + c.n_layers * per_layer + c.dim
