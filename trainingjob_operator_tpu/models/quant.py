"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound (models/decode.py: each step streams every
weight for one token's worth of FLOPs), so halving weight bytes is the
single biggest decode-throughput lever on TPU.  This module quantizes the
matmul weights to int8 with float32 scales, symmetric, per OUTPUT channel
-- the scale axis is the one NOT reduced by the matmul, so dequantization
commutes with the contraction and XLA fuses the ``int8 -> bf16`` convert
and the scale multiply into the dot's operand read (the HBM read is int8).

Quantized leaves are ``{"q": int8, "s": f32}`` dicts; models/decode.py's
``_w`` resolves either form, so fp and quantized weights interoperate
leaf-by-leaf.  Embeddings quantize per ROW (the lookup gathers a row; its
scale rides along).  Norm scales stay f32 (tiny, precision-sensitive).

The reference operator has no serving stack at all (SURVEY.md §0); this
extends the framework's own decode path (models/decode.py).
"""

from __future__ import annotations

from typing import Any, Dict

#: Matmul-weight leaf names (quantize per output channel = axis -2 kept).
_MATMUL_LEAVES = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def int8_effective(batch: int) -> bool:
    """True when weight-only int8 is expected to pay for itself at this
    decode batch size.

    Historically gated at batch <= 4: the old path MATERIALIZED the
    dequantized weight (``dequantize``) before the dot, an O(in x out)
    convert+multiply whose cost is batch-invariant while the bandwidth win
    it buys shrinks with batch -- BENCH_r05 measured 1.28x at batch 1
    degrading to 0.88x at batch 8.  ``qmatmul`` removed that term: the dot
    contracts the int8 weight directly and the per-output-channel scale is
    applied AFTER the accumulate, an O(batch x out) epilogue.  The weight
    read stays int8 (the bandwidth win) at every batch, so the gate is
    now unconditional; the function survives as the single place callers
    ask the question (bench.py pins ``int8_speedup >= 1.0`` per batch)."""
    return batch >= 1


def _quantize_leaf(w, axis: int):
    """Symmetric int8 over ``axis`` (the reduction axis): q = round(w/s)."""
    import jax.numpy as jnp

    s = jnp.max(jnp.abs(w), axis=axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-12)
    q = jnp.clip(jnp.round(w / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def quantize_weights(params: Dict[str, Any]) -> Dict[str, Any]:
    """Llama param pytree -> same structure with matmul weights, lm_head
    and tok_embed as ``{"q": int8, "s": f32}``; norms untouched."""

    def walk(node, name=""):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if name in _MATMUL_LEAVES or name == "lm_head":
            # [..., in, out]: reduce over ``in`` (axis -2) at matmul time,
            # so the scale lives per output channel.
            return _quantize_leaf(node, axis=-2)
        if name == "tok_embed":
            # [vocab, D]: the lookup gathers a row; scale per row.
            return _quantize_leaf(node, axis=-1)
        return node

    return walk(params)


def dequantize(leaf, compute):
    """``{"q", "s"}`` (or a plain array) -> a ``compute``-dtype array.

    Materializes the FULL weight -- an O(in x out) convert whose cost does
    not amortize at larger decode batches (the BENCH_r05 batch-8
    regression).  Matmul call sites should use ``qmatmul`` instead; this
    survives for non-contraction uses (error metrics, tests)."""
    if isinstance(leaf, dict) and "q" in leaf:
        return (leaf["q"].astype(compute) * leaf["s"].astype(compute))
    return leaf.astype(compute)


def qmatmul(x, leaf, compute):
    """``x @ leaf`` with dequantization fused AFTER the accumulate.

    The per-OUTPUT-channel scale commutes with the contraction
    (``x @ (q * s) == (x @ q) * s`` when ``s`` is constant along the
    reduced axis), so the dot contracts the int8 weight directly -- the
    HBM read stays int8 at any batch -- and the scale multiply becomes an
    O(batch x out) epilogue instead of the O(in x out) weight
    materialization that made int8 REGRESS past batch 4 (BENCH_r05
    ``int8_speedup: 0.881``).  Plain (fp) leaves take the ordinary dot."""
    if isinstance(leaf, dict) and "q" in leaf:
        y = x @ leaf["q"].astype(compute)
        s = leaf["s"]
        # Scale is stored keepdims over the reduced axis ([..., 1, out]);
        # drop that axis so it broadcasts against y's [..., out].
        return y * s.reshape(s.shape[:-2] + s.shape[-1:]).astype(compute)
    return x @ leaf.astype(compute)


def dequantize_rows(leaf, idx, compute):
    """Row lookup for plain or row-quantized tables: gathers the int8 rows
    AND their per-row scales -- the full table is never dequantized (the
    embedding path's whole point)."""
    if isinstance(leaf, dict) and "q" in leaf:
        return leaf["q"][idx].astype(compute) * leaf["s"][idx].astype(compute)
    return leaf.astype(compute)[idx]


def quantization_error(params: Dict[str, Any]) -> Dict[str, float]:
    """Relative Frobenius error per quantized leaf (sanity metric)."""
    import jax.numpy as jnp

    qp = quantize_weights(params)
    out: Dict[str, float] = {}

    def walk(orig, quant, path=""):
        if isinstance(quant, dict) and "q" in quant:
            deq = dequantize(quant, jnp.float32)
            num = float(jnp.linalg.norm(orig.astype(jnp.float32) - deq))
            den = float(jnp.linalg.norm(orig.astype(jnp.float32))) or 1.0
            out[path] = num / den
            return
        if isinstance(orig, dict):
            for k in orig:
                walk(orig[k], quant[k], f"{path}/{k}" if path else k)

    walk(params, qp)
    return out
