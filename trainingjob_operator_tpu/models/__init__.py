"""Model families for the BASELINE configs, TPU-first.

- ``resnet``: ResNet-50 (config 3, v5e-8 data-parallel) -- conv/matmul work
  lands on the MXU; batch-norm folded into XLA fusions.
- ``bert``: BERT-base encoder (config 4, v5e-16 multi-host).
- ``llama``: Llama-2 decoder family (config 5, elastic pretrain) with
  dp/fsdp/tp/sp sharding rules and ring attention for long context.
- ``moe``: Mixtral-style sparse-MoE decoder exercising the ``ep`` mesh axis
  (GShard dense-dispatch routing: static shapes, einsum all-to-all).

All models are plain-JAX pytrees (init_fn/apply_fn pairs): explicit param
trees keep sharding rules trivially addressable by path
(parallel/sharding.py), and everything under jit is static-shape,
scan-friendly XLA.
"""
