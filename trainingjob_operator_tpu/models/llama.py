"""Llama-2 family: decoder-only transformer, TPU-first.

Plain-JAX pytree model (no framework Module graph): params are a nested dict
whose paths drive the sharding rules; the forward is jit/scan-friendly
(static shapes, ``lax.scan`` over layers via stacked params).

TPU mapping:
- matmuls in bf16 on the MXU; params kept f32 (master) unless configured.
- GQA attention; ring attention over the ``sp`` axis for long context
  (parallel/ringattention.py), plain attention otherwise.
- sharding rules (SHARDING_RULES): embeddings and lm_head tp-sharded on
  vocab, attention/MLP projections tp-sharded on heads/ffn, everything
  fsdp-sharded on the leading dim -- gradients reduce-scatter on ICI,
  params all-gather per layer (XLA inserts both).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    #: Sliding-window attention (Mistral-style): 0 = full causal; W > 0
    #: restricts row i to keys (i - W, i].  The flash kernels skip KV
    #: blocks outside the band (O(W) work per query); unsupported with
    #: sequence_parallel (ring attention is the full-context long path).
    sliding_window: int = 0
    dtype: str = "bfloat16"  # compute dtype; params stay float32

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def base_124m(cls) -> "LlamaConfig":
        """GPT2-small-scale config (~124M params): large enough that
        recovery is dominated by real restore/compile work (VERDICT r4 #4),
        small enough for CPU trials."""
        return cls(dim=768, n_layers=8, n_heads=12, n_kv_heads=12,
                   ffn_dim=3072, max_seq_len=2048)

    @classmethod
    def tiny(cls, vocab_size: int = 256, dim: int = 64, n_layers: int = 2,
             n_heads: int = 4, n_kv_heads: int = 2, ffn_dim: int = 128,
             max_seq_len: int = 128) -> "LlamaConfig":
        """Test/dryrun-sized config with the same code path."""
        return cls(vocab_size=vocab_size, dim=dim, n_layers=n_layers,
                   n_heads=n_heads, n_kv_heads=n_kv_heads, ffn_dim=ffn_dim,
                   max_seq_len=max_seq_len)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


#: path-pattern -> PartitionSpec args (parallel/sharding.py Rules).
#: fsdp shards the big dim; tp shards heads/ffn/vocab.
#: Megatron-style layout; stacked layer params carry a leading scan axis that
#: stays unsharded (None) by default, or rides ``pp`` under pipeline
#: parallelism (each stage owns its contiguous layer block).
def sharding_rules(pipeline: bool = False):
    lead = "pp" if pipeline else None
    return [
        # Embedding table: vocab over tp x fsdp, D REPLICATED.  Sharding D
        # makes every lookup inherit a D-sharded layout that the partitioner
        # can only reshard to the activation layout by replicate-then-
        # repartition (involuntary full remat; measured on the sp mesh 2 vs
        # 0).  Sharding the vocab dim over both axes keeps the same bytes
        # per device with a gather XLA partitions cleanly.
        (r"tok_embed", (("tp", "fsdp"), None)),
        (r"lm_head", ("fsdp", "tp")),
        (r"attn/w[qkv]$", (lead, "fsdp", "tp")),
        (r"attn/wo$", (lead, "tp", "fsdp")),
        (r"mlp/w_(gate|up)$", (lead, "fsdp", "tp")),
        (r"mlp/w_down$", (lead, "tp", "fsdp")),
        (r"layers/.*norm", (lead, None)),
        (r"norm", (None,)),
    ]


SHARDING_RULES = sharding_rules()


def init_params(config: LlamaConfig, key) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    c = config
    k_emb, k_layers, k_head = jax.random.split(key, 3)

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale)

    kv_dim = c.n_kv_heads * c.head_dim
    keys = jax.random.split(k_layers, 7)

    # Stacked layer params: leading axis = layer, consumed by lax.scan.
    def stacked(k, shape, scale=None):
        return dense(k, (c.n_layers,) + shape, scale)

    params = {
        "tok_embed": dense(k_emb, (c.vocab_size, c.dim), 0.02),
        "layers": {
            "attn": {
                "wq": stacked(keys[0], (c.dim, c.dim)),
                "wk": stacked(keys[1], (c.dim, kv_dim)),
                "wv": stacked(keys[2], (c.dim, kv_dim)),
                "wo": stacked(keys[3], (c.dim, c.dim)),
            },
            "mlp": {
                "w_gate": stacked(keys[4], (c.dim, c.ffn_dim)),
                "w_up": stacked(keys[5], (c.dim, c.ffn_dim)),
                "w_down": stacked(keys[6], (c.ffn_dim, c.dim)),
            },
            "attn_norm": jnp.ones((c.n_layers, c.dim), jnp.float32),
            "mlp_norm": jnp.ones((c.n_layers, c.dim), jnp.float32),
        },
        "final_norm": jnp.ones((c.dim,), jnp.float32),
        "lm_head": dense(k_head, (c.dim, c.vocab_size), 0.02),
    }
    return params


def choose_microbatches(batch: int, target: int, n_data: int,
                        n_stages: int, explicit: bool) -> int:
    """Pick the GPipe microbatch count M.

    M must divide ``batch``.  With an EXPLICIT request (``n_microbatches``
    arg or LLAMA_PP_MICROBATCH) the largest divisor <= the request wins,
    period -- the user's bubble/memory trade is not second-guessed.  For
    the default, prefer an M whose microbatch tiles the data axes
    (``(batch/M) % n_data == 0`` -- the condition for the Pallas kernel on
    the pp path, flash_attention_pp) but only when the relative schedule
    cost (M+S-1)/M stays within 15%: the kernel's measured step win is
    ~1.23x end-to-end (BENCH_TPU_MEASURED.md), which pays for a modestly
    deeper bubble but never for a collapsed pipeline (e.g. M 8 -> 1 is a
    75% bubble at pp=4).
    """
    divs = [d for d in range(1, min(target, batch) + 1) if batch % d == 0]
    m0 = max(divs)
    if explicit:
        return m0
    flashable = [d for d in divs if (batch // d) % n_data == 0]
    if flashable:
        f = max(flashable)
        s = n_stages
        if (f + s - 1) / f <= 1.15 * (m0 + s - 1) / m0:
            return f
    return m0


def _remat_wrap(block, remat):
    """Apply the requested rematerialization policy to a layer block.

    ``remat`` is False/"none" (save everything), True/"full" (save only the
    layer boundary; backward re-runs the whole layer, +~1/3 model FLOPs), or
    "attn" (additionally save the attention residuals, tagged ``attn_out``
    in ops/flash_attention.py ``_flash_fwd`` AND
    parallel/ringattention.py ``_ring_fwd`` -- the backward skips
    re-running the quadratic attention forward, the dominant recompute,
    at ~one extra [B, T, D] tensor + lse per layer of HBM; on the sp path
    it also skips the ring's ppermute rounds).  "dots" saves every
    no-batch-dim matmul output (cheapest compute, largest HBM; only fits
    smaller configs).
    """
    import jax

    if remat in (False, None, "none"):
        return block
    if remat in (True, "full"):
        return jax.checkpoint(block)
    policies = {
        "attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    if remat not in policies:
        raise ValueError(f"unknown remat policy {remat!r}; "
                         f"expected bool, 'none', 'full', 'attn' or 'dots'")
    return jax.checkpoint(block, policy=policies[remat])


def _rmsnorm(x, scale, eps):
    from trainingjob_operator_tpu.ops import rmsnorm

    return rmsnorm(x, scale, eps)


def _rope(x, positions, theta):
    """Rotary embedding; x: [B, T, H, D]."""
    import jax.numpy as jnp

    d = x.shape[-1]
    freqs = jnp.exp(-jnp.log(theta) * jnp.arange(0, d, 2, jnp.float32) / d)
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape)


def forward(params: Dict[str, Any], tokens, config: LlamaConfig, *,
            mesh=None, sequence_parallel: bool = False, remat=False,
            n_microbatches: Optional[int] = None, return_kv: bool = False,
            return_hidden: bool = False):
    """Logits for tokens [B, T] -> [B, T, vocab].

    With ``return_kv`` returns ``(logits, (k, v))`` where k/v are the
    post-rope per-layer projections stacked [L, B, T, Hkv, Dh] -- decode
    prefill reuses THIS forward so sampling can never desynchronize from
    the trained math (models/decode.py).  With ``return_hidden`` returns
    the final-norm hidden states [B, T, D] instead of logits (the chunked
    cross-entropy path, ``_chunked_ce``).

    With ``sequence_parallel`` (and a mesh with an ``sp`` axis), attention runs
    as ring attention over the sequence shards; positions account for the
    global offset of each shard.

    With a ``pp`` axis (size > 1) on the mesh, the layer stack runs as a
    GPipe pipeline (parallel/pipeline.py): stages own contiguous layer
    blocks, activations rotate via ppermute, and microbatching amortizes
    the (S-1)/(M+S-1) bubble.  ``n_microbatches`` defaults to
    ``LLAMA_PP_MICROBATCH`` from env, else 8*(S-1) (bubble ~= 11%), clipped
    to the largest divisor of the batch.  Attention inside the pipeline
    runs the Pallas flash kernel via a nested partial-manual shard_map
    (flash_attention_pp); embed/head stay outside the pipeline, replicated
    over pp.

    ``remat`` wraps each layer in ``jax.checkpoint``: the backward recomputes
    the layer's activations instead of saving them -- the standard HBM-for-
    FLOPs trade that lets chip-saturating batch*seq fit in 16 GB v5e HBM
    (saved activations drop from ~6 tensors/layer to the layer boundary).
    Accepts a policy name instead of a bool: "full" (= True), "attn" (also
    save the attention output -- backward skips re-running the quadratic
    attention forward at one extra [B, T, D]/layer of HBM), "dots", "none"
    (= False); see ``_remat_wrap``.
    """
    import jax
    import jax.numpy as jnp

    c = config
    compute = jnp.dtype(c.dtype)
    B, T = tokens.shape
    h = params["tok_embed"].astype(compute)[tokens]

    pipelined = (mesh is not None and "pp" in mesh.axis_names
                 and mesh.shape["pp"] > 1)

    # Pre-cast the stacked matmul weights to the compute dtype with
    # explicit sharding anchors (parallel/sharding.py precast_weights:
    # prevents the partitioner's involuntary full rematerialization of the
    # hoisted bf16 casts on many-axis meshes).  The in-body
    # ``astype(compute)`` calls below become no-ops; norm scales stay f32.
    layers = params["layers"]
    if mesh is not None:
        from trainingjob_operator_tpu.parallel.sharding import (
            precast_weights)

        layers = precast_weights(layers, sharding_rules(pipeline=pipelined),
                                 mesh, compute, r"attn/w|mlp/w_")

    def attn(h, layer):
        # Shapes from h, not the captured globals: inside the pp pipeline
        # the leading dim is a MICROBATCH of the global batch.  Positions are
        # computed inline (not closed over) so the attn body is closure-free
        # under the pipeline's partial-manual shard_map.
        Bh = h.shape[0]
        q = (h @ layer["attn"]["wq"].astype(compute))
        k = (h @ layer["attn"]["wk"].astype(compute))
        v = (h @ layer["attn"]["wv"].astype(compute))
        q = q.reshape(Bh, T, c.n_heads, c.head_dim)
        k = k.reshape(Bh, T, c.n_kv_heads, c.head_dim)
        v = v.reshape(Bh, T, c.n_kv_heads, c.head_dim)
        pos = jnp.broadcast_to(jnp.arange(T)[None, :], (Bh, T))
        q = _rope(q, pos, c.rope_theta)
        k = _rope(k, pos, c.rope_theta)
        if pipelined:
            # Inside the pp-manual shard_map stage body: the Pallas kernel
            # runs per-shard via a nested partial-manual shard_map over the
            # data/tp axes (falls back to identical-math XLA attention where
            # that cannot apply -- see flash_attention_pp).
            from trainingjob_operator_tpu.ops.flash_attention import (
                flash_attention_pp)

            o = flash_attention_pp(q, k, v, mesh, causal=True,
                                   window=c.sliding_window)
        elif sequence_parallel and mesh is not None and "sp" in mesh.axis_names:
            # Ring attention is GQA-aware: the narrow kv blocks travel the
            # ring un-repeated (ICI bytes scale with n_kv_heads).
            from trainingjob_operator_tpu.parallel.ringattention import (
                ring_attention_sharded)

            o = ring_attention_sharded(q, k, v, mesh, causal=True)
        else:
            # Dense path: Pallas flash attention on TPU (GQA-native, no KV
            # repeat in memory), identical-math XLA fallback elsewhere.  On a
            # mesh the kernel runs per-shard via shard_map (a custom call is
            # opaque to GSPMD).
            from trainingjob_operator_tpu.ops import flash_attention
            from trainingjob_operator_tpu.ops.flash_attention import (
                flash_attention_sharded)

            if mesh is not None and mesh.devices.size > 1:
                o = flash_attention_sharded(q, k, v, mesh, causal=True,
                                            window=c.sliding_window)
            else:
                o = flash_attention(q, k, v, causal=True,
                                    window=c.sliding_window)
        o = o.reshape(Bh, T, c.dim)
        # The "attn" remat anchors live on the flash kernel's RESIDUALS
        # (ops/flash_attention.py _flash_fwd): tagging here, downstream of
        # the custom_vjp call, would not stop the backward from re-running
        # the attention forward to regenerate them.
        return o @ layer["attn"]["wo"].astype(compute), (k, v)

    def mlp(h, layer):
        gate = jax.nn.silu(h @ layer["mlp"]["w_gate"].astype(compute))
        up = h @ layer["mlp"]["w_up"].astype(compute)
        return (gate * up) @ layer["mlp"]["w_down"].astype(compute)

    def pin_act(y):
        # Pin normed activations (and, via the transpose, their cotangents)
        # to the batch sharding -- keeps rmsnorm's custom-vjp backward
        # sharding-consistent (parallel/sharding.py pin_batch_act).
        # Skipped under pp: the stage body runs in a partial-manual
        # shard_map where a concrete-mesh NamedSharding cannot appear.
        if mesh is None or pipelined:
            return y
        from trainingjob_operator_tpu.parallel.sharding import pin_batch_act

        return pin_batch_act(y, mesh, sequence_parallel=sequence_parallel)

    # The embedding gather inherits the TABLE's sharding (D over fsdp from
    # the (tp, fsdp) vocab layout); pin the result to the activation layout
    # up front or the partitioner full-remats the transition (observed on
    # the sp mesh: replicate-then-repartition of the [B, T, D] embed).
    h = pin_act(h)

    def block(h, layer):
        a, kv = attn(pin_act(_rmsnorm(h, layer["attn_norm"], c.norm_eps)),
                     layer)
        h = h + a
        h = h + mlp(pin_act(_rmsnorm(h, layer["mlp_norm"], c.norm_eps)),
                    layer)
        # kv only survives the scan under return_kv (else y=None below).
        return h, kv

    block = _remat_wrap(block, remat)

    if sequence_parallel and c.sliding_window:
        raise ValueError("sliding_window is not supported with "
                         "sequence_parallel (ring attention is the "
                         "full-context long path)")
    if return_kv and sequence_parallel:
        # Under sp the k/v are shard-local ring chunks, not the full-sequence
        # cache the decode contract promises -- padding them into a cache
        # would silently attend to zero slots.
        raise ValueError("return_kv is not supported with sequence_parallel")
    if pipelined:
        if return_kv:
            raise ValueError("return_kv is not supported under pipeline "
                             "parallelism (stage-sharded layers)")
        import os

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        explicit = n_microbatches is not None
        if n_microbatches is None:
            env_m = int(os.environ.get("LLAMA_PP_MICROBATCH", "0") or 0)
            explicit = env_m > 0
            # Default M ~ 8*(S-1): bubble (S-1)/(M+S-1) ~= 11% at any depth.
            n_microbatches = env_m or 8 * (mesh.shape["pp"] - 1)
        n_data = 1
        for a in ("dp", "fsdp"):
            if a in mesh.axis_names:
                n_data *= mesh.shape[a]
        m = choose_microbatches(B, n_microbatches, n_data,
                                mesh.shape["pp"], explicit)
        h = gpipe(lambda hh, layer: block(hh, layer)[0], layers,
                  h, mesh, n_microbatches=m)
        kv = None
    else:
        # Scan over stacked layers: one compiled block, L iterations --
        # compile time O(1) in depth, XLA-friendly (no Python unrolling).
        def body(hh, layer):
            h2, kv2 = block(hh, layer)
            return h2, (kv2 if return_kv else None)

        h, kv = jax.lax.scan(body, h, layers)
    h = _rmsnorm(h, params["final_norm"], c.norm_eps)
    if return_hidden:
        return h
    logits = (h @ params["lm_head"].astype(compute)).astype(jnp.float32)
    if return_kv:
        # Post-rope per-layer K/V, stacked [L, B, T, Hkv, Dh] -- the decode
        # cache layout (models/decode.py prefill).
        return logits, kv
    return logits


def _chunked_ce(h, lm_head, targets, chunk: int, compute):
    """Next-token CE without materializing the full [B, T, V] logits.

    The fp32 logits are the single biggest live tensor of the train step
    (B * T * vocab * 4 bytes -- ~2.7 GB at batch 8 / seq 2048 / vocab 32k,
    plus the bf16 copy): scanning ``chunk``-length sequence slices under
    ``jax.checkpoint`` keeps only ONE chunk's logits alive at a time, in
    both forward and backward (recomputed per chunk from the saved hidden).
    Exact -- per-position CE is independent, so chunking changes nothing
    but peak HBM.
    """
    import jax
    import jax.numpy as jnp
    import optax

    B, T, D = h.shape
    n = T // chunk

    def body(total, xs):
        hh, tt = xs                               # [B, chunk, D], [B, chunk]
        logits = (hh @ lm_head.astype(compute)).astype(jnp.float32)
        return total + optax.softmax_cross_entropy_with_integer_labels(
            logits, tt).sum(), None

    h_ch = h.reshape(B, n, chunk, D).swapaxes(0, 1)
    t_ch = targets.reshape(B, n, chunk).swapaxes(0, 1)
    total, _ = jax.lax.scan(jax.checkpoint(body), jnp.float32(0.0),
                            (h_ch, t_ch))
    return total / (B * T)


def loss_fn(params, batch, config: LlamaConfig, *, mesh=None,
            sequence_parallel: bool = False, remat=False, ce_chunk: int = 0):
    """Next-token cross-entropy; batch: {"tokens": [B, T+1]}.

    ``ce_chunk`` > 0 (dividing T) computes the head + CE in sequence chunks
    so the full [B, T, vocab] logits never materialize (``_chunked_ce``) --
    the HBM that buys typically funds a lighter remat policy or a larger
    batch.
    """
    import jax.numpy as jnp
    import optax

    c = config
    tokens = batch["tokens"]
    T = tokens.shape[1] - 1
    if ce_chunk:
        # A requested-but-unusable chunking must not silently fall back to
        # the monolithic logits: the user asked for it to FIT, and a bench
        # trial tagged ce=N must actually measure it.
        if sequence_parallel:
            raise ValueError("ce_chunk is not supported with "
                             "sequence_parallel (logits are seq-sharded)")
        if T % ce_chunk != 0:
            raise ValueError(f"ce_chunk={ce_chunk} does not divide seq {T}")
        h = forward(params, tokens[:, :-1], c, mesh=mesh, remat=remat,
                    return_hidden=True)
        return _chunked_ce(h, params["lm_head"], tokens[:, 1:], ce_chunk,
                           jnp.dtype(c.dtype))
    logits = forward(params, tokens[:, :-1], c, mesh=mesh,
                     sequence_parallel=sequence_parallel, remat=remat)
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:]).mean()


def num_params(config: LlamaConfig) -> int:
    c = config
    kv_dim = c.n_kv_heads * c.head_dim
    per_layer = (c.dim * c.dim * 2 + c.dim * kv_dim * 2
                 + c.dim * c.ffn_dim * 3 + 2 * c.dim)
    return (c.vocab_size * c.dim * 2 + c.n_layers * per_layer + c.dim)
