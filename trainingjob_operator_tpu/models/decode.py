"""Autoregressive decoding for the Llama family: KV cache + sampling.

The reference operator serves no models (it is a control plane, SURVEY.md
§0); the TPU build owns the workload layer, and a training framework whose
checkpoints cannot be sampled from is half a framework.  Design is
XLA-first, mirroring the training side's constraints:

- **Static shapes everywhere**: the KV cache is allocated up front --
  ``max_len`` slots for full causal attention, a RING of ``window`` slots
  under sliding-window attention (slot = position % size; memory O(window)
  for any generation length) -- and written with
  ``lax.dynamic_update_slice``; the decode loop is a ``lax.scan`` over
  positions (one compiled step, no Python loop, no recompilation as the
  sequence grows).
- **Causality via position masking**, not shape: visibility is decided per
  slot from the loop counter (full mode: slot <= t; ring mode: the slot's
  absolute position is inside the window) -- the data-dependent part stays
  in predicates, where XLA wants it.
- **Same params, same shardings**: decode reuses the training pytree and
  SHARDING_RULES; under a mesh the per-step attention/matmuls partition over
  tp/fsdp exactly like training (decode attention is a [B, H, 1, t] matvec,
  MXU-light, HBM-bound -- the cache layout keeps the contiguous T axis
  innermost-but-one so cache reads stream).

Prefill runs the training ``forward`` once over the whole prompt (full
flash-attention path) while also returning each layer's K/V; generation then
scans single-token steps.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from trainingjob_operator_tpu.models import llama


def cache_len(config: llama.LlamaConfig, max_len: int) -> int:
    """Cache slots actually needed: ``max_len`` for full causal attention,
    min(max_len, window) under a sliding window -- positions older than the
    window can never be attended again, so the cache is a RING over the
    last ``window`` positions (slot = position % size) and its memory is
    O(window) regardless of generation length."""
    w = config.sliding_window
    return min(max_len, w) if w else max_len


def pack_cache(k, v, config, max_len: int):
    """Stacked per-layer K/V from prefill ([L, B, T, Hkv, Dh]) -> the cache
    dict, ring-packed when the window cache is smaller than ``max_len``
    (keep the last min(T, S) positions at slot = position % S via a cyclic
    shift).  Shared by the Llama and MoE prefills -- the slot math must
    stay identical across families."""
    import jax.numpy as jnp

    dtype = jnp.dtype(config.dtype)
    T = k.shape[2]
    S = cache_len(config, max_len)
    if S < max_len:
        keep = min(T, S)
        kk, vv = k[:, :, T - keep:], v[:, :, T - keep:]
        pad = ((0, 0), (0, 0), (0, S - keep), (0, 0), (0, 0))
        kk, vv = jnp.pad(kk, pad), jnp.pad(vv, pad)
        # Element at array index i holds position T - keep + i; its slot is
        # that position mod S -- a cyclic shift by (T - keep) % S.
        shift = (T - keep) % S
        return {"k": jnp.roll(kk, shift, axis=2).astype(dtype),
                "v": jnp.roll(vv, shift, axis=2).astype(dtype)}
    pad = ((0, 0), (0, 0), (0, max_len - T), (0, 0), (0, 0))
    return {"k": jnp.pad(k, pad).astype(dtype),
            "v": jnp.pad(v, pad).astype(dtype)}


def init_cache(config: llama.LlamaConfig, batch: int, max_len: int,
               dtype=None) -> Dict[str, Any]:
    """Zeroed KV cache: k/v of [L, B, cache_len, Hkv, Dh] (``cache_len`` =
    ``max_len``, or the sliding window when one is configured)."""
    import jax.numpy as jnp

    c = config
    dtype = dtype or jnp.dtype(c.dtype)
    shape = (c.n_layers, batch, cache_len(c, max_len), c.n_kv_heads,
             c.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _attend_cache(q, keys, values, t, group: int, window: int = 0):
    """q: [B, 1, Hq, Dh] vs cache [B, S, Hkv, Dh].

    Full mode (window == 0): slot == position, slots <= t visible.  Ring
    mode (window > 0): slot s holds position p = t - ((t - s) mod S), the
    newest position congruent to s; visible iff p >= 0 (written) and
    p > t - window (inside the band).  RoPE is applied at write time with
    the ABSOLUTE position, so wrapped slots need no re-rotation.

    ``t`` is a scalar (whole batch at one position -- offline ``generate``)
    or a [B, 1, 1, 1] per-row position tensor (continuous batching:
    ``serve_step`` rows each sit at their own position); both broadcast
    through the same mask algebra."""
    import jax
    import jax.numpy as jnp

    B, S, Hkv, Dh = keys.shape
    qh = q.reshape(B, Hkv, group, Dh).astype(jnp.float32)
    kh = keys.transpose(0, 2, 1, 3).astype(jnp.float32)    # [B,Hkv,S,Dh]
    vh = values.transpose(0, 2, 1, 3).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhsd->bhgs", qh, kh) * (Dh ** -0.5)
    slots = jnp.arange(S)[None, None, None, :]
    if window:
        pos = t - jnp.mod(t - slots, S)
        mask = jnp.logical_and(pos >= 0, pos > t - window)
    else:
        mask = slots <= t
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bhsd->bhgd", probs, vh)
    return out.reshape(B, 1, Hkv * group, Dh)


def prefill(params, tokens, config: llama.LlamaConfig, max_len: int, *,
            mesh=None):
    """Run the prompt [B, T] through the model once; returns (logits of the
    LAST position [B, vocab], cache filled for slots [0, T)).

    Delegates to the TRAINING ``llama.forward`` (``return_kv=True``) -- one
    implementation of the layer math, so sampling cannot desynchronize from
    what was trained (full flash-attention path included).
    """
    import jax.numpy as jnp

    c = config
    B, T = tokens.shape
    if T > max_len:
        raise ValueError(f"prompt {T} exceeds max_len {max_len}")
    logits_all, (k, v) = llama.forward(params, tokens, c, mesh=mesh,
                                       return_kv=True)
    return logits_all[:, -1, :], pack_cache(k, v, c, max_len)


def decode_step(params, cache, token, t, config: llama.LlamaConfig, *,
                mesh=None):
    """One token [B] at position ``t`` (scalar) -> (logits [B, vocab],
    updated cache).

    ``params`` may carry weight-only int8 leaves (models/quant.py
    ``quantize_weights``): decode streams every weight per token, so int8
    halves the HBM bytes that bound decode throughput; ``qmatmul``
    contracts the int8 weight directly and applies the per-output-channel
    scale after the accumulate, so the dequant cost is O(batch x out) --
    it no longer regresses large batches (BENCH_r05's 0.88x at batch 8
    came from materializing the dequantized weight per step).
    """
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.models.quant import (
        dequantize_rows,
        qmatmul,
    )

    c = config
    compute = jnp.dtype(c.dtype)
    B = token.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = dequantize_rows(params["tok_embed"], token, compute)[:, None, :]
    pos = jnp.broadcast_to(t[None, None], (B, 1))

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = qmatmul(x, layer["attn"]["wq"], compute).reshape(
            B, 1, c.n_heads, c.head_dim)
        k = qmatmul(x, layer["attn"]["wk"], compute).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        v = qmatmul(x, layer["attn"]["wv"], compute).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        q = llama._rope(q, pos, c.rope_theta)
        k = llama._rope(k, pos, c.rope_theta)
        # Ring cache under a sliding window: slot = position mod size.
        S = k_cache.shape[1]
        slot = jnp.mod(t, S) if c.sliding_window else t
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0))
        o = _attend_cache(q, k_cache, v_cache, t, group,
                          window=c.sliding_window).astype(compute)
        h = h + qmatmul(o.reshape(B, 1, c.dim), layer["attn"]["wo"], compute)
        x = llama._rmsnorm(h, layer["mlp_norm"], c.norm_eps)
        gate = jax.nn.silu(qmatmul(x, layer["mlp"]["w_gate"], compute))
        up = qmatmul(x, layer["mlp"]["w_up"], compute)
        h = h + qmatmul(gate * up, layer["mlp"]["w_down"], compute)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = qmatmul(h[:, 0, :], params["lm_head"], compute)
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def serve_step(params, cache, token, ts, config: llama.LlamaConfig, *,
               mesh=None):
    """One decode step for a continuous-batching slot batch: tokens [B] at
    PER-SLOT positions ``ts`` [B] (int32) -> (logits [B, vocab], cache).

    Identical layer math to ``decode_step`` with the two generalizations
    the slot scheduler (workloads/serve.py) needs:

    - each row b writes its K/V at its OWN position ts[b] (a vmapped
      ``dynamic_update_slice`` -- one scatter along the slot axis), and
    - causal visibility is evaluated per row (slots <= ts[b]; ring mode
      applies the same slot->position congruence row-wise).

    Free / mid-prefill rows still execute (the step is one fixed-shape
    executable): the scheduler passes their next UNWRITTEN position, so
    the junk K/V such a row writes lands exactly where admission or the
    next prefill chunk will overwrite, and no row's mask can see past its
    own ts -- slot reuse cannot leak stale KV (tests/test_serve.py pins
    the two-sequence content check).
    """
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.models.quant import (
        dequantize_rows,
        qmatmul,
    )

    c = config
    compute = jnp.dtype(c.dtype)
    B = token.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = dequantize_rows(params["tok_embed"], token, compute)[:, None, :]
    pos = ts[:, None]                                           # [B, 1]
    tb = ts.reshape(B, 1, 1, 1)

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = qmatmul(x, layer["attn"]["wq"], compute).reshape(
            B, 1, c.n_heads, c.head_dim)
        k = qmatmul(x, layer["attn"]["wk"], compute).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        v = qmatmul(x, layer["attn"]["wv"], compute).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        q = llama._rope(q, pos, c.rope_theta)
        k = llama._rope(k, pos, c.rope_theta)
        S = k_cache.shape[1]
        slot = jnp.mod(ts, S) if c.sliding_window else ts       # [B]
        write = jax.vmap(
            lambda cc, kk, s: jax.lax.dynamic_update_slice(cc, kk, (s, 0, 0)))
        k_cache = write(k_cache, k.astype(k_cache.dtype), slot)
        v_cache = write(v_cache, v.astype(v_cache.dtype), slot)
        o = _attend_cache(q, k_cache, v_cache, tb, group,
                          window=c.sliding_window).astype(compute)
        h = h + qmatmul(o.reshape(B, 1, c.dim), layer["attn"]["wo"], compute)
        x = llama._rmsnorm(h, layer["mlp_norm"], c.norm_eps)
        gate = jax.nn.silu(qmatmul(x, layer["mlp"]["w_gate"], compute))
        up = qmatmul(x, layer["mlp"]["w_up"], compute)
        h = h + qmatmul(gate * up, layer["mlp"]["w_down"], compute)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = qmatmul(h[:, 0, :], params["lm_head"], compute)
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def _attend_cache_block(q, keys, values, positions, group: int):
    """Chunked-prefill attention for ONE sequence: q [C, Hq, Dh] against
    the full cache row [S, Hkv, Dh]; ``positions`` [C] are the queries'
    absolute positions.  Full-causal only (slot == position, slots <=
    position visible) -- the serving plane runs the full cache
    (``prefill_chunk`` rejects sliding-window configs)."""
    import jax
    import jax.numpy as jnp

    C = q.shape[0]
    S, Hkv, Dh = keys.shape
    qh = q.reshape(C, Hkv, group, Dh).transpose(1, 2, 0, 3)  # [Hkv,g,C,Dh]
    kh = keys.transpose(1, 0, 2).astype(jnp.float32)         # [Hkv,S,Dh]
    vh = values.transpose(1, 0, 2).astype(jnp.float32)
    scores = jnp.einsum("hgcd,hsd->hgcs", qh.astype(jnp.float32),
                        kh) * (Dh ** -0.5)
    mask = jnp.arange(S)[None, None, None, :] <= positions[None, None, :, None]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hgcs,hsd->hgcd", probs, vh)
    return out.transpose(2, 0, 1, 3).reshape(C, Hkv * group * Dh)


def prefill_chunk(params, cache, tokens, slot, t0,
                  config: llama.LlamaConfig, *, mesh=None):
    """Prefill ONE slot with a fixed-size prompt chunk.

    ``tokens`` [C] is the chunk (the LAST chunk of a prompt arrives padded
    to the static C -- two compiled executables serve the whole plane:
    this one and ``serve_step``); ``slot`` is the batch row, ``t0`` the
    chunk's first absolute position.  Writes the chunk's K/V into cache
    positions [t0, t0 + C) of that row and returns (logits [C, vocab],
    cache); the caller reads the logit at its last VALID chunk offset and
    ignores the padded tail -- the junk K/V the padding writes sits at
    positions the sequence's own ``t`` has not reached, so no mask can see
    it before the next chunk/decode overwrites it.

    Requires a full-causal cache: in ring mode (sliding window) padded
    positions would WRAP and clobber live slots.  The scheduler enforces
    ``sliding_window == 0``.
    """
    import jax
    import jax.numpy as jnp

    from trainingjob_operator_tpu.models.quant import (
        dequantize_rows,
        qmatmul,
    )

    c = config
    if c.sliding_window:
        raise ValueError("chunked prefill requires a full-causal cache "
                         "(sliding_window == 0): padded chunk positions "
                         "would wrap the ring and clobber live slots")
    compute = jnp.dtype(c.dtype)
    C = tokens.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = dequantize_rows(params["tok_embed"], tokens, compute)[None, :, :]
    positions = t0 + jnp.arange(C)
    pos = positions[None, :]                                    # [1, C]

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = qmatmul(x, layer["attn"]["wq"], compute).reshape(
            1, C, c.n_heads, c.head_dim)
        k = qmatmul(x, layer["attn"]["wk"], compute).reshape(
            1, C, c.n_kv_heads, c.head_dim)
        v = qmatmul(x, layer["attn"]["wv"], compute).reshape(
            1, C, c.n_kv_heads, c.head_dim)
        q = llama._rope(q, pos, c.rope_theta)
        k = llama._rope(k, pos, c.rope_theta)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (slot, t0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (slot, t0, 0, 0))
        row_k = jax.lax.dynamic_index_in_dim(k_cache, slot, 0, False)
        row_v = jax.lax.dynamic_index_in_dim(v_cache, slot, 0, False)
        o = _attend_cache_block(q[0], row_k, row_v, positions,
                                group).astype(compute)
        h = h + qmatmul(o[None, :, :], layer["attn"]["wo"], compute)
        x = llama._rmsnorm(h, layer["mlp_norm"], c.norm_eps)
        gate = jax.nn.silu(qmatmul(x, layer["mlp"]["w_gate"], compute))
        up = qmatmul(x, layer["mlp"]["w_up"], compute)
        h = h + qmatmul(gate * up, layer["mlp"]["w_down"], compute)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = qmatmul(h[0], params["lm_head"], compute)
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def reset_slot(cache, slot):
    """Per-slot cache paging: zero ONE batch row's K/V across all layers
    (cache arrays are [L, B, S, Hkv, Dh]) so an admitted sequence starts
    from a clean page.  Position masking already guarantees a new
    occupant cannot attend the previous one's rows (its ``t`` restarts at
    0 and every position below it is freshly written), so this is the
    belt-AND-braces half of the no-stale-KV contract -- and what makes a
    leak detectable as exact zeros in debugging dumps.  Survivor rows are
    untouched: admission never re-prefills them."""
    import jax
    import jax.numpy as jnp

    def zero_row(a):
        upd = jnp.zeros((a.shape[0], 1) + a.shape[2:], a.dtype)
        return jax.lax.dynamic_update_slice(a, upd, (0, slot, 0, 0, 0))

    return {"k": zero_row(cache["k"]), "v": zero_row(cache["v"])}


def _mask_logits(logits, top_k: int, top_p: float):
    """Restrict sampling support: outside top-k ids and/or beyond the top-p
    nucleus, logits become -inf.  Static-shape (sort + threshold), so it
    jits into the decode scan."""
    import jax.numpy as jnp

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        import jax

        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative prob >= top_p (always
        # keep the first); the cutoff logit is the last kept one.
        keep = cum - probs < top_p
        cutoff = jnp.max(jnp.where(keep, sorted_logits, -jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def generate(params, prompt, config: llama.LlamaConfig, *, steps: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 0.0, key=None, mesh=None,
             quantize: bool = False):
    """Sample ``steps`` tokens after ``prompt`` [B, T]; returns [B, steps].

    ``temperature`` 0 is greedy (argmax); otherwise requires ``key``, and
    ``top_k``/``top_p`` optionally restrict the sampling support (both may
    be combined; applied in that order).  The whole generation is one
    jit-able computation: prefill + ``lax.scan`` over decode steps.

    ``quantize`` runs the decode loop on weight-only int8 (models/quant.py)
    -- decode streams every weight per token, so int8 halves the HBM bytes
    that bound its throughput, at EVERY batch: ``qmatmul`` contracts the
    int8 weight directly and scales after the accumulate, so the dequant
    that used to regress past batch 4 (BENCH_r05 0.88x at batch 8, the old
    ``INT8_DECODE_MAX_BATCH`` gate) is an O(batch x out) epilogue now.
    Prefill stays full-precision (one compute-bound pass over the prompt;
    also the KV cache source).  For a serving deployment that must also
    drop the fp weights from HBM, call ``quantize_weights`` once at load
    and pass the quantized pytree to ``decode_step`` directly.
    """
    import jax
    import jax.numpy as jnp

    B, T = prompt.shape
    max_len = max_len or (T + steps)
    if T + steps > max_len:
        raise ValueError(f"{T} prompt + {steps} steps > max_len {max_len}")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    # top_k >= vocab and top_p >= 1.0 restrict nothing: treat as disabled
    # (so e.g. top_p=1.0 with greedy decoding is not a spurious error).
    top_k = 0 if top_k >= config.vocab_size else top_k
    top_p = 0.0 if top_p >= 1.0 else top_p
    if (top_k or top_p > 0.0) and temperature <= 0.0:
        raise ValueError("top_k/top_p require temperature > 0 (greedy "
                         "already picks the single best token)")

    logits, cache = prefill(params, prompt, config, max_len, mesh=mesh)
    step_params = params
    if quantize:
        from trainingjob_operator_tpu.models.quant import quantize_weights

        step_params = quantize_weights(params)

    def pick(logits, k):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Temperature FIRST: the top-p nucleus must hold top_p mass of the
        # distribution actually sampled from, not of the unscaled one.
        logits = _mask_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(k, logits, axis=-1).astype(jnp.int32)

    key0 = key if key is not None else jax.random.PRNGKey(0)
    first = pick(logits, jax.random.fold_in(key0, 0))

    def step(carry, i):
        token, cache = carry
        logits, cache = decode_step(step_params, cache, token, T + i, config,
                                    mesh=mesh)
        nxt = pick(logits, jax.random.fold_in(key0, i + 1))
        return (nxt, cache), nxt

    # steps - 1 decode calls: the first token came from prefill's logits,
    # and the scan emits each NEW sample (no wasted final step).
    (_, _), rest = jax.lax.scan(step, (first, cache),
                                jnp.arange(steps - 1))
    return jnp.concatenate([first[:, None], rest.T], axis=1)  # [B, steps]
