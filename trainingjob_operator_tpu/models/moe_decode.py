"""Autoregressive decoding for the MoE family: KV cache + routed experts.

Same architecture as models/decode.py (static shapes, ring KV cache under a
sliding window, prefill delegating to the training forward), with the dense
MLP replaced by per-token top-k expert routing.  The serving win MoE
promises -- compute (and weight reads, via the gathered expert slices) for
only k of E experts per token -- is kept at decode time: the router picks
top-k per token and ``jnp.take`` gathers exactly those experts' weight
slices, so HBM streams k expert FFNs per token, not E.

The reference operator serves no models (SURVEY.md §0); this completes the
train -> checkpoint -> sample loop for the second model family.
"""

from __future__ import annotations

import warnings
from typing import Optional

from trainingjob_operator_tpu.models import decode as _decode
from trainingjob_operator_tpu.models import llama as _llama
from trainingjob_operator_tpu.models import moe


def _check_capacity(config: moe.MoEConfig) -> None:
    """Warn when prefill can drop tokens that decode would keep.

    Prefill runs the training dispatch, whose per-expert capacity is
    ``capacity_factor * k * T / E`` slots; decode's per-token gather is
    dropless.  Whenever ``capacity_factor < E / k`` a sufficiently skewed
    router can overflow an expert at prefill (dropped tokens contribute
    zero from that expert) while the very same tokens, decoded one at a
    time, would get their full top-k mix -- the cache and the sampled
    continuation then disagree about the prompt's representations."""
    c = config
    threshold = c.n_experts / c.experts_per_token
    if c.capacity_factor < threshold:
        warnings.warn(
            f"capacity_factor={c.capacity_factor} < n_experts/"
            f"experts_per_token={threshold:g}: prefill may drop tokens "
            f"that the dropless decode path would route, so prompt "
            f"representations can differ between prefill and decode",
            RuntimeWarning, stacklevel=3)


def prefill(params, tokens, config: moe.MoEConfig, max_len: int, *,
            mesh=None):
    """Prompt [B, T] -> (last-position logits [B, vocab], KV cache).

    Delegates to the training ``moe.forward`` (``return_kv=True``) -- one
    implementation of the layer math, so sampling cannot desynchronize
    from what was trained (routing decisions included).  One residual
    mismatch is inherent: ``moe.forward`` dispatches with finite expert
    capacity (tokens beyond it are dropped), while ``decode_step``'s
    per-token gather is dropless -- see ``_check_capacity``."""
    c = config
    B, T = tokens.shape
    if T > max_len:
        raise ValueError(f"prompt {T} exceeds max_len {max_len}")
    _check_capacity(c)
    logits_all, _aux, (k, v) = moe.forward(params, tokens, c, mesh=mesh,
                                           return_kv=True)
    return logits_all[:, -1, :], _decode.pack_cache(k, v, c, max_len)


def _routed_mlp_token(x, layer, config: moe.MoEConfig, compute):
    """Top-k routed expert MLP for single-token rows x [B, 1, D].

    Gathers the k chosen experts' weight slices per token (``jnp.take``
    along the expert dim), so only k expert FFNs' bytes stream from HBM --
    the capacity machinery of training-time dense dispatch is pointless
    for one token and is skipped entirely (a single token can never
    overflow an expert).  That makes decode *dropless* where prefill is
    not: under a tight ``capacity_factor`` the same token can be dropped
    by prefill's dispatch yet fully routed here (``_check_capacity``
    warns when the configuration admits this)."""
    import jax
    import jax.numpy as jnp

    c = config
    B = x.shape[0]
    xf = x[:, 0]                                            # [B, D]
    logits = xf.astype(jnp.float32) @ layer["moe"]["router"]  # [B, E]
    gates, idx = jax.lax.top_k(jax.nn.softmax(logits, -1),
                               c.experts_per_token)          # [B, k]
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)

    # [B, k, D, F] gathered expert weights; k small (2 for Mixtral).
    wg = jnp.take(layer["moe"]["w_gate"], idx, axis=0).astype(compute)
    wu = jnp.take(layer["moe"]["w_up"], idx, axis=0).astype(compute)
    wd = jnp.take(layer["moe"]["w_down"], idx, axis=0).astype(compute)
    gate = jax.nn.silu(jnp.einsum("bd,bkdf->bkf", xf, wg))
    up = jnp.einsum("bd,bkdf->bkf", xf, wu)
    y = jnp.einsum("bkf,bkfd->bkd", gate * up, wd)           # [B, k, D]
    y = jnp.einsum("bkd,bk->bd", y, gates.astype(compute))
    return y[:, None, :]


def decode_step(params, cache, token, t, config: moe.MoEConfig, *,
                mesh=None):
    """One token [B] at position ``t`` -> (logits [B, vocab], new cache)."""
    import jax
    import jax.numpy as jnp

    c = config
    compute = jnp.dtype(c.dtype)
    B = token.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = params["tok_embed"].astype(compute)[token][:, None, :]
    pos = jnp.broadcast_to(t[None, None], (B, 1))

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = _llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = (x @ layer["attn"]["wq"].astype(compute)).reshape(
            B, 1, c.n_heads, c.head_dim)
        k = (x @ layer["attn"]["wk"].astype(compute)).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        v = (x @ layer["attn"]["wv"].astype(compute)).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        q = _llama._rope(q, pos, c.rope_theta)
        k = _llama._rope(k, pos, c.rope_theta)
        S = k_cache.shape[1]
        slot = jnp.mod(t, S) if c.sliding_window else t
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, slot, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, slot, 0, 0))
        o = _decode._attend_cache(q, k_cache, v_cache, t, group,
                                  window=c.sliding_window).astype(compute)
        h = h + o.reshape(B, 1, c.dim) @ layer["attn"]["wo"].astype(compute)
        x = _llama._rmsnorm(h, layer["moe_norm"], c.norm_eps)
        h = h + _routed_mlp_token(x, layer, c, compute)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = _llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = (h[:, 0, :] @ params["lm_head"].astype(compute))
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def serve_step(params, cache, token, ts, config: moe.MoEConfig, *,
               mesh=None):
    """Continuous-batching decode step: tokens [B] at PER-SLOT positions
    ``ts`` [B] -> (logits [B, vocab], cache).  MoE mirror of
    ``models.decode.serve_step`` -- per-row cache writes (vmapped
    ``dynamic_update_slice``) and per-row causal masks; the routed MLP is
    already per-token (``_routed_mlp_token``), so it needs no change."""
    import jax
    import jax.numpy as jnp

    c = config
    compute = jnp.dtype(c.dtype)
    B = token.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = params["tok_embed"].astype(compute)[token][:, None, :]
    pos = ts[:, None]
    tb = ts.reshape(B, 1, 1, 1)

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = _llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = (x @ layer["attn"]["wq"].astype(compute)).reshape(
            B, 1, c.n_heads, c.head_dim)
        k = (x @ layer["attn"]["wk"].astype(compute)).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        v = (x @ layer["attn"]["wv"].astype(compute)).reshape(
            B, 1, c.n_kv_heads, c.head_dim)
        q = _llama._rope(q, pos, c.rope_theta)
        k = _llama._rope(k, pos, c.rope_theta)
        S = k_cache.shape[1]
        slot = jnp.mod(ts, S) if c.sliding_window else ts
        write = jax.vmap(
            lambda cc, kk, s: jax.lax.dynamic_update_slice(cc, kk, (s, 0, 0)))
        k_cache = write(k_cache, k.astype(k_cache.dtype), slot)
        v_cache = write(v_cache, v.astype(v_cache.dtype), slot)
        o = _decode._attend_cache(q, k_cache, v_cache, tb, group,
                                  window=c.sliding_window).astype(compute)
        h = h + o.reshape(B, 1, c.dim) @ layer["attn"]["wo"].astype(compute)
        x = _llama._rmsnorm(h, layer["moe_norm"], c.norm_eps)
        h = h + _routed_mlp_token(x, layer, c, compute)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = _llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = (h[:, 0, :] @ params["lm_head"].astype(compute))
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def prefill_chunk(params, cache, tokens, slot, t0, config: moe.MoEConfig, *,
                  mesh=None):
    """Prefill ONE slot with a fixed-size chunk (MoE mirror of
    ``models.decode.prefill_chunk``): tokens [C] at positions
    [t0, t0 + C) -> (logits [C, vocab], cache).

    The chunk's MLP routes per token via ``_routed_mlp_token`` (the chunk
    is folded into the batch dim, [1, C, D] -> [C, 1, D]), so CHUNKED
    prefill is dropless exactly like decode -- it sidesteps the
    capacity-drop mismatch ``_check_capacity`` warns about in the
    whole-prompt ``prefill`` path.  Full-causal cache only, as in the
    Llama mirror."""
    import jax
    import jax.numpy as jnp

    c = config
    if c.sliding_window:
        raise ValueError("chunked prefill requires a full-causal cache "
                         "(sliding_window == 0): padded chunk positions "
                         "would wrap the ring and clobber live slots")
    compute = jnp.dtype(c.dtype)
    C = tokens.shape[0]
    group = c.n_heads // c.n_kv_heads
    h = params["tok_embed"].astype(compute)[tokens][None, :, :]
    positions = t0 + jnp.arange(C)
    pos = positions[None, :]

    def layer_step(h, inputs):
        layer, k_cache, v_cache = inputs
        x = _llama._rmsnorm(h, layer["attn_norm"], c.norm_eps)
        q = (x @ layer["attn"]["wq"].astype(compute)).reshape(
            1, C, c.n_heads, c.head_dim)
        k = (x @ layer["attn"]["wk"].astype(compute)).reshape(
            1, C, c.n_kv_heads, c.head_dim)
        v = (x @ layer["attn"]["wv"].astype(compute)).reshape(
            1, C, c.n_kv_heads, c.head_dim)
        q = _llama._rope(q, pos, c.rope_theta)
        k = _llama._rope(k, pos, c.rope_theta)
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (slot, t0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (slot, t0, 0, 0))
        row_k = jax.lax.dynamic_index_in_dim(k_cache, slot, 0, False)
        row_v = jax.lax.dynamic_index_in_dim(v_cache, slot, 0, False)
        o = _decode._attend_cache_block(q[0], row_k, row_v, positions,
                                        group).astype(compute)
        h = h + o[None, :, :] @ layer["attn"]["wo"].astype(compute)
        x = _llama._rmsnorm(h, layer["moe_norm"], c.norm_eps)
        y = _routed_mlp_token(
            x.reshape(C, 1, c.dim), layer, c, compute)      # [C, 1, D]
        h = h + y.reshape(1, C, c.dim)
        return h, (k_cache, v_cache)

    h, (k_new, v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], cache["k"], cache["v"]))
    h = _llama._rmsnorm(h, params["final_norm"], c.norm_eps)
    logits = (h[0] @ params["lm_head"].astype(compute))
    return logits.astype(jnp.float32), {"k": k_new, "v": v_new}


def generate(params, prompt, config: moe.MoEConfig, *, steps: int,
             max_len: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 0.0, key=None, mesh=None):
    """Sample ``steps`` tokens after ``prompt`` [B, T]; returns [B, steps].
    Same sampling surface as the Llama path (models/decode.py)."""
    import jax
    import jax.numpy as jnp

    B, T = prompt.shape
    max_len = max_len or (T + steps)
    if T + steps > max_len:
        raise ValueError(f"{T} prompt + {steps} steps > max_len {max_len}")
    if temperature > 0.0 and key is None:
        raise ValueError("temperature sampling needs a PRNG key")
    top_k = 0 if top_k >= config.vocab_size else top_k
    top_p = 0.0 if top_p >= 1.0 else top_p
    if (top_k or top_p > 0.0) and temperature <= 0.0:
        raise ValueError("top_k/top_p require temperature > 0")

    logits, cache = prefill(params, prompt, config, max_len, mesh=mesh)

    def pick(logits, k):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = _decode._mask_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(k, logits, axis=-1).astype(jnp.int32)

    key0 = key if key is not None else jax.random.PRNGKey(0)
    first = pick(logits, jax.random.fold_in(key0, 0))

    def step(carry, i):
        token, cache = carry
        logits, cache = decode_step(params, cache, token, T + i, config,
                                    mesh=mesh)
        nxt = pick(logits, jax.random.fold_in(key0, i + 1))
        return (nxt, cache), nxt

    (_, _), rest = jax.lax.scan(step, (first, cache),
                                jnp.arange(steps - 1))
    return jnp.concatenate([first[:, None], rest.T], axis=1)
