"""BERT-base encoder -- BASELINE config 4 (multi-host v5e-16, MLM pretrain).

Plain-JAX pytree encoder: learned position embeddings, post-LN transformer
blocks via ``lax.scan`` over stacked layer params, MLM head tied to the token
embedding.  Sharding: dp/fsdp data+param sharding like Llama (rules below);
attention is dense (bidirectional) -- sequence lengths here don't need ring
attention, the sp axis stays size 1 for this model.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_seq_len: int = 512
    norm_eps: float = 1e-12
    dtype: str = "bfloat16"

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=512, dim=64, n_layers=2, n_heads=4, ffn_dim=128,
                   max_seq_len=64)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


#: Stacked layer params carry a leading scan axis that stays unsharded.
SHARDING_RULES = [
    (r"tok_embed|pos_embed", ("tp", "fsdp")),
    (r"attn/w[qkv]$", (None, "fsdp", "tp")),
    (r"attn/wo$", (None, "tp", "fsdp")),
    (r"mlp/w_in$", (None, "fsdp", "tp")),
    (r"mlp/w_out$", (None, "tp", "fsdp")),
    (r".*", ()),
]


def init_params(config: BertConfig, key) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    c = config
    keys = jax.random.split(key, 8)

    def dense(k, shape, scale=0.02):
        return jax.random.normal(k, shape, jnp.float32) * scale

    def stacked(k, shape):
        return dense(k, (c.n_layers,) + shape)

    def stacked_zeros(shape):
        return jnp.zeros((c.n_layers,) + shape, jnp.float32)

    def stacked_ones(shape):
        return jnp.ones((c.n_layers,) + shape, jnp.float32)

    return {
        "tok_embed": dense(keys[0], (c.vocab_size, c.dim)),
        "pos_embed": dense(keys[1], (c.max_seq_len, c.dim)),
        "embed_norm": {"scale": jnp.ones((c.dim,)), "bias": jnp.zeros((c.dim,))},
        "layers": {
            "attn": {
                "wq": stacked(keys[2], (c.dim, c.dim)),
                "wk": stacked(keys[3], (c.dim, c.dim)),
                "wv": stacked(keys[4], (c.dim, c.dim)),
                "wo": stacked(keys[5], (c.dim, c.dim)),
                "bq": stacked_zeros((c.dim,)),
                "bk": stacked_zeros((c.dim,)),
                "bv": stacked_zeros((c.dim,)),
                "bo": stacked_zeros((c.dim,)),
            },
            "mlp": {
                "w_in": stacked(keys[6], (c.dim, c.ffn_dim)),
                "b_in": stacked_zeros((c.ffn_dim,)),
                "w_out": stacked(keys[7], (c.ffn_dim, c.dim)),
                "b_out": stacked_zeros((c.dim,)),
            },
            "attn_norm": {"scale": stacked_ones((c.dim,)),
                          "bias": stacked_zeros((c.dim,))},
            "mlp_norm": {"scale": stacked_ones((c.dim,)),
                         "bias": stacked_zeros((c.dim,))},
        },
        "mlm_bias": jnp.zeros((c.vocab_size,), jnp.float32),
    }


def _layernorm(x, scale, bias, eps):
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    return (((xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps)))
            * scale + bias).astype(x.dtype)


def forward(params, tokens, config: BertConfig, attention_mask=None):
    """tokens [B, T] -> hidden [B, T, dim]."""
    import jax
    import jax.numpy as jnp

    c = config
    compute = jnp.dtype(c.dtype)
    B, T = tokens.shape
    h = (params["tok_embed"].astype(compute)[tokens]
         + params["pos_embed"].astype(compute)[None, :T])
    h = _layernorm(h, params["embed_norm"]["scale"],
                   params["embed_norm"]["bias"], c.norm_eps)
    if attention_mask is None:
        attention_mask = jnp.ones((B, T), bool)
    bias = jnp.where(attention_mask[:, None, None, :], 0.0, -1e30)

    def block(h, layer):
        a = layer["attn"]
        q = (h @ a["wq"].astype(compute) + a["bq"].astype(compute))
        k = (h @ a["wk"].astype(compute) + a["bk"].astype(compute))
        v = (h @ a["wv"].astype(compute) + a["bv"].astype(compute))
        q = q.reshape(B, T, c.n_heads, c.head_dim)
        k = k.reshape(B, T, c.n_heads, c.head_dim)
        v = v.reshape(B, T, c.n_heads, c.head_dim)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (c.head_dim ** -0.5)
        s = s + bias.astype(s.dtype)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(compute)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, T, c.dim)
        o = o @ a["wo"].astype(compute) + a["bo"].astype(compute)
        h = _layernorm(h + o, layer["attn_norm"]["scale"],
                       layer["attn_norm"]["bias"], c.norm_eps)
        m = layer["mlp"]
        f = jax.nn.gelu(h @ m["w_in"].astype(compute) + m["b_in"].astype(compute))
        f = f @ m["w_out"].astype(compute) + m["b_out"].astype(compute)
        h = _layernorm(h + f, layer["mlp_norm"]["scale"],
                       layer["mlp_norm"]["bias"], c.norm_eps)
        return h, None

    h, _ = jax.lax.scan(block, h, params["layers"])
    return h


def mlm_logits(params, hidden, config: BertConfig):
    """Tied-embedding MLM head."""
    import jax.numpy as jnp

    compute = jnp.dtype(config.dtype)
    logits = hidden @ params["tok_embed"].astype(compute).T
    return logits.astype(jnp.float32) + params["mlm_bias"]


def loss_fn(params, batch, config: BertConfig):
    """Masked-LM loss; batch: tokens [B,T], targets [B,T], mask [B,T]
    (mask==1 where a token was masked out and must be predicted)."""
    import jax.numpy as jnp
    import optax

    hidden = forward(params, batch["tokens"], config)
    logits = mlm_logits(params, hidden, config)
    raw = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch["targets"])
    mask = batch["mask"].astype(jnp.float32)
    return (raw * mask).sum() / jnp.maximum(mask.sum(), 1.0)
