"""Controller expectations: in-flight create/delete accounting.

Reference: k8s.io/kubernetes/pkg/controller ControllerExpectations
(controller.go:63,112), with the kubeflow-common key helpers
``GenExpectationPodsKey``/``GenExpectationServicesKey`` (controller.go:399-400).

The reconcile loop skips a job while its expected creations/deletions have not
yet been observed by the informer (reference: controller.go:295,390-404) --
this is what prevents re-entrant syncs from double-creating pods.  Entries
expire after 5 minutes (client-go's ExpectationsTimeout) so a lost event can't
wedge a job forever.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict

EXPECTATION_TIMEOUT = 5 * 60.0


def pods_key(job_key: str, replica_type: str) -> str:
    """Reference: kubeflow common GenExpectationPodsKey."""
    return f"{job_key}/{replica_type}/pods"


def services_key(job_key: str, replica_type: str) -> str:
    return f"{job_key}/{replica_type}/services"


@dataclass
class _Entry:
    adds: int = 0
    dels: int = 0
    timestamp: float = field(default_factory=time.monotonic)


class ControllerExpectations:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    def expect_creations(self, key: str, count: int) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None or (e.adds <= 0 and e.dels <= 0):
                e = _Entry()
                self._entries[key] = e
            e.adds += count
            e.timestamp = time.monotonic()

    def expect_deletions(self, key: str, count: int) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is None or (e.adds <= 0 and e.dels <= 0):
                e = _Entry()
                self._entries[key] = e
            e.dels += count
            e.timestamp = time.monotonic()

    def creation_observed(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.adds -= 1

    def deletion_observed(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.dels -= 1

    def satisfied(self, key: str) -> bool:
        """Fulfilled, expired, or never set -- all mean "go ahead and sync"."""
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return True
            if e.adds <= 0 and e.dels <= 0:
                return True
            if time.monotonic() - e.timestamp > EXPECTATION_TIMEOUT:
                return True
            return False

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)
