"""Shared informers and listers.

Reference: pkg/client/informers/externalversions/ (SharedInformerFactory,
factory.go) and pkg/client/listers/ (indexer-backed lookup).  The tracker is
in-process, so the "cache" is the store itself: listers read through, and
informers fan tracker watch events out to registered handlers -- the add/
update/delete handler triples the controller wires up
(reference: controller.go:118-156).
"""

from __future__ import annotations

import contextlib
import copy
import logging
import threading
from typing import Any, Callable, Dict, List, Optional

from trainingjob_operator_tpu.client.tracker import (
    ADDED,
    DELETED,
    MODIFIED,
    NotFoundError,
    ObjectTracker,
    WatchEvent,
)
from trainingjob_operator_tpu.utils.metrics import METRICS

log = logging.getLogger("trainingjob.informers")


class Lister:
    """Reference: listers/aitrainingjob/v1/aitrainingjob.go:55-93."""

    def __init__(self, tracker: ObjectTracker, kind: str):
        self._tracker = tracker
        self._kind = kind

    def get(self, namespace: str, name: str) -> Any:
        return self._tracker.get(self._kind, namespace, name)

    def try_get(self, namespace: str, name: str) -> Optional[Any]:
        try:
            return self._tracker.get(self._kind, namespace, name)
        except NotFoundError:
            return None

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self._tracker.list(self._kind, namespace, label_selector)


class Informer:
    """Per-kind informer: dispatches watch events to handler triples.

    Handlers run on the mutating thread (synchronously after commit), which is
    the in-process analogue of the informer delivering from its event queue;
    handlers must be cheap -- the controller's handlers only touch the
    workqueue/expectations, same as the reference's.

    A closed/errored watch stream (a tracker that can drop streams reports
    it via the ``on_error`` callback) is survived, not ignored: the informer
    reconnects first, then runs a gap-detecting :meth:`relist` that
    synthesizes exactly the deltas the dead stream swallowed, so handler
    state and secondary indices stay complete across the drop.
    """

    def __init__(self, tracker: ObjectTracker, kind: str):
        self._tracker = tracker
        self._kind = kind
        self._lock = threading.Lock()
        self._handlers: List[Dict[str, Callable]] = []
        self._last_seen: Dict[str, Any] = {}
        # index name -> key fn; index name -> index key -> {obj key: obj}.
        # Maintained incrementally from the same event stream the handlers
        # see, so an indexed lookup is O(bucket) instead of an O(store)
        # deepcopy list -- the difference between a reconcile that scales
        # with the job's pods and one that scales with the cluster.
        self._index_fns: Dict[str, Callable[[Any], Optional[str]]] = {}
        self._indices: Dict[str, Dict[str, Dict[str, Any]]] = {}
        #: Watch re-establishments survived (also a per-kind metric).
        self.relists_total = 0
        self._unsub = self._subscribe()
        with self._lock:
            for obj in self._quorum_list():
                self._last_seen[f"{obj.metadata.namespace}/{obj.metadata.name}"] = obj
        self.lister = Lister(tracker, kind)

    def _subscribe(self) -> Callable[[], None]:
        try:
            return self._tracker.watch(self._kind, self._on_event,
                                       on_error=self._on_stream_error)
        except TypeError:
            # Tracker predating the on_error contract: it can't report
            # drops, so there is nothing to recover from.
            return self._tracker.watch(self._kind, self._on_event)

    def _quorum_list(self) -> List[Any]:
        """Consistent read for seeding and relist.  A plain ``list`` may be
        served stale (lagging follower); relist-after-gap must not be."""
        fn = getattr(self._tracker, "quorum_list", None) or self._tracker.list
        return fn(self._kind)

    def add_event_handler(self,
                          on_add: Optional[Callable[[Any], None]] = None,
                          on_update: Optional[Callable[[Any, Any], None]] = None,
                          on_delete: Optional[Callable[[Any], None]] = None) -> None:
        """Register a handler triple.  Objects already in the store are
        replayed to ``on_add`` (informer cache-sync semantics: at-least-once
        delivery; handlers must be idempotent, which enqueue-style handlers
        are)."""
        with self._lock:
            self._handlers.append({"add": on_add, "update": on_update, "delete": on_delete})
        if on_add is not None:
            for obj in self._tracker.list(self._kind):
                on_add(obj)

    def add_index(self, name: str, key_fn: Callable[[Any], Optional[str]]) -> None:
        """Register a secondary index (reference: cache.Indexer).  ``key_fn``
        maps an object to its index key, or None to leave it unindexed.
        Existing cached objects are indexed immediately; later watch events
        keep the buckets current."""
        with self._lock:
            self._index_fns[name] = key_fn
            buckets: Dict[str, Dict[str, Any]] = {}
            self._indices[name] = buckets
            for obj_key, obj in self._last_seen.items():
                idx_key = key_fn(obj)
                if idx_key is not None:
                    buckets.setdefault(idx_key, {})[obj_key] = obj

    def by_index(self, name: str, key: str) -> List[Any]:
        """All cached objects whose index key equals ``key`` (deepcopied, like
        a lister read: callers may mutate freely)."""
        with self._lock:
            bucket = self._indices.get(name, {}).get(key)
            if not bucket:
                return []
            return [copy.deepcopy(obj) for obj in bucket.values()]

    def _reindex(self, key: str, old: Optional[Any], new: Optional[Any]) -> None:
        """Move ``key`` between index buckets.  Caller holds ``_lock``."""
        for name, key_fn in self._index_fns.items():
            buckets = self._indices[name]
            old_key = key_fn(old) if old is not None else None
            new_key = key_fn(new) if new is not None else None
            if old_key is not None and old_key != new_key:
                bucket = buckets.get(old_key)
                if bucket is not None:
                    bucket.pop(key, None)
                    if not bucket:
                        buckets.pop(old_key, None)
            if new_key is not None:
                buckets.setdefault(new_key, {})[key] = new

    @staticmethod
    def _rv_newer(obj: Any, than: Any) -> bool:
        """True when ``obj`` is a strictly newer revision than ``than``.
        Non-integer resource versions (mirrored external apiservers) can't
        be ordered, so any difference counts as newer."""
        a, b = obj.metadata.resource_version, than.metadata.resource_version
        if isinstance(a, int) and isinstance(b, int):
            return a > b
        return a != b

    def _on_event(self, event: WatchEvent) -> None:
        obj = event.obj
        key = f"{obj.metadata.namespace}/{obj.metadata.name}"
        with self._lock:
            handlers = list(self._handlers)
            old = self._last_seen.get(key)
            if (event.type != DELETED and old is not None
                    and isinstance(obj.metadata.resource_version, int)
                    and isinstance(old.metadata.resource_version, int)
                    and obj.metadata.resource_version
                    < old.metadata.resource_version):
                # Stale replay: an event committed before a relist already
                # brought the cache past it (per-object rv order, like the
                # reference informer's resourceVersion dedup).  Applying it
                # would regress the cache and indices.
                return
            if event.type == DELETED:
                self._last_seen.pop(key, None)
                self._reindex(key, old if old is not None else obj, None)
            else:
                self._last_seen[key] = obj
                self._reindex(key, old, obj)
        for h in handlers:
            if event.type == ADDED and h["add"]:
                h["add"](obj)
            elif event.type == MODIFIED and h["update"]:
                h["update"](old if old is not None else obj, obj)
            elif event.type == DELETED and h["delete"]:
                h["delete"](obj)

    def _on_stream_error(self, err: BaseException) -> None:
        """The watch stream died.  Reconnect FIRST (so every commit after the
        relist snapshot reaches the new stream), then close the gap with a
        relist -- the same reconnect-then-list order the reference reflector
        uses to guarantee no delta is lost between the two."""
        log.warning("%s watch stream dropped (%s); reconnecting + relisting",
                    self._kind, err)
        try:
            self._unsub()
        except Exception as exc:  # the dead stream may already be detached
            log.debug("%s stale unsubscribe failed: %s", self._kind, exc)
        self._unsub = self._subscribe()
        self.relist()

    def relist(self) -> None:
        """Gap-detecting relist: quorum-list the kind and synthesize the
        ADDED/MODIFIED/DELETED deltas the cache missed.

        Runs under the tracker's dispatch lock (when it has one) so no watch
        event can interleave with the diff: the cache is frozen while we
        compare it against the listed state.  Events already committed but
        not yet drained will be delivered *after* us -- as stale replays
        (rv <= listed rv) they are dropped by ``_on_event``'s rv guard, so
        the cache never regresses.

        rv0 (the tracker's latest rv, read before listing) guards deletes:
        a cached entry absent from the list is only deleted if its rv <= rv0
        -- an entry the cache learned of *after* the snapshot must not be
        killed by an older list.
        """
        self.relists_total += 1
        METRICS.inc("trainingjob_informer_relists_total", kind=self._kind)
        rv_fn = getattr(self._tracker, "latest_resource_version", None)
        dispatch_lock = getattr(self._tracker, "_dispatch_lock", None)
        ctx = dispatch_lock if dispatch_lock is not None else contextlib.nullcontext()
        with ctx:
            rv0 = rv_fn() if rv_fn is not None else None
            listed = {f"{o.metadata.namespace}/{o.metadata.name}": o
                      for o in self._quorum_list()}
            with self._lock:
                cached = dict(self._last_seen)
            deltas: List[WatchEvent] = []
            for key, obj in listed.items():
                old = cached.get(key)
                if old is None:
                    deltas.append(WatchEvent(ADDED, obj))
                elif self._rv_newer(obj, old):
                    deltas.append(WatchEvent(MODIFIED, obj))
            for key, old in cached.items():
                if key in listed:
                    continue
                rv = old.metadata.resource_version
                if (rv0 is not None and isinstance(rv, int)) and rv > rv0:
                    continue  # newer than the snapshot; not provably gone
                deltas.append(WatchEvent(DELETED, old))
            for ev in deltas:
                self._on_event(ev)

    def resync(self) -> None:
        """Re-deliver every object as an update (reference: the informer
        resync the controller relies on for its 10 s periodic reconcile,
        options.go:36)."""
        for obj in self._tracker.list(self._kind):
            with self._lock:
                handlers = list(self._handlers)
            for h in handlers:
                if h["update"]:
                    h["update"](obj, obj)

    def stop(self) -> None:
        self._unsub()


class InformerFactory:
    """Reference: informers/externalversions/factory.go -- one shared informer
    per kind."""

    def __init__(self, tracker: ObjectTracker):
        self._tracker = tracker
        self._informers: Dict[str, Informer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str) -> Informer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self._tracker, kind)
                self._informers[kind] = inf
            return inf

    def lister(self, kind: str) -> Lister:
        return self.informer(kind).lister

    def resync_all(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            inf.resync()

    def stop(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
            self._informers.clear()
        for inf in informers:
            inf.stop()
