"""Shared bounded-retry-with-jitter: the one retry idiom for API writes.

Every transient-failure loop in the operator used to be hand-rolled
(``publish_generation``'s three fixed pauses, ad-hoc conflict loops) -- N
idioms with N bug surfaces, and none of them jittered, so a fleet of
controllers recovering from the same apiserver brownout would retry in
lockstep and re-create the brownout (the thundering herd TJA018's jitter
advisory now warns about).  This module is the replacement:

- :class:`RetryPolicy` -- bounded attempts, exponential backoff, a jitter
  fraction that de-synchronizes concurrent retriers;
- :func:`retry_call` -- run a callable under a policy with a
  retryable-exception predicate; every retry is counted in
  ``trainingjob_api_retries_total{verb}``;
- :func:`retrying_clientset` -- a clientset view whose *write* verbs ride
  :func:`retry_call`, transparently absorbing transient API faults
  (``ApiUnavailableError`` / ``ApiTimeoutError`` -- the 5xx/deadline shapes
  ``client/chaos.py`` injects).  ``ConflictError`` is deliberately NOT
  retryable here: a conflict means the caller's read is stale, and blind
  re-submission of the same stale object can never succeed -- the
  re-read-and-merge loops in ``controller/status.py`` own that case.

Sleeping here is fine: the proxy wraps *API round trips*, which already
block the calling worker for the round trip itself (fleet harness
``api_latency``); the backoff budget is bounded and small (sub-second at
the default policy), the same order as one API round trip under load.
"""

from __future__ import annotations

import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.utils.metrics import METRICS

log = logging.getLogger("trainingjob.retry")


class ApiUnavailableError(RuntimeError):
    """Transient 5xx-style failure: the server never processed the request.
    Always safe to retry."""


class ApiTimeoutError(TimeoutError):
    """The request deadline elapsed before the server answered.  The chaos
    plane injects these request-not-delivered (docs/CHAOS.md fault
    taxonomy), so retrying is safe here too."""


#: Exception types every write verb may safely retry.
TRANSIENT_ERRORS = (ApiUnavailableError, ApiTimeoutError)


def is_transient(err: BaseException) -> bool:
    """Default retryable predicate: transient API faults only."""
    return isinstance(err, TRANSIENT_ERRORS)


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts with exponential backoff and jitter.

    ``jitter`` is the +/- fraction applied to each pause: pause =
    ``base_delay * 2**retry * uniform(1-jitter, 1+jitter)``, capped at
    ``max_delay``.  Frozen so one policy instance can be shared across
    every typed client without aliasing surprises.
    """

    attempts: int = 5
    base_delay: float = 0.02
    max_delay: float = 1.0
    jitter: float = 0.5

    def pause(self, retry: int, rng: Optional[random.Random] = None) -> float:
        spread = (rng.uniform if rng is not None else random.uniform)(
            1.0 - self.jitter, 1.0 + self.jitter)
        return min(self.base_delay * (2 ** retry), self.max_delay) * spread


def default_policy() -> RetryPolicy:
    """Policy for controller API writes; attempt count is operator-tunable
    via ``TRAININGJOB_API_RETRIES`` (bounded to something sane)."""
    try:
        attempts = int(os.environ.get(constants.API_RETRIES_ENV, "") or 5)
    except ValueError:
        attempts = 5
    return RetryPolicy(attempts=max(1, min(attempts, 16)))


def backoff_pause(policy: RetryPolicy, retry: int,
                  rng: Optional[random.Random] = None) -> None:
    """Sleep the policy's jittered pause for the ``retry``-th failure.  The
    name is load-bearing: TJA018 recognizes ``*backoff*`` callees as pacing,
    so loops built on this helper are provably not hot loops."""
    time.sleep(policy.pause(retry, rng))


def retry_call(fn: Callable[..., Any], *args: Any,
               policy: Optional[RetryPolicy] = None,
               retryable: Callable[[BaseException], bool] = is_transient,
               verb: str = "call",
               rng: Optional[random.Random] = None,
               **kwargs: Any) -> Any:
    """Run ``fn(*args, **kwargs)`` under ``policy``.

    Retries only exceptions ``retryable`` approves; the final attempt's
    exception propagates unwrapped so callers keep their existing handlers.
    Each retry increments ``trainingjob_api_retries_total{verb}``.
    """
    pol = policy if policy is not None else default_policy()
    for attempt in range(pol.attempts):
        try:
            return fn(*args, **kwargs)
        except Exception as err:
            if attempt >= pol.attempts - 1 or not retryable(err):
                raise
            METRICS.inc("trainingjob_api_retries_total", verb=verb)
            log.debug("retrying %s after %s (attempt %d/%d)",
                      verb, type(err).__name__, attempt + 1, pol.attempts)
            backoff_pause(pol, attempt, rng)
    raise AssertionError("unreachable: attempts >= 1")


class _RetryingClient:
    """Typed-client proxy whose write verbs ride :func:`retry_call`.  Reads
    pass through untouched (they come from informer caches / the local
    store and transient write faults do not apply)."""

    def __init__(self, inner: Any, policy: RetryPolicy):
        self._inner = inner
        self._policy = policy

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def create(self, obj: Any) -> Any:
        return retry_call(self._inner.create, obj,
                          policy=self._policy, verb="create")

    def update(self, obj: Any) -> Any:
        return retry_call(self._inner.update, obj,
                          policy=self._policy, verb="update")

    def update_status(self, obj: Any) -> Any:
        return retry_call(self._inner.update_status, obj,
                          policy=self._policy, verb="update_status")

    def delete(self, namespace: str, name: str,
               grace_period: Optional[int] = None) -> Any:
        return retry_call(self._inner.delete, namespace, name, grace_period,
                          policy=self._policy, verb="delete")


class RetryingClientset:
    """Clientset view wrapping the *given* typed clients (never rebuilt from
    the tracker: an injected latency/chaos layer on those clients must stay
    in the path).  Nodes stay unwrapped -- the controller never writes
    them."""

    def __init__(self, inner: Any, policy: RetryPolicy):
        self._inner = inner
        self.tracker = inner.tracker
        self.trainingjobs = _RetryingClient(inner.trainingjobs, policy)
        self.pods = _RetryingClient(inner.pods, policy)
        self.services = _RetryingClient(inner.services, policy)
        self.events = _RetryingClient(inner.events, policy)
        self.nodes = inner.nodes

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def retrying_clientset(cs: Any,
                       policy: Optional[RetryPolicy] = None) -> Any:
    """Wrap ``cs``'s write verbs in bounded-retry-with-jitter.  A policy of
    one attempt returns ``cs`` unchanged (retry disabled)."""
    pol = policy if policy is not None else default_policy()
    if pol.attempts <= 1:
        return cs
    return RetryingClientset(cs, pol)
