"""Real-Kubernetes clientset: REST CRUD + reflector-fed informer cache.

Reference: the generated client plumbing (pkg/client/) and its wiring in
createClientSets (cmd/app/server.go:111-151) + the SharedInformerFactory
List/Watch glue (pkg/client/informers/externalversions/factory.go:100-130).
Design here: the controller keeps talking to the SAME ``Clientset`` surface
it uses in-memory -- typed clients whose CRUD crosses to the apiserver over
``client/rest.py`` -- while a :class:`Reflector` per kind mirrors the
apiserver's state into the local :class:`ObjectTracker` (mirror_* methods),
so the informer/lister layer is byte-identical between backends.  The
tracker is never the source of truth on this backend; it is purely the
informer cache.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Type

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.rest import ApiError, ClusterConfig, RestClient
from trainingjob_operator_tpu.client.tracker import NotFoundError, ObjectTracker
from trainingjob_operator_tpu.core.objects import Event, Node, Pod, Service

log = logging.getLogger("trainingjob.kube")

CORE_PREFIX = "/api/v1"
GROUP_PREFIX = f"/apis/{constants.GROUP_NAME}/{constants.GROUP_VERSION}"


@dataclass(frozen=True)
class KindInfo:
    kind: str
    plural: str
    prefix: str
    cls: Type
    api_version: str
    namespaced: bool = True


KINDS: Dict[str, KindInfo] = {
    info.kind: info for info in [
        KindInfo(constants.KIND, constants.KIND_PLURAL, GROUP_PREFIX,
                 TPUTrainingJob, constants.API_VERSION),
        KindInfo(Pod.KIND, "pods", CORE_PREFIX, Pod, "v1"),
        KindInfo(Service.KIND, "services", CORE_PREFIX, Service, "v1"),
        KindInfo(Node.KIND, "nodes", CORE_PREFIX, Node, "v1",
                 namespaced=False),
        KindInfo(Event.KIND, "events", CORE_PREFIX, Event, "v1"),
    ]
}


def collection_path(info: KindInfo, namespace: Optional[str] = None) -> str:
    """LIST/CREATE path; no namespace = all namespaces (LIST only)."""
    if not info.namespaced or not namespace:
        return f"{info.prefix}/{info.plural}"
    return f"{info.prefix}/namespaces/{namespace}/{info.plural}"


def item_path(info: KindInfo, namespace: str, name: str) -> str:
    return f"{collection_path(info, namespace if info.namespaced else None)}/{name}"


def label_selector_param(selector: Optional[Dict[str, str]]) -> Optional[Dict[str, str]]:
    if not selector:
        return None
    return {"labelSelector": ",".join(f"{k}={v}" for k, v in sorted(selector.items()))}


class KubeResourceClient:
    """Typed CRUD against the apiserver for one kind.

    Reference: the generated typed client
    (pkg/client/clientset/versioned/typed/aitrainingjob/v1/aitrainingjob.go:38-49).
    Raises the same NotFound/Conflict/AlreadyExists errors as the in-memory
    tracker, so every controller retry path behaves identically.
    """

    def __init__(self, rest: RestClient, info: KindInfo):
        self._rest = rest
        self.info = info

    def _encode(self, obj: Any) -> Dict[str, Any]:
        d = obj.to_dict()
        d["apiVersion"] = self.info.api_version
        d["kind"] = self.info.kind
        return d

    def _decode(self, d: Dict[str, Any]) -> Any:
        return self.info.cls.from_dict(d)

    def create(self, obj: Any) -> Any:
        ns = obj.metadata.namespace if self.info.namespaced else None
        out = self._rest.request("POST", collection_path(self.info, ns or "default"),
                                 body=self._encode(obj))
        return self._decode(out)

    def get(self, namespace: str, name: str) -> Any:
        return self._decode(self._rest.request(
            "GET", item_path(self.info, namespace, name)))

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        out = self._rest.request(
            "GET", collection_path(self.info, namespace),
            query=label_selector_param(label_selector))
        return [self._decode(item) for item in out.get("items", [])]

    def update(self, obj: Any) -> Any:
        out = self._rest.request(
            "PUT", item_path(self.info, obj.metadata.namespace, obj.metadata.name),
            body=self._encode(obj))
        return self._decode(out)

    def delete(self, namespace: str, name: str,
               grace_period: Optional[int] = None) -> None:
        query = ({"gracePeriodSeconds": str(grace_period)}
                 if grace_period is not None else None)
        body = ({"gracePeriodSeconds": grace_period}
                if grace_period is not None else None)
        self._rest.request("DELETE", item_path(self.info, namespace, name),
                           body=body, query=query)


class KubeTrainingJobClient(KubeResourceClient):
    def update_status(self, job: TPUTrainingJob) -> TPUTrainingJob:
        """Status subresource write (the reference quirk fixed: status.go:290
        used plain Update despite UpdateStatus existing)."""
        out = self._rest.request(
            "PUT",
            item_path(self.info, job.metadata.namespace, job.metadata.name)
            + "/status",
            body=self._encode(job))
        return self._decode(out)


class KubeNodeClient(KubeResourceClient):
    """Cluster-scoped; namespace arguments are ignored."""

    def get_node(self, name: str) -> Node:
        return self.get("", name)


class Reflector:
    """LIST+WATCH one kind into the tracker mirror.

    Reference: the reflector inside client-go's shared informer (driven by
    factory.go:100-130).  Initial LIST replaces the cache (mirror_replace),
    then a streaming WATCH applies deltas; any error -- stream end, 410 Gone
    (resourceVersion fell off the server's history window), connection loss --
    falls back to a fresh LIST.  resourceVersion resume means no event gap
    when the reconnect succeeds in-window.
    """

    def __init__(self, rest: RestClient, info: KindInfo,
                 tracker: ObjectTracker, namespace: str = "",
                 watch_timeout: int = 300):
        self._rest = rest
        self._info = info
        self._tracker = tracker
        self._ns = namespace if info.namespaced else ""
        self._watch_timeout = watch_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._synced = threading.Event()
        self.relist_count = 0  # observability/tests

    @property
    def path(self) -> str:
        return collection_path(self._info, self._ns or None)

    def list_once(self) -> str:
        """Full LIST -> mirror_replace; returns the collection
        resourceVersion to watch from."""
        out = self._rest.request("GET", self.path)
        objs = [self._info.cls.from_dict(item)
                for item in out.get("items", [])]
        self._tracker.mirror_replace(self._info.kind, objs)
        self.relist_count += 1
        self._synced.set()
        return str(out.get("metadata", {}).get("resourceVersion", ""))

    def _apply(self, etype: str, obj_dict: Dict[str, Any]) -> Optional[str]:
        if etype == "BOOKMARK":
            return str(obj_dict.get("metadata", {}).get("resourceVersion", ""))
        if etype == "ERROR":
            # Status object: 410 Gone et al. -> force re-list.
            raise ApiError(int(obj_dict.get("code", 500) or 500),
                           obj_dict.get("message", "watch error"))
        obj = self._info.cls.from_dict(obj_dict)
        if etype == "DELETED":
            self._tracker.mirror_delete(self._info.kind,
                                        obj.metadata.namespace
                                        if self._info.namespaced else "",
                                        obj.metadata.name)
        else:  # ADDED | MODIFIED
            self._tracker.mirror_upsert(obj)
        return str(obj.metadata.resource_version or "")

    def run(self) -> None:
        rv = ""
        backoff = 0.0  # grows on consecutive failures, resets on progress
        while not self._stop.is_set():
            try:
                if not rv:
                    rv = self.list_once()
                for etype, obj in self._rest.watch(
                        self.path, resource_version=rv,
                        timeout_seconds=self._watch_timeout):
                    if self._stop.is_set():
                        return
                    new_rv = self._apply(etype, obj)
                    if new_rv:
                        rv = new_rv
                        # Watch PROGRESS (an event made it through) resets
                        # the failure backoff: environments whose LBs RST
                        # long watches instead of closing them cleanly must
                        # not ratchet to the cap while healthy.  A mere
                        # successful list does NOT reset it -- a watch-only
                        # 5xx would then re-list in a tight 0.5 s loop.
                        backoff = 0.0
                # Clean server-side stream end: provably healthy.
                backoff = 0.0
            except ApiError as exc:
                if exc.status == 410:  # Gone: rv outside the server's window
                    log.info("%s watch expired (410); re-listing",
                             self._info.kind)
                    rv = ""
                    continue  # 410 is normal aging, not a server fault
                log.warning("%s watch error: %s", self._info.kind, exc)
                rv = ""
                # Exponential backoff: a persistent 5xx (overloaded or
                # crash-looping apiserver) must not be hammered with
                # full re-lists in a tight loop.
                backoff = min(backoff * 2 or 0.5, 30.0)
                self._stop.wait(backoff)
            except NotFoundError:
                # CRD not applied yet; retry after a beat.
                rv = ""
                self._stop.wait(1.0)
            except Exception as exc:  # connection drop, decode error
                if self._stop.is_set():
                    return
                log.warning("%s watch connection lost (%s); re-syncing",
                            self._info.kind, exc)
                rv = ""
                backoff = min(backoff * 2 or 0.2, 30.0)
                self._stop.wait(backoff)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True,
            name=f"reflector-{self._info.plural}")
        self._thread.start()

    def wait_synced(self, timeout: float = 10.0) -> bool:
        """Reference: WaitForCacheSync (controller.go:195)."""
        return self._synced.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=2.0)
            self._thread = None


#: Kinds the controller consumes through informers/listers.
WATCHED_KINDS = (constants.KIND, Pod.KIND, Service.KIND, Node.KIND)


class KubeClientset(Clientset):
    """``Clientset`` whose writes cross to a real apiserver and whose tracker
    is a reflector-maintained informer cache.

    The controller, handed one of these, runs unchanged: informers fire from
    mirrored watch events, typed CRUD goes straight to the cluster.
    """

    def __init__(self, config: ClusterConfig, namespace: str = "",
                 watch_timeout: int = 300):
        super().__init__(tracker=ObjectTracker())
        self.rest = RestClient(config)
        self.config = config
        self.trainingjobs = KubeTrainingJobClient(self.rest, KINDS[constants.KIND])
        self.pods = KubeResourceClient(self.rest, KINDS[Pod.KIND])
        self.services = KubeResourceClient(self.rest, KINDS[Service.KIND])
        self.nodes = KubeNodeClient(self.rest, KINDS[Node.KIND])
        self.events = KubeResourceClient(self.rest, KINDS[Event.KIND])
        self.reflectors = [
            Reflector(self.rest, KINDS[kind], self.tracker,
                      namespace=namespace, watch_timeout=watch_timeout)
            for kind in WATCHED_KINDS
        ]

    @classmethod
    def from_options(cls, options: Any) -> "KubeClientset":
        """Build from OperatorOptions (reference: createClientSets,
        server.go:111-151): in-cluster serviceaccount, else kubeconfig, with
        --master overriding the server URL."""
        if options.run_in_cluster:
            config = ClusterConfig.in_cluster()
        else:
            try:
                config = ClusterConfig.from_kubeconfig(options.kubeconfig)
            except (FileNotFoundError, KeyError):
                if not options.master_url:
                    raise
                # Master-only mode (reference: BuildConfigFromFlags accepts a
                # bare --master with no kubeconfig, server.go:116).
                config = ClusterConfig()
        if options.master_url:
            config.server = options.master_url
        return cls(config, namespace=options.namespace)

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_synced: bool = True, timeout: float = 30.0) -> None:
        """Start reflectors (informers begin firing); optionally block until
        every cache has completed its first LIST."""
        for r in self.reflectors:
            r.start()
        if wait_synced:
            for r in self.reflectors:
                if not r.wait_synced(timeout):
                    raise TimeoutError(
                        f"cache for {r.path} not synced within {timeout}s")

    def stop(self) -> None:
        for r in self.reflectors:
            r.stop()

    # -- CRD bootstrap (reference: createCRD, controller.go:210-234) ---------

    def ensure_crd(self) -> bool:
        """Apply the structural CRD; True if created, False if it existed."""
        from trainingjob_operator_tpu.client.tracker import AlreadyExistsError
        from trainingjob_operator_tpu.runtime.kube import crd_manifest

        try:
            self.rest.request(
                "POST", "/apis/apiextensions.k8s.io/v1/customresourcedefinitions",
                body=crd_manifest())
            return True
        except AlreadyExistsError:
            return False
