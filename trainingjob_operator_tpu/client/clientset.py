"""Typed clients over the object tracker.

Reference: pkg/client/clientset/versioned/typed/aitrainingjob/v1/
aitrainingjob.go:38-49 (REST CRUD for the CR) and the corev1 clients the
controller uses for pods/services/nodes/events.  One ``Clientset`` bundles the
typed clients, mirroring ``createClientSets`` (cmd/app/server.go:111-151)
collapsing to a single backend handle.

``TrainingJobClient.update_status`` exists and is what the controller calls --
fixing the reference quirk of writing status through plain ``Update`` despite
the subresource method existing (SURVEY.md §8, status.go:290).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.client.tracker import ObjectTracker
from trainingjob_operator_tpu.core.objects import Event, Node, Pod, Service, new_uid, now


class _TypedClient:
    KIND = ""

    def __init__(self, tracker: ObjectTracker):
        self._tracker = tracker

    def create(self, obj):
        return self._tracker.create(obj)

    def get(self, namespace: str, name: str):
        return self._tracker.get(self.KIND, namespace, name)

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None):
        return self._tracker.list(self.KIND, namespace, label_selector)

    def update(self, obj):
        return self._tracker.update(obj)

    def delete(self, namespace: str, name: str, grace_period: Optional[int] = None):
        return self._tracker.delete(self.KIND, namespace, name, grace_period)


class TrainingJobClient(_TypedClient):
    KIND = constants.KIND

    def update_status(self, job: TPUTrainingJob) -> TPUTrainingJob:
        """Status-subresource-style update (whole-object store underneath,
        like the fake clientset's UpdateStatus)."""
        return self._tracker.update(job)


class PodClient(_TypedClient):
    KIND = Pod.KIND


class ServiceClient(_TypedClient):
    KIND = Service.KIND


class NodeClient(_TypedClient):
    """Nodes are cluster-scoped: namespace is always normalized to ""."""

    KIND = Node.KIND

    def create(self, obj: Node) -> Node:
        obj = copy.deepcopy(obj)
        obj.metadata.namespace = ""
        return self._tracker.create(obj)

    def get(self, namespace: str, name: str) -> Node:
        return self._tracker.get(self.KIND, "", name)

    def get_node(self, name: str) -> Node:
        return self._tracker.get(self.KIND, "", name)

    def list(self, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None):
        return self._tracker.list(self.KIND, "", label_selector)

    def update(self, obj: Node) -> Node:
        obj = copy.deepcopy(obj)
        obj.metadata.namespace = ""
        return self._tracker.update(obj)

    def delete(self, namespace: str, name: str, grace_period: Optional[int] = None):
        return self._tracker.delete(self.KIND, "", name, grace_period)


class EventClient(_TypedClient):
    KIND = Event.KIND


class Clientset:
    """The one handle the controller takes; swap the tracker for a real
    cluster adapter to run against Kubernetes."""

    def __init__(self, tracker: Optional[ObjectTracker] = None):
        self.tracker = tracker or ObjectTracker()
        self.trainingjobs = TrainingJobClient(self.tracker)
        self.pods = PodClient(self.tracker)
        self.services = ServiceClient(self.tracker)
        self.nodes = NodeClient(self.tracker)
        self.events = EventClient(self.tracker)
