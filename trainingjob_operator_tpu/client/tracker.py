"""Thread-safe object tracker: the in-process API server.

Reference: the object tracker backing the generated fake clientset
(pkg/client/clientset/versioned/fake/clientset_generated.go:33) -- here with
watch fan-out, optimistic concurrency and graceful-deletion semantics so it can
back the *real* control plane, not just tests:

- Monotonic resource versions; ``update`` conflicts when the caller's version
  is stale (the optimistic-concurrency behavior the reference's 5-retry status
  writer is built around, status.go:288-303).
- Watch handlers receive (ADDED | MODIFIED | DELETED, obj-copy) after the
  mutation commits, outside the store lock.
- Graceful deletion: kinds with a registered finalizer (the runtime/"kubelet")
  get ``deletion_timestamp`` set and a MODIFIED event; the runtime later calls
  ``finalize_delete``.  ``grace_period=0`` deletes immediately (force delete,
  reference: pod.go:469-481).
"""

from __future__ import annotations

import copy
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.core.objects import new_uid, now

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

#: Default grace period for kinds with a finalizer (k8s pod default is 30 s;
#: the sim/localproc runtimes finalize much sooner).
DEFAULT_GRACE_PERIOD = 30


class NotFoundError(KeyError):
    def __init__(self, kind: str, namespace: str, name: str):
        super().__init__(f"{kind} {namespace}/{name} not found")
        self.kind, self.namespace, self.name = kind, namespace, name


class AlreadyExistsError(ValueError):
    pass


class ConflictError(ValueError):
    """Stale resource version on update."""


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: Any


Key = Tuple[str, str, str]  # (kind, namespace, name)


def obj_key(obj: Any) -> Key:
    return (obj.KIND, obj.metadata.namespace, obj.metadata.name)


def split_meta_namespace_key(key: str) -> Tuple[str, str]:
    """'namespace/name' -> (namespace, name); reference: cache.SplitMetaNamespaceKey."""
    parts = key.split("/")
    if len(parts) == 2:
        return parts[0], parts[1]
    if len(parts) == 1:
        return "", parts[0]
    raise ValueError(f"unexpected key format: {key!r}")


def meta_namespace_key(obj: Any) -> str:
    """Reference: controller.KeyFunc / DeletionHandlingMetaNamespaceKeyFunc."""
    ns = obj.metadata.namespace
    return f"{ns}/{obj.metadata.name}" if ns else obj.metadata.name


def match_labels(labels: Dict[str, str], selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class ObjectTracker:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._objects: Dict[Key, Any] = {}
        self._rv = 0
        self._watchers: Dict[str, List[Callable[[WatchEvent], None]]] = {}
        # kind -> finalizer callback(obj) invoked (outside the lock) when a
        # graceful delete begins; the owner must eventually finalize_delete().
        self._finalizers: Dict[str, Callable[[Any], None]] = {}
        # Commit-ordered event log drained under a dedicated dispatch lock so
        # watchers observe mutations in resource-version order even when
        # multiple threads mutate concurrently.
        self._pending_events: List[Tuple[str, WatchEvent]] = []
        self._dispatch_lock = threading.RLock()

    # -- watch ---------------------------------------------------------------

    def watch(self, kind: str, handler: Callable[[WatchEvent], None],
              on_error: Optional[Callable[[BaseException], None]] = None,
              ) -> Callable[[], None]:
        # ``on_error`` is part of the watch contract (stream closed/errored;
        # the subscriber must reconnect + relist).  The in-process tracker
        # never drops a stream, so it is accepted and unused here; fault
        # injection (client/chaos.py ChaosTracker) is what fires it.
        del on_error
        with self._lock:
            self._watchers.setdefault(kind, []).append(handler)

        def unsubscribe() -> None:
            with self._lock:
                try:
                    self._watchers.get(kind, []).remove(handler)
                except ValueError:
                    pass

        return unsubscribe

    def register_finalizer(self, kind: str, fn: Callable[[Any], None]) -> None:
        with self._lock:
            self._finalizers[kind] = fn

    def _enqueue_event(self, kind: str, event: WatchEvent) -> None:
        """Must be called with the store lock held (commit order)."""
        self._pending_events.append((kind, event))

    def _drain_events(self) -> None:
        """Deliver pending events in commit order, outside the store lock.

        The dispatch lock serializes delivery; a handler that mutates the
        tracker re-enters safely (RLock) and drains inline.
        """
        with self._dispatch_lock:
            while True:
                with self._lock:
                    if not self._pending_events:
                        return
                    kind, event = self._pending_events.pop(0)
                    handlers = list(self._watchers.get(kind, []))
                for h in handlers:
                    h(WatchEvent(event.type, copy.deepcopy(event.obj)))

    # -- CRUD ----------------------------------------------------------------

    def create(self, obj: Any) -> Any:
        with self._lock:
            stored = copy.deepcopy(obj)
            meta = stored.metadata
            if not meta.name and meta.generate_name:
                meta.name = meta.generate_name + new_uid()[:5]
            key = obj_key(stored)
            if key in self._objects:
                raise AlreadyExistsError(f"{key} already exists")
            self._rv += 1
            meta.resource_version = self._rv
            if not meta.uid:
                meta.uid = new_uid()
            if meta.creation_timestamp is None:
                meta.creation_timestamp = now()
            self._objects[key] = stored
            # ``stored`` is never mutated after commit (update() swaps in a new
            # object), so the event can reference it; handlers get copies.
            self._enqueue_event(stored.KIND, WatchEvent(ADDED, stored))
            out = copy.deepcopy(stored)
        self._drain_events()
        return out

    def get(self, kind: str, namespace: str, name: str) -> Any:
        with self._lock:
            obj = self._objects.get((kind, namespace, name))
            if obj is None:
                raise NotFoundError(kind, namespace, name)
            return copy.deepcopy(obj)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        with self._lock:
            out = []
            for (k, ns, _), obj in self._objects.items():
                if k != kind:
                    continue
                if namespace is not None and namespace != "" and ns != namespace:
                    continue
                if not match_labels(obj.metadata.labels, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def update(self, obj: Any, check_version: bool = True) -> Any:
        with self._lock:
            key = obj_key(obj)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(*key)
            if (check_version and obj.metadata.resource_version
                    and obj.metadata.resource_version != cur.metadata.resource_version):
                raise ConflictError(
                    f"{key}: resource version {obj.metadata.resource_version} is stale "
                    f"(current {cur.metadata.resource_version})")
            stored = copy.deepcopy(obj)
            self._rv += 1
            stored.metadata.resource_version = self._rv
            stored.metadata.uid = cur.metadata.uid
            stored.metadata.creation_timestamp = cur.metadata.creation_timestamp
            self._objects[key] = stored
            self._enqueue_event(stored.KIND, WatchEvent(MODIFIED, stored))
            out = copy.deepcopy(stored)
        self._drain_events()
        return out

    def delete(self, kind: str, namespace: str, name: str,
               grace_period: Optional[int] = None) -> None:
        """Graceful when a finalizer is registered for ``kind`` and
        grace_period != 0; immediate otherwise."""
        finalizer: Optional[Callable[[Any], None]] = None
        obj_copy: Any = None
        with self._lock:
            key = (kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFoundError(kind, namespace, name)
            fin = self._finalizers.get(kind)
            if fin is not None and grace_period != 0:
                if cur.metadata.deletion_timestamp is not None:
                    return  # already terminating
                grace = DEFAULT_GRACE_PERIOD if grace_period is None else grace_period
                marked = copy.deepcopy(cur)
                self._rv += 1
                marked.metadata.resource_version = self._rv
                marked.metadata.deletion_timestamp = now() + grace
                marked.metadata.deletion_grace_period_seconds = grace
                self._objects[key] = marked
                self._enqueue_event(kind, WatchEvent(MODIFIED, marked))
                finalizer = fin
                obj_copy = copy.deepcopy(marked)
            else:
                del self._objects[key]
                self._enqueue_event(kind, WatchEvent(DELETED, cur))
        self._drain_events()
        if finalizer is not None:
            finalizer(obj_copy)

    def finalize_delete(self, kind: str, namespace: str, name: str) -> None:
        """Complete a graceful delete (called by the runtime/"kubelet")."""
        with self._lock:
            key = (kind, namespace, name)
            cur = self._objects.pop(key, None)
            if cur is not None:
                self._enqueue_event(kind, WatchEvent(DELETED, cur))
        self._drain_events()

    # -- reflector mirror API (kube backend cache) ---------------------------

    def mirror_upsert(self, obj: Any) -> None:
        """Store an object observed from an external apiserver AS-IS (its
        resourceVersion is authoritative -- the tracker must not restamp it)
        and emit ADDED/MODIFIED.  Used by the kube reflector; the tracker is
        then purely an informer cache, never the source of truth."""
        with self._lock:
            stored = copy.deepcopy(obj)
            key = obj_key(stored)
            etype = MODIFIED if key in self._objects else ADDED
            self._objects[key] = stored
            self._enqueue_event(stored.KIND, WatchEvent(etype, stored))
        self._drain_events()

    def mirror_delete(self, kind: str, namespace: str, name: str) -> None:
        """Drop a mirrored object (DELETED observed upstream); no grace/
        finalizer machinery -- the apiserver already did all of that."""
        with self._lock:
            cur = self._objects.pop((kind, namespace, name), None)
            if cur is not None:
                self._enqueue_event(kind, WatchEvent(DELETED, cur))
        self._drain_events()

    def mirror_replace(self, kind: str, objs: List[Any]) -> None:
        """Full-state resync for one kind (the reflector's initial LIST or a
        re-list after a watch gap): upsert everything observed, delete
        everything local that upstream no longer has."""
        seen = set()
        for obj in objs:
            seen.add(obj_key(obj))
            self.mirror_upsert(obj)
        with self._lock:
            stale = [k for k in self._objects
                     if k[0] == kind and k not in seen]
        for _, ns, name in stale:
            self.mirror_delete(kind, ns, name)

    # -- introspection -------------------------------------------------------

    def latest_resource_version(self) -> int:
        """The tracker's current global resource version.  Informers snapshot
        this before a relist so the diff can tell 'deleted during the gap'
        apart from 'created after my list returned'."""
        with self._lock:
            return self._rv

    def count(self, kind: str) -> int:
        with self._lock:
            return sum(1 for (k, _, _) in self._objects if k == kind)
