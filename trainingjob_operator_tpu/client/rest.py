"""Kubernetes apiserver REST client -- stdlib only, no dependencies.

The reference reaches the apiserver through generated client-go clients
(pkg/client/, cmd/app/server.go:111-151).  This is the equivalent transport
layer built directly on http.client + ssl: kubeconfig / in-cluster auth,
JSON CRUD, and streaming watch.  Keeping it dependency-free means the kube
backend works wherever Python does -- no ``kubernetes`` package needed.
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, Optional, Tuple
from urllib.parse import urlencode, urlsplit

from trainingjob_operator_tpu.client.tracker import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver {status}: {message}")
        self.status = status
        self.message = message


@dataclass
class ClusterConfig:
    """Where and how to reach one apiserver."""

    server: str = "https://127.0.0.1:6443"
    token: str = ""
    ca_data: bytes = b""           # PEM
    client_cert_data: bytes = b""  # PEM
    client_key_data: bytes = b""   # PEM
    insecure_skip_tls_verify: bool = False

    @classmethod
    def in_cluster(cls) -> "ClusterConfig":
        """Pod-mounted serviceaccount (KUBERNETES_SERVICE_HOST/_PORT)."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SERVICEACCOUNT_DIR, "token")) as f:
            token = f.read().strip()
        with open(os.path.join(SERVICEACCOUNT_DIR, "ca.crt"), "rb") as f:
            ca = f.read()
        return cls(server=f"https://{host}:{port}", token=token, ca_data=ca)

    @classmethod
    def from_kubeconfig(cls, path: str = "",
                        context: str = "") -> "ClusterConfig":
        """Minimal kubeconfig loader: current-context cluster + user with
        token / client-cert / CA (data or file variants)."""
        import yaml

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            cfg = yaml.safe_load(f)

        def by_name(section, name):
            for entry in cfg.get(section) or []:
                if entry.get("name") == name:
                    return entry.get(section.rstrip("s"), {})
            raise KeyError(f"{section}/{name} not in kubeconfig")

        ctx_name = context or cfg.get("current-context", "")
        ctx = by_name("contexts", ctx_name)
        cluster = by_name("clusters", ctx["cluster"])
        user = by_name("users", ctx["user"]) if ctx.get("user") else {}

        def load(data_key: str, file_key: str) -> bytes:
            if cluster.get(data_key):
                return base64.b64decode(cluster[data_key])
            if user.get(data_key):
                return base64.b64decode(user[data_key])
            src = cluster.get(file_key) or user.get(file_key)
            if src:
                with open(src, "rb") as f:
                    return f.read()
            return b""

        return cls(
            server=cluster.get("server", "https://127.0.0.1:6443"),
            token=user.get("token", ""),
            ca_data=load("certificate-authority-data", "certificate-authority"),
            client_cert_data=load("client-certificate-data", "client-certificate"),
            client_key_data=load("client-key-data", "client-key"),
            insecure_skip_tls_verify=bool(cluster.get("insecure-skip-tls-verify")),
        )


class RestClient:
    """Thread-safe JSON REST transport to one apiserver.

    One persistent connection per calling thread (http.client is not
    thread-safe); watches hold their own connection open for streaming.
    """

    def __init__(self, config: ClusterConfig):
        self._cfg = config
        self._local = threading.local()
        split = urlsplit(config.server)
        self._https = split.scheme == "https"
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or (443 if self._https else 80)
        self._ssl_ctx = self._build_ssl() if self._https else None

    def _build_ssl(self) -> ssl.SSLContext:
        ctx = ssl.create_default_context()
        if self._cfg.insecure_skip_tls_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self._cfg.ca_data:
            ctx.load_verify_locations(cadata=self._cfg.ca_data.decode())
        if self._cfg.client_cert_data and self._cfg.client_key_data:
            # ssl wants files; write them briefly and remove as soon as the
            # context has read them -- key material must not persist on disk.
            cert = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            key = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            try:
                cert.write(self._cfg.client_cert_data)
                cert.close()
                key.write(self._cfg.client_key_data)
                key.close()
                ctx.load_cert_chain(cert.name, key.name)
            finally:
                for path in (cert.name, key.name):
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        return ctx

    def _connection(self, fresh: bool = False,
                    timeout: Optional[float] = 60):
        import http.client

        if not fresh:
            conn = getattr(self._local, "conn", None)
            if conn is not None:
                return conn
        if self._https:
            conn = http.client.HTTPSConnection(
                self._host, self._port, context=self._ssl_ctx,
                timeout=timeout)
        else:
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=timeout)
        if not fresh:
            self._local.conn = conn
        return conn

    def _headers(self) -> Dict[str, str]:
        headers = {"Accept": "application/json",
                   "Content-Type": "application/json"}
        if self._cfg.token:
            headers["Authorization"] = f"Bearer {self._cfg.token}"
        return headers

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None,
                query: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        if query:
            path = f"{path}?{urlencode(query)}"
        payload = json.dumps(body).encode() if body is not None else None
        # Stale keep-alive connections are retried once, but only for
        # idempotent methods: a POST whose connection died mid-flight may
        # already have been applied (duplicate create on retry).
        retries = (0, 1) if method in ("GET", "PUT", "DELETE") else (0,)
        for attempt in retries:
            conn = self._connection()
            try:
                conn.request(method, path, body=payload,
                             headers=self._headers())
                resp = conn.getresponse()
                data = resp.read()
                break
            except (ConnectionError, ssl.SSLError, OSError):
                self._local.conn = None
                if attempt == retries[-1]:
                    raise
        return self._decode(resp.status, data, method, path)

    @staticmethod
    def _decode(status: int, data: bytes, method: str,
                path: str) -> Dict[str, Any]:
        try:
            obj = json.loads(data) if data else {}
        except json.JSONDecodeError:
            obj = {"message": data.decode(errors="replace")}
        if status == 404:
            raise NotFoundError("", "", path)
        if status == 409:
            if obj.get("reason") == "AlreadyExists":
                raise AlreadyExistsError(obj.get("message", path))
            raise ConflictError(obj.get("message", path))
        if status >= 400:
            raise ApiError(status, obj.get("message", f"{method} {path}"))
        return obj

    def watch(self, path: str, resource_version: str = "",
              timeout_seconds: int = 0) -> Iterator[Tuple[str, Dict[str, Any]]]:
        """Stream (event_type, object) pairs until the server closes.

        A dedicated connection: the stream would otherwise block CRUD.
        """
        query = {"watch": "true"}
        if resource_version:
            query["resourceVersion"] = resource_version
        # Always bound the stream server-side: with no socket timeout below, a
        # half-open connection (apiserver crash, NAT drop without FIN) would
        # otherwise hang readline() forever.  The server closes cleanly at
        # timeoutSeconds and the reflector re-lists/re-watches.
        server_timeout = timeout_seconds or 300
        query["timeoutSeconds"] = str(server_timeout)
        # Socket timeout strictly ABOVE the server-side bound: on a healthy
        # connection the server always closes first (at timeoutSeconds), so
        # the socket deadline only fires on a half-open connection (apiserver
        # crash, NAT drop without FIN) -- where no server close ever arrives
        # and readline() would otherwise block forever with a silently stale
        # reflector cache.  The margin absorbs scheduling/RTT slop.
        margin = max(5.0, 0.25 * server_timeout)
        conn = self._connection(fresh=True, timeout=server_timeout + margin)
        conn.request("GET", f"{path}?{urlencode(query)}",
                     headers=self._headers())
        resp = conn.getresponse()
        if resp.status >= 400:
            data = resp.read()
            conn.close()
            self._decode(resp.status, data, "WATCH", path)
        try:
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                yield event.get("type", ""), event.get("object", {})
        finally:
            conn.close()
