"""Fault-injecting clientset/tracker proxies: the chaos plane's muscle.

``fleet/chaos.py`` plans *what* goes wrong (seeded, deterministic);
this module makes it go wrong, between the controller and the tracker:

- :class:`ChaosMonkey` consumes a ``ChaosPlan``: per-verb fault decision
  streams (decision ``i`` applies to the ``i``-th call of that verb),
  wall-clock latency-spike windows, timer-armed watch-stream drops, and
  a stale-list decision stream.  Every injected fault is counted in
  ``trainingjob_chaos_faults_total{kind}``.
- :func:`chaos_clientset` wraps a clientset's typed *write* verbs so they
  draw from the monkey before touching the tracker.
- :class:`ChaosTracker` wraps the tracker the *informers* watch: it can
  sever watch subscriptions mid-run (resumption gap included) and serve
  stale ``list()`` snapshots, while ``quorum_list()`` stays exact -- the
  consistent read informers use to relist after a gap (k8s semantics:
  relist is a quorum read even when plain lists may hit a lagging
  follower).

Injection is strictly **pre-commit**: a faulted request never reaches the
tracker, so "timeout" means *request lost before apply*.  That keeps the
fault model at-most-once.  The nastier at-least-once shape (applied but
unacknowledged, so a retry hits AlreadyExists/Conflict) is exercised
separately by the conflict stream; see docs/CHAOS.md for the taxonomy.
"""

from __future__ import annotations

import copy
import threading
import time
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple

from trainingjob_operator_tpu.client.retry import (
    ApiTimeoutError,
    ApiUnavailableError,
)
from trainingjob_operator_tpu.client.tracker import ConflictError, WatchEvent
from trainingjob_operator_tpu.fleet.chaos import (
    FAULT_CONFLICT,
    FAULT_TIMEOUT,
    FAULT_UNAVAILABLE,
    ChaosPlan,
)
from trainingjob_operator_tpu.utils.metrics import METRICS


class ChaosMonkey:
    """Runtime state for one chaos schedule: call counters, the run clock,
    and the timers that fire time-shaped faults.

    Verb decisions are live from construction (they index call *order*,
    not time); :meth:`attach` starts the run clock that latency windows
    and watch drops key off, so time-shaped faults line up with the churn
    schedule no matter how long harness setup took.
    """

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {v: 0 for v in plan.decisions}
        self._stale_idx = 0
        self.faults: Counter = Counter()
        self._timers: List[threading.Timer] = []
        self._trackers: List["ChaosTracker"] = []
        self._t0: Optional[float] = None      # monotonic, for windows
        self._wall0: Optional[float] = None   # wall, for incident windows
        self._closed = False

    # -- plan consumption ----------------------------------------------------

    def decide(self, verb: str) -> str:
        """Next fault decision for ``verb`` ("ok" past the stream's end)."""
        stream = self.plan.decisions.get(verb)
        if stream is None:
            return "ok"
        with self._lock:
            i = self._counters[verb]
            self._counters[verb] = i + 1
        return stream[i] if i < len(stream) else "ok"

    def decide_stale(self) -> bool:
        with self._lock:
            i = self._stale_idx
            self._stale_idx = i + 1
        stream = self.plan.stale
        return stream[i] if i < len(stream) else False

    def record_fault(self, kind: str) -> None:
        with self._lock:
            self.faults[kind] += 1
        METRICS.inc("trainingjob_chaos_faults_total", kind=kind)

    def maybe_spike(self) -> None:
        """Hold the calling thread for the active latency window's delay,
        if the run clock is inside one."""
        if self._t0 is None:
            return
        elapsed = time.monotonic() - self._t0
        for s in self.plan.spikes:
            if s.start <= elapsed < s.end:
                self.record_fault("latency")
                time.sleep(s.delay)
                return

    # -- lifecycle -----------------------------------------------------------

    def attach(self) -> None:
        """Start the run clock and arm the watch-drop timers."""
        self._t0 = time.monotonic()
        self._wall0 = time.time()
        for drop in self.plan.drops:
            t = threading.Timer(drop.at, self._fire_drop, args=(drop,))
            t.daemon = True
            t.start()
            with self._lock:
                self._timers.append(t)

    def _fire_drop(self, drop: Any) -> None:
        if self._closed:
            return
        self.record_fault("watch_drop")
        for tr in list(self._trackers):
            tr.drop_streams(drop.kind, drop.gap)

    def register_tracker(self, tracker: "ChaosTracker") -> None:
        self._trackers.append(tracker)

    def track_timer(self, timer: threading.Timer) -> None:
        with self._lock:
            self._timers.append(timer)

    def windows_abs(self) -> List[Tuple[str, float, float]]:
        """Chaos windows as (kind, start, end) wall-clock spans, for the
        incident recorder's downtime attribution.  Empty before attach."""
        if self._wall0 is None:
            return []
        w0 = self._wall0
        out: List[Tuple[str, float, float]] = []
        for s in self.plan.spikes:
            out.append(("latency", w0 + s.start, w0 + s.end))
        for d in self.plan.drops:
            out.append(("watch_drop", w0 + d.at, w0 + d.at + d.gap))
        return out

    def close(self) -> None:
        self._closed = True
        with self._lock:
            timers, self._timers = self._timers, []
        for t in timers:
            t.cancel()


class _ChaosClient:
    """Typed-client proxy that draws a fault decision before each write.
    Reads pass through -- read-side chaos lives in :class:`ChaosTracker`
    (stale lists) where the informers actually read."""

    def __init__(self, inner: Any, monkey: ChaosMonkey):
        self._inner = inner
        self._monkey = monkey

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def _pre(self, verb: str) -> None:
        m = self._monkey
        m.maybe_spike()
        decision = m.decide(verb)
        if decision == FAULT_UNAVAILABLE:
            m.record_fault(decision)
            raise ApiUnavailableError(f"chaos: injected 5xx on {verb}")
        if decision == FAULT_TIMEOUT:
            m.record_fault(decision)
            time.sleep(m.plan.profile.timeout_hold)
            raise ApiTimeoutError(f"chaos: injected timeout on {verb}")
        if decision == FAULT_CONFLICT:
            m.record_fault(decision)
            raise ConflictError(f"chaos: injected conflict on {verb}")

    def create(self, obj: Any) -> Any:
        self._pre("create")
        return self._inner.create(obj)

    def update(self, obj: Any) -> Any:
        self._pre("update")
        return self._inner.update(obj)

    def update_status(self, obj: Any) -> Any:
        self._pre("update_status")
        return self._inner.update_status(obj)

    def delete(self, namespace: str, name: str,
               grace_period: Optional[int] = None) -> Any:
        self._pre("delete")
        return self._inner.delete(namespace, name, grace_period)


class ChaosClientset:
    """Clientset view whose write verbs misbehave per the plan.  Wraps the
    *given* typed clients (never rebuilt from the tracker) so an injected
    latency layer underneath stays in the request path.  Nodes stay
    unwrapped: the controller never writes them, and faulting the
    harness's capacity setup would test the harness, not the operator."""

    def __init__(self, inner: Any, monkey: ChaosMonkey):
        self._inner = inner
        self.tracker = inner.tracker
        self.trainingjobs = _ChaosClient(inner.trainingjobs, monkey)
        self.pods = _ChaosClient(inner.pods, monkey)
        self.services = _ChaosClient(inner.services, monkey)
        self.events = _ChaosClient(inner.events, monkey)
        self.nodes = inner.nodes

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def chaos_clientset(cs: Any, monkey: ChaosMonkey) -> Any:
    return ChaosClientset(cs, monkey)


class ChaosTracker:
    """Tracker proxy for the *informer* side of the control plane.

    - ``watch`` subscriptions are registered here so a planned drop can
      sever every stream of a kind, wait out the resumption gap, then
      notify subscribers via their ``on_error`` callback (hardened
      informers reconnect + relist).  A subscriber without ``on_error``
      is silently resubscribed after the gap -- deltas committed during
      the gap are lost, which is exactly the legacy hazard the informer
      relist regression test pins.
    - ``list`` may serve the previous snapshot for its query (a lagging
      follower read), per the stale decision stream.
    - ``quorum_list`` is always exact: the consistent read relist uses.

    Everything else (get, register_finalizer, ``_dispatch_lock``, ...)
    passes through to the real tracker.
    """

    def __init__(self, inner: Any, monkey: ChaosMonkey):
        self._inner = inner
        self._monkey = monkey
        self._lock = threading.Lock()
        self._subs: Dict[int, Dict[str, Any]] = {}
        self._next_id = 0
        #: query-key -> previous result (deepcopies), for stale serving.
        self._snapshots: Dict[Any, List[Any]] = {}
        monkey.register_tracker(self)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    # -- watch ---------------------------------------------------------------

    def watch(self, kind: str, handler: Callable[[WatchEvent], None],
              on_error: Optional[Callable[[BaseException], None]] = None,
              ) -> Callable[[], None]:
        rec: Dict[str, Any] = {
            "kind": kind, "handler": handler, "on_error": on_error,
            "unsub": self._inner.watch(kind, handler), "dropped": False,
        }
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._subs[sid] = rec

        def unsubscribe() -> None:
            with self._lock:
                r = self._subs.pop(sid, None)
            if r is not None and not r["dropped"]:
                r["unsub"]()

        return unsubscribe

    def drop_streams(self, kind: str, gap: float) -> None:
        """Sever every live subscription of ``kind`` now; after ``gap``
        seconds (the resumption gap -- deltas committed inside it flow
        past the dead stream) notify or resubscribe the victims."""
        with self._lock:
            victims = [(sid, r) for sid, r in self._subs.items()
                       if r["kind"] == kind and not r["dropped"]]
            for _, r in victims:
                r["dropped"] = True
        for _, r in victims:
            r["unsub"]()
        if not victims:
            return
        t = threading.Timer(gap, self._after_gap, args=(victims,))
        t.daemon = True
        t.start()
        self._monkey.track_timer(t)

    def _after_gap(self, victims: List[Tuple[int, Dict[str, Any]]]) -> None:
        for sid, r in victims:
            with self._lock:
                if sid not in self._subs:
                    continue  # unsubscribed during the gap
                if r["on_error"] is not None:
                    # The subscriber owns recovery: it will re-watch (a
                    # fresh subscription) and relist.  Retire this one.
                    self._subs.pop(sid, None)
            if r["on_error"] is not None:
                r["on_error"](
                    ApiUnavailableError(f"chaos: {r['kind']} watch dropped"))
            else:
                with self._lock:
                    if sid in self._subs:
                        r["unsub"] = self._inner.watch(r["kind"], r["handler"])
                        r["dropped"] = False

    # -- reads ---------------------------------------------------------------

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        qkey = (kind, namespace,
                tuple(sorted(label_selector.items())) if label_selector else None)
        if self._monkey.decide_stale():
            with self._lock:
                snap = self._snapshots.get(qkey)
            if snap is not None:
                self._monkey.record_fault("stale_list")
                return [copy.deepcopy(o) for o in snap]
        fresh = self._inner.list(kind, namespace, label_selector)
        with self._lock:
            self._snapshots[qkey] = [copy.deepcopy(o) for o in fresh]
        return fresh

    def quorum_list(self, kind: str, namespace: Optional[str] = None,
                    label_selector: Optional[Dict[str, str]] = None) -> List[Any]:
        return self._inner.list(kind, namespace, label_selector)
