"""Client plumbing: object tracker, typed clients, informers, listers,
workqueue, expectations.

Reference: ``pkg/client/`` (generated clientset/informers/listers) plus the
client-go machinery the controller imports (workqueue, expectations --
SURVEY.md §1 "external load-bearing dependencies").  Here the cluster store is
an in-process object tracker with watch semantics -- the same design as the
reference's fake clientset (pkg/client/clientset/versioned/fake/
clientset_generated.go:33, object-tracker-backed), promoted to the primary
backend so the whole control plane runs and is tested without a kube apiserver.
A real-Kubernetes backend can implement the same ``Clientset`` surface
(runtime/kube.py, gated on the kubernetes package).
"""

from trainingjob_operator_tpu.client.tracker import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    ObjectTracker,
    WatchEvent,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.informers import InformerFactory, Lister
from trainingjob_operator_tpu.client.workqueue import RateLimitingQueue
from trainingjob_operator_tpu.client.expectations import ControllerExpectations

__all__ = [
    "AlreadyExistsError",
    "ConflictError",
    "NotFoundError",
    "ObjectTracker",
    "WatchEvent",
    "Clientset",
    "InformerFactory",
    "Lister",
    "RateLimitingQueue",
    "ControllerExpectations",
]
