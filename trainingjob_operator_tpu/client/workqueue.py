"""Rate-limited delaying workqueue.

Reference: k8s.io/client-go/util/workqueue as used by the controller
(controller.go:113 ``NewNamedRateLimitingQueue(DefaultControllerRateLimiter())``,
enqueue modes immediate/rate-limited/delayed at controller.go:406-421).

Semantics preserved from client-go:
- An item present in the queue is not added again (dedup).
- An item being processed (between Get and Done) that is re-added is marked
  dirty and requeued on Done -- the single-writer-per-key guarantee the
  reconcile loop's correctness rests on (SURVEY.md §5.2).
- ``add_rate_limited`` applies per-item exponential backoff
  (base 5 ms, cap 1000 s -- client-go's DefaultControllerRateLimiter
  ItemExponentialFailureRateLimiter parameters); ``forget`` resets it.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple


class RateLimitingQueue:
    def __init__(self, name: str = "queue",
                 base_delay: float = 0.005, max_delay: float = 1000.0):
        self.name = name
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._cond = threading.Condition()
        self._queue: List[Any] = []          # FIFO of ready items
        self._queued: Set[Any] = set()        # items in _queue
        self._processing: Set[Any] = set()
        self._dirty: Set[Any] = set()         # re-added while processing
        self._waiting: List[Tuple[float, int, Any]] = []  # delayed heap
        self._waiting_seq = 0
        self._failures: Dict[Any, int] = {}
        self._shutdown = False
        self._pump = threading.Thread(target=self._pump_waiting, daemon=True,
                                      name=f"workqueue-{name}-delay")
        self._pump.start()

    # -- add variants --------------------------------------------------------

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            if item in self._queued:
                return
            self._queue.append(item)
            self._queued.add(item)
            self._cond.notify_all()

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            self._waiting_seq += 1
            heapq.heappush(self._waiting, (time.monotonic() + delay, self._waiting_seq, item))
            self._cond.notify_all()

    def add_rate_limited(self, item: Any) -> None:
        with self._cond:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
        delay = min(self._base_delay * (2 ** failures), self._max_delay)
        self.add_after(item, delay)

    def forget(self, item: Any) -> None:
        with self._cond:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    # -- consume -------------------------------------------------------------

    def get(self, timeout: Optional[float] = None) -> Tuple[Optional[Any], bool]:
        """Block until an item is ready.  Returns (item, shutdown)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._shutdown:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, False
                self._cond.wait(timeout=remaining)
            if self._shutdown and not self._queue:
                return None, True
            item = self._queue.pop(0)
            self._queued.discard(item)
            self._processing.add(item)
            return item, False

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queue.append(item)
                    self._queued.add(item)
                    self._cond.notify_all()

    # -- lifecycle -----------------------------------------------------------

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def _pump_waiting(self) -> None:
        while True:
            with self._cond:
                if self._shutdown:
                    return
                now = time.monotonic()
                while self._waiting and self._waiting[0][0] <= now:
                    _, _, item = heapq.heappop(self._waiting)
                    if item not in self._queued and item not in self._processing:
                        self._queue.append(item)
                        self._queued.add(item)
                        self._cond.notify_all()
                    elif item in self._processing:
                        self._dirty.add(item)
                # Sleep until the next delayed item is due; add_after/shut_down
                # notify to wake us.  No waiting items -> block indefinitely.
                wait = max(0.001, self._waiting[0][0] - now) if self._waiting else None
                self._cond.wait(timeout=wait)
