"""Rate-limited delaying workqueue.

Reference: k8s.io/client-go/util/workqueue as used by the controller
(controller.go:113 ``NewNamedRateLimitingQueue(DefaultControllerRateLimiter())``,
enqueue modes immediate/rate-limited/delayed at controller.go:406-421).

Semantics preserved from client-go:
- An item present in the queue is not added again (dedup).
- An item being processed (between Get and Done) that is re-added is marked
  dirty and requeued on Done -- the single-writer-per-key guarantee the
  reconcile loop's correctness rests on (SURVEY.md §5.2).  This is what makes
  raising ``thread_num`` safe: however many workers drain the queue, a key is
  never reconciled by two of them at once (tests/test_workqueue_concurrency.py
  hammers exactly this).
- ``add_rate_limited`` applies per-item exponential backoff
  (base 5 ms, cap 1000 s -- client-go's DefaultControllerRateLimiter
  ItemExponentialFailureRateLimiter parameters); ``forget`` resets it.
- ``add_after`` coalesces duplicate delayed keys to the EARLIEST pending
  deadline (client-go delayingQueue waitForPriorityQueue semantics): a job
  that arms a delayed re-sync on every reconcile must not grow the heap by
  one entry per sync.  Superseded heap entries are dropped lazily on pop.
- ``shut_down`` cancels all pending delayed deliveries (the single pump
  thread exits and the waiting heap is cleared) -- a fleet-scale run that
  armed thousands of delayed re-syncs leaks nothing on teardown.
- Optional per-key failure **quarantine**: a key failing
  ``quarantine_after`` consecutive syncs is parked for a flat
  ``quarantine_delay`` instead of riding the exponential ladder further --
  a poisoned key (bad spec, wedged dependency) stops consuming worker
  slots at the retry cadence, and ``forget`` (one success) releases it.
  Off by default (``quarantine_after=0``); the controller turns it on.

Scale counters (read by the controller's metrics gauges and bench.py):
``retries_total`` (rate-limited requeues), ``depth_high_water`` (max ready
depth observed), and per-item queue-wait tracking (``pop_wait``) feeding the
``trainingjob_reconcile_latency_ms`` histogram.
"""

from __future__ import annotations

import collections
import heapq
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Set, Tuple


class RateLimitingQueue:
    def __init__(self, name: str = "queue",
                 base_delay: float = 0.005, max_delay: float = 1000.0,
                 quarantine_after: int = 0, quarantine_delay: float = 30.0):
        self.name = name
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._quarantine_after = quarantine_after
        self._quarantine_delay = quarantine_delay
        self._quarantined: Set[Any] = set()
        self._cond = threading.Condition()
        self._queue: Deque[Any] = collections.deque()  # FIFO of ready items
        self._queued: Set[Any] = set()        # items in _queue
        self._processing: Set[Any] = set()
        self._dirty: Set[Any] = set()         # re-added while processing
        self._waiting: List[Tuple[float, int, Any]] = []  # delayed heap
        self._waiting_seq = 0
        # item -> its earliest pending deadline; the authoritative view of the
        # delayed set (heap entries that disagree are stale and skipped).
        self._waiting_deadlines: Dict[Any, float] = {}
        self._failures: Dict[Any, int] = {}
        # First-enqueue timestamp while the item sits ready, moved to
        # _wait_seconds on get() (single processor per key -> no races).
        self._enqueued_at: Dict[Any, float] = {}
        self._wait_seconds: Dict[Any, float] = {}
        self._shutdown = False
        #: Scale observability (monotonic; read without the lock is fine).
        self.retries_total = 0
        self.coalesced_total = 0
        self.depth_high_water = 0
        self.quarantined_total = 0
        self._pump = threading.Thread(target=self._pump_waiting, daemon=True,
                                      name=f"workqueue-{name}-delay")
        self._pump.start()

    # -- add variants --------------------------------------------------------

    def _append_ready(self, item: Any) -> None:
        """Append to the ready FIFO.  Caller holds ``_cond``."""
        self._queue.append(item)
        # analyzer: allow[lock-discipline] every caller (add, done,
        # _pump_waiting) invokes this helper with self._cond already held;
        # the mutation is lock-protected, just not lexically.
        self._queued.add(item)
        self._enqueued_at.setdefault(item, time.monotonic())
        if len(self._queue) > self.depth_high_water:
            self.depth_high_water = len(self._queue)
        self._cond.notify_all()

    def add(self, item: Any) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            if item in self._queued:
                return
            self._append_ready(item)

    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._cond:
            if self._shutdown:
                return
            deadline = time.monotonic() + delay
            current = self._waiting_deadlines.get(item)
            if current is not None:
                # Coalesce to the earliest deadline; the later entry stays in
                # the heap and is discarded on pop (deadline mismatch).
                self.coalesced_total += 1
                if current <= deadline:
                    return
            self._waiting_deadlines[item] = deadline
            self._waiting_seq += 1
            heapq.heappush(self._waiting, (deadline, self._waiting_seq, item))
            self._cond.notify_all()

    def add_rate_limited(self, item: Any) -> bool:
        """Requeue after per-item backoff.  Returns True when this failure
        pushed the item INTO quarantine (the transition, not the steady
        state) so the caller can record/alert exactly once per episode."""
        entered = False
        with self._cond:
            failures = self._failures.get(item, 0)
            self._failures[item] = failures + 1
            self.retries_total += 1
            if (self._quarantine_after > 0
                    and failures + 1 >= self._quarantine_after):
                if item not in self._quarantined:
                    self._quarantined.add(item)
                    self.quarantined_total += 1
                    entered = True
                delay = self._quarantine_delay
            else:
                delay = min(self._base_delay * (2 ** failures), self._max_delay)
        self.add_after(item, delay)
        return entered

    def forget(self, item: Any) -> None:
        with self._cond:
            self._failures.pop(item, None)
            self._quarantined.discard(item)

    def num_requeues(self, item: Any) -> int:
        with self._cond:
            return self._failures.get(item, 0)

    def num_quarantined(self) -> int:
        """Keys currently parked in quarantine (gauge source)."""
        with self._cond:
            return len(self._quarantined)

    def is_quarantined(self, item: Any) -> bool:
        with self._cond:
            return item in self._quarantined

    # -- consume -------------------------------------------------------------

    def get(self, timeout: Optional[float] = None) -> Tuple[Optional[Any], bool]:
        """Block until an item is ready.  Returns (item, shutdown)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._shutdown:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None, False
                self._cond.wait(timeout=remaining)
            if self._shutdown and not self._queue:
                return None, True
            item = self._queue.popleft()
            self._queued.discard(item)
            self._processing.add(item)
            ts = self._enqueued_at.pop(item, None)
            if ts is not None:
                self._wait_seconds[item] = time.monotonic() - ts
            return item, False

    def pop_wait(self, item: Any) -> Optional[float]:
        """Seconds the item most recently spent ready-queued before its get()
        (None when unknown).  Valid between get() and done() -- the
        single-writer-per-key guarantee makes the per-item slot race-free."""
        with self._cond:
            return self._wait_seconds.pop(item, None)

    def done(self, item: Any) -> None:
        with self._cond:
            self._processing.discard(item)
            self._wait_seconds.pop(item, None)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._append_ready(item)

    # -- lifecycle -----------------------------------------------------------

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)

    def waiting(self) -> int:
        """Delayed items pending delivery (post-coalescing)."""
        with self._cond:
            return len(self._waiting_deadlines)

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            # Cancel pending delayed deliveries: nothing may fire after
            # shutdown, and a fleet run's thousands of armed re-syncs must
            # not pin their keys in memory.
            self._waiting.clear()
            self._waiting_deadlines.clear()
            self._cond.notify_all()
        # Join outside the condition: the pump re-acquires it to observe
        # _shutdown, so joining under the lock would deadlock shutdown.
        self._pump.join(timeout=2.0)

    def _pump_waiting(self) -> None:
        while True:
            with self._cond:
                if self._shutdown:
                    return
                now = time.monotonic()
                while self._waiting and self._waiting[0][0] <= now:
                    deadline, _, item = heapq.heappop(self._waiting)
                    if self._waiting_deadlines.get(item) != deadline:
                        continue  # superseded by an earlier re-add: stale
                    del self._waiting_deadlines[item]
                    if item not in self._queued and item not in self._processing:
                        self._append_ready(item)
                    elif item in self._processing:
                        self._dirty.add(item)
                # Sleep until the next delayed item is due; add_after/shut_down
                # notify to wake us.  No waiting items -> block indefinitely.
                wait = max(0.001, self._waiting[0][0] - now) if self._waiting else None
                self._cond.wait(timeout=wait)
