#!/usr/bin/env python
"""Benchmark: elastic recovery p50 (preempt -> Running).

The north-star metric (BASELINE.json): after a worker is preempted
(SIGKILLed, spot-reclaim analogue), how long until the job is fully Running
again -- restart machinery fired, replacement pods created, scheduled and
running.  Target: < 90 s.  The reference publishes no numbers (BASELINE.md);
vs_baseline is the 90 s target divided by our p50 (>1 = beating the target).

Runs the REAL control plane end-to-end: threaded controller + local-process
runtime with actual worker subprocesses, repeated preemption trials.

Prints exactly one JSON line.
"""

import json
import statistics
import sys
import time

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)
from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime

TRIALS = 9
WORKERS = 4


def wait_for(pred, timeout=60.0, interval=0.005):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def fully_running(cs, name, expect_restarts):
    job = cs.trainingjobs.get("default", name)
    if job.status.phase != TrainingJobPhase.RUNNING:
        return False
    pods = cs.pods.list("default")
    if len(pods) != WORKERS:
        return False
    return all(
        p.metadata.labels.get(constants.RESTART_COUNT_LABEL) == str(expect_restarts)
        and p.status.phase == "Running"
        for p in pods)


def main() -> int:
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    rt = LocalProcRuntime(cs, nodes=2, termination_grace=1.0,
                          log_dir="/tmp/tpu-trainingjob-bench-logs")
    rt.start()
    tc.run(workers=2)

    job = TPUTrainingJob(metadata=ObjectMeta(name="bench", namespace="default"))
    job.spec.replica_specs["worker"] = ReplicaSpec(
        replicas=WORKERS,
        restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
        restart_scope=RestartScope.ALL,
        template=PodTemplateSpec(spec=PodSpec(containers=[
            Container(name="aitj-worker",
                      command=[sys.executable, "-c", "import time; time.sleep(600)"],
                      ports=[ContainerPort(name="aitj-7900", container_port=7900)])])))
    job.spec.restarting_exit_code = "137,143"
    cs.trainingjobs.create(job)

    samples = []
    ok = wait_for(lambda: fully_running(cs, "bench", 0), timeout=60)
    if not ok:
        print(json.dumps({"metric": "elastic_recovery_p50", "value": None,
                          "unit": "s", "vs_baseline": None,
                          "error": "job never reached Running"}))
        return 1

    for trial in range(TRIALS):
        victim = f"bench-worker-{trial % WORKERS}"
        t0 = time.time()
        rt.preempt_pod("default", victim)
        if not wait_for(lambda: fully_running(cs, "bench", trial + 1), timeout=60):
            continue
        samples.append(time.time() - t0)

    tc.stop()
    rt.stop()

    if not samples:
        print(json.dumps({"metric": "elastic_recovery_p50", "value": None,
                          "unit": "s", "vs_baseline": None,
                          "error": "no successful recovery trials"}))
        return 1

    p50 = statistics.median(samples)
    print(json.dumps({
        "metric": "elastic_recovery_p50",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(90.0 / p50, 1),
        "samples": [round(s, 4) for s in samples],
        "trials": TRIALS,
        "workers": WORKERS,
        "note": "preempt (SIGKILL) -> job fully Running again; real controller"
                " + subprocess workers; reference target <90s (BASELINE.md)",
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
