#!/usr/bin/env python
"""Benchmarks: single-chip training MFU + elastic recovery (control plane
and full workload).

Prints exactly ONE JSON line.  Primary metric (on TPU): Llama train-step MFU
vs the v5e bf16 peak (197 TF/s), with a Pallas-vs-XLA attention A/B --
``vs_baseline`` is the Pallas/XLA step-time speedup at the largest config
both paths can run (the Pallas kernel's headline config OOMs the XLA path:
materializing [B,H,T,T] scores needs ~4x more HBM than the chip has).
Off TPU the primary falls back to the control-plane elastic-recovery p50
(round-1 metric); the full-workload recovery (preempt -> training step
completes at the new width, incl. JAX re-init + mesh rebuild + orbax
restore) is measured on the localproc backend either way.

The reference publishes no numbers (BASELINE.md); recovery targets come from
BASELINE.json's <90 s north star.
"""

import functools
import json
import math
import os
import re
import statistics
import sys
import time

# ---------------------------------------------------------------------------
# Part 1: single-chip training throughput / MFU (VERDICT round 1, item 2)
# ---------------------------------------------------------------------------

V5E_PEAK_BF16 = 197e12  # FLOP/s
PEAKS = {"TPU v5 lite": V5E_PEAK_BF16, "TPU v5e": V5E_PEAK_BF16,
         "TPU v4": 275e12, "TPU v6": 918e12}


def _chip_peak():
    import jax

    kind = jax.devices()[0].device_kind
    for prefix, peak in PEAKS.items():
        if kind.startswith(prefix):
            return peak
    return V5E_PEAK_BF16


def train_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Model FLOPs (fwd+bwd): 6N per token for the matmuls plus causal
    attention's 12*L*T*D/2 per token.  Remat recompute is NOT counted (MFU
    convention: useful model FLOPs over peak)."""
    n = __import__("trainingjob_operator_tpu.models.llama",
                   fromlist=["num_params"]).num_params(cfg)
    return 6.0 * n * batch * seq + 6.0 * cfg.n_layers * batch * seq * seq * cfg.dim


def _timed_loop(step, params, opt, tokens, steps, min_plausible_s=0.0):
    """Guarded step-timing loop shared by every train bench leg.

    NOTE: jax.block_until_ready does NOT wait for device execution on the
    axon PJRT runtime (tools/repro_block_until_ready.py: 0.024 ms/step
    "measured" vs ~70-90 ms real).  A device-to-host transfer of the loss
    scalar is the only reliable fence: it cannot complete before every
    step it depends on has executed.
    """
    params, opt, l = step(params, opt, tokens)  # compile
    for _ in range(2):                          # warmup
        params, opt, l = step(params, opt, tokens)
    # analyzer: allow[host-sync-in-hot-loop] the D2H read IS the fence this
    # harness depends on (block_until_ready does not wait on this runtime;
    # see the docstring) -- it runs once per timing leg, not per step.
    float(l)  # d2h fence; see note above

    def timed(n):
        nonlocal params, opt, l
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt, l = step(params, opt, tokens)
        # analyzer: allow[host-sync-in-hot-loop] deliberate timing fence,
        # once per measured window of n steps (not per step); the only
        # reliable sync on this runtime per the _timed_loop docstring.
        float(l)  # forced sync
        return (time.perf_counter() - t0) / n

    # Scaling cross-check: per-step time from N and 3N steps must agree,
    # else the harness is measuring dispatch, not execution.
    t_a = timed(steps)
    t_b = timed(steps * 3)
    if not (0.5 < t_a / t_b < 2.0):
        raise RuntimeError(
            f"timing does not scale with step count "
            f"({t_a * 1e3:.2f} ms/step at {steps} steps vs "
            f"{t_b * 1e3:.2f} at {steps * 3}): harness is broken")
    if t_b < min_plausible_s:
        # Absolute floor (= model FLOPs at 100% of chip peak): catches a
        # fence that silently stops synchronizing, which the relative
        # scaling check alone cannot (both runs would measure dispatch).
        raise RuntimeError(
            f"step time {t_b * 1e3:.3f} ms below the physical floor "
            f"{min_plausible_s * 1e3:.3f} ms: harness is not synchronizing")
    return t_b  # longer run: better amortization of host overhead


def _timed_train(model, cfg, batch, seq, steps, donate=True,
                 min_plausible_s=0.0, remat=True):
    """One timing rig for every model family: identical optimizer, ce_chunk
    handling, and fence protocol, so the llama and moe numbers stay
    comparable by construction.  ``model`` is the family module (must
    expose ``init_params`` and ``loss_fn(params, batch, cfg, remat=,
    ce_chunk=)``)."""
    import jax
    import optax

    params = model.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
    opt = tx.init(params)

    ce_chunk = int(os.environ.get("TRAININGJOB_CE_CHUNK", "0") or 0)

    @functools.partial(jax.jit, donate_argnums=(0, 1) if donate else ())
    def step(p, o, tokens):
        l, grads = jax.value_and_grad(
            lambda pp: model.loss_fn(pp, {"tokens": tokens}, cfg,
                                     remat=remat, ce_chunk=ce_chunk))(p)
        updates, o2 = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o2, l

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)
    return _timed_loop(step, params, opt, tokens, steps, min_plausible_s)


def _timed_steps(cfg, batch, seq, steps, donate=True, min_plausible_s=0.0,
                 remat=True):
    from trainingjob_operator_tpu.models import llama

    return _timed_train(llama, cfg, batch, seq, steps, donate=donate,
                        min_plausible_s=min_plausible_s, remat=remat)


def moe_train_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Model FLOPs for an MoE step on the ACTIVE-parameter basis (6N_active
    per token + causal attention), the standard MoE MFU convention: the
    dense-dispatch einsums and dropped-token slack are NOT credited, so
    routing overhead shows up as lower MFU instead of being graded away."""
    from trainingjob_operator_tpu.models import moe

    a = moe.active_params(cfg)
    return (6.0 * a * batch * seq
            + 6.0 * cfg.n_layers * batch * seq * seq * cfg.dim)


def _timed_steps_moe(cfg, batch, seq, steps, min_plausible_s=0.0,
                     remat=True):
    from trainingjob_operator_tpu.models import moe

    return _timed_train(moe, cfg, batch, seq, steps,
                        min_plausible_s=min_plausible_s, remat=remat)


def bench_train():
    import jax

    from trainingjob_operator_tpu.models import llama

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # Chip-saturating single-chip config (~785M params, seq 2048): fits
        # 16 GB HBM with remat + donation + the Pallas flash kernel.
        cfg = llama.LlamaConfig(vocab_size=32000, dim=2048, n_layers=12,
                                n_heads=16, n_kv_heads=16, ffn_dim=6144,
                                max_seq_len=2048)
        batch, seq, steps = 8, 2048, 10
        ab_batch = 2  # largest batch the XLA-attention path can also run
        peak = _chip_peak()
    else:
        cfg = llama.LlamaConfig.tiny()
        batch, seq, steps, ab_batch, peak = 2, 128, 3, 2, None

    os.environ["TRAININGJOB_PALLAS"] = "auto"
    flops = train_flops_per_step(cfg, batch, seq)
    floor = flops / peak if peak else 0.0
    # Policy ladder: "attn" saves the flash kernel's residuals so the
    # backward skips re-running the quadratic attention forward (~one extra
    # [B, T, D] + lse per layer of HBM); if that does not fit, fall back to
    # full remat (the round-4 measured 42.3% MFU configuration).
    t_step = None
    remat_policy = None
    for pol in (["attn", "full"] if on_tpu else ["full"]):
        try:
            t_step = _timed_steps(cfg, batch, seq, steps, remat=pol,
                                  min_plausible_s=floor)
            remat_policy = pol
            break
        except Exception as exc:
            # Only an OOM downgrades the ladder; anything else -- above all
            # _timed_steps' own harness-integrity RuntimeErrors (broken
            # fence, scaling mismatch) -- must fail loudly, not be masked
            # by a retry at the next policy.
            msg = str(exc)
            if ("RESOURCE_EXHAUSTED" not in msg
                    and "out of memory" not in msg.lower()):
                raise
            last_exc = exc
    if t_step is None:
        raise last_exc
    mfu = flops / t_step / peak * 100 if peak else None
    if mfu is not None and not (0.0 < mfu < 100.0):
        # A physically impossible number must fail loudly, never be the
        # headline metric (VERDICT r3).
        raise RuntimeError(
            f"implausible MFU {mfu:.1f}% (step {t_step * 1e3:.3f} ms): "
            f"timing harness is not synchronizing")
    result = {
        "platform": jax.devices()[0].device_kind,
        "params_m": round(llama.num_params(cfg) / 1e6, 1),
        "batch": batch, "seq": seq,
        "step_ms": round(t_step * 1e3, 1),
        "tokens_per_s": round(batch * seq / t_step),
        "model_tflops_per_step": round(flops / 1e12, 1),
        "mfu_pct": round(mfu, 1) if mfu is not None else None,
        "remat_policy": remat_policy,
    }

    # Pallas vs XLA attention A/B at a size both fit.
    ab_floor = (train_flops_per_step(cfg, ab_batch, seq) / peak
                if peak else 0.0)
    os.environ["TRAININGJOB_PALLAS"] = "auto"
    t_pallas = _timed_steps(cfg, ab_batch, seq, steps,
                            min_plausible_s=ab_floor)
    os.environ["TRAININGJOB_PALLAS"] = "off"
    try:
        t_xla = _timed_steps(cfg, ab_batch, seq, steps,
                             min_plausible_s=ab_floor)
    except Exception as exc:  # XLA path OOMs even at the A/B size
        t_xla = None
        result["xla_attention_error"] = type(exc).__name__
    os.environ["TRAININGJOB_PALLAS"] = "auto"
    result["ab_batch"] = ab_batch
    result["step_ms_pallas_ab"] = round(t_pallas * 1e3, 1)
    result["step_ms_xla_ab"] = round(t_xla * 1e3, 1) if t_xla else None
    result["pallas_speedup"] = (round(t_xla / t_pallas, 3) if t_xla else None)

    # Secondary legs ride along but never sink the headline number.
    for name, leg in (("moe", bench_moe), ("decode", bench_decode),
                      ("serving", bench_serving)):
        try:
            result[name] = leg(on_tpu)
        except Exception as exc:
            result[name] = {"error": f"{type(exc).__name__}: "
                                     f"{str(exc)[:300]}"}
    return result


def bench_moe(on_tpu: bool):
    """MoE train-step MFU on the active-params FLOPs basis (VERDICT r4 #3).

    Routing is whole-sequence, the only mode left: the ``router_group``
    knob and its A/B were removed after BENCH_r05 measured grouped routing
    at 0.994x (a no-op -- XLA already fuses the dense-dispatch einsums at
    bench shapes; rationale in models/moe.py ``_moe_mlp``).
    """
    import dataclasses

    from trainingjob_operator_tpu.models import moe

    if on_tpu:
        # ~650M total / ~210M active params: E=8 experts at mixtral-like
        # ratios, sized for 16 GB v5e HBM with remat + donation.
        cfg = moe.MoEConfig(vocab_size=32000, dim=1024, n_layers=6,
                            n_heads=16, n_kv_heads=8, ffn_dim=2816,
                            n_experts=8, experts_per_token=2,
                            max_seq_len=2048)
        batch, seq, steps = 8, 2048, 5
        peak = _chip_peak()
    else:
        cfg = moe.MoEConfig.tiny()
        cfg = dataclasses.replace(cfg, max_seq_len=128)
        batch, seq, steps, peak = 2, 64, 3, None

    flops = moe_train_flops_per_step(cfg, batch, seq)
    floor = flops / peak if peak else 0.0
    t_step = None
    for pol in (["attn", "full"] if on_tpu else ["full"]):
        try:
            t_step = _timed_steps_moe(cfg, batch, seq, steps, remat=pol,
                                      min_plausible_s=floor)
            remat_policy = pol
            break
        except Exception as exc:
            msg = str(exc)
            if ("RESOURCE_EXHAUSTED" not in msg
                    and "out of memory" not in msg.lower()):
                raise
            last_exc = exc
    if t_step is None:
        raise last_exc
    mfu = flops / t_step / peak * 100 if peak else None
    if mfu is not None and not (0.0 < mfu < 100.0):
        raise RuntimeError(f"implausible MoE MFU {mfu:.1f}%")
    result = {
        "params_m": round(moe.num_params(cfg) / 1e6, 1),
        "active_params_m": round(moe.active_params(cfg) / 1e6, 1),
        "batch": batch, "seq": seq,
        "step_ms": round(t_step * 1e3, 1),
        "tokens_per_s": round(batch * seq / t_step),
        "active_tflops_per_step": round(flops / 1e12, 2),
        "mfu_pct": round(mfu, 1) if mfu is not None else None,
        "remat_policy": remat_policy,
    }
    return result


def bench_decode(on_tpu: bool):
    """Serving-side numbers (VERDICT r4 #6): prefill tokens/s and per-token
    decode latency, with the int8 crossover table over batch 1/2/4/8.

    ``generate(steps)`` costs prefill + (steps-1) decode steps; timing two
    step counts isolates the two components without trusting any in-loop
    fence (the d2h transfer of the sampled tokens is the sync point).
    """
    import jax
    import numpy as np

    from trainingjob_operator_tpu.models import decode, llama

    if on_tpu:
        cfg = llama.LlamaConfig(vocab_size=32000, dim=2048, n_layers=12,
                                n_heads=16, n_kv_heads=16, ffn_dim=6144,
                                max_seq_len=2048)
        prompt_len, s_a, s_b = 512, 32, 96
        batches = (1, 2, 4, 8)
    else:
        cfg = llama.LlamaConfig.tiny()
        prompt_len, s_a, s_b = 16, 4, 12
        batches = (1, 8)

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    out = {}
    for batch in batches:
        prompt = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, prompt_len), 0, cfg.vocab_size)
        max_len = prompt_len + s_b

        @functools.partial(jax.jit, static_argnums=(2, 3))
        def gen(p, t, steps, quantize):
            return decode.generate(p, t, cfg, steps=steps, max_len=max_len,
                                   quantize=quantize)

        def timed(steps, quantize=False, reps=3):
            np.asarray(gen(params, prompt, steps, quantize))  # compile+fence
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                np.asarray(gen(params, prompt, steps, quantize))
                best = min(best, time.perf_counter() - t0)
            return best

        t_a, t_b = timed(s_a), timed(s_b)
        per_tok = (t_b - t_a) / (s_b - s_a)
        prefill_s = max(t_a - (s_a - 1) * per_tok, 1e-9)
        if per_tok <= 0:
            out[f"batch{batch}"] = {"error": "decode timing not scaling "
                                             "with step count"}
            continue
        leg = {
            "prompt_len": prompt_len,
            "prefill_tokens_per_s": round(batch * prompt_len / prefill_s),
            "decode_ms_per_token": round(per_tok * 1e3, 2),
            "decode_tokens_per_s": round(batch / per_tok),
        }
        # Weight-only int8 A/B (models/quant.py): decode streams every
        # weight per token, so int8 halves the HBM bytes that bound it.
        # Since ``qmatmul`` fused the scale into the dot's epilogue the
        # win holds at EVERY batch (the old dequant materialization made
        # it REGRESS past batch 4 -- BENCH_r05 int8_speedup: 0.881 at 8);
        # the per-batch crossover table below is the regression gate.
        try:
            q_a, q_b = timed(s_a, quantize=True), timed(s_b, quantize=True)
            q_tok = (q_b - q_a) / (s_b - s_a)
            if q_tok > 0:
                leg["decode_ms_per_token_int8"] = round(q_tok * 1e3, 2)
                leg["int8_speedup"] = round(per_tok / q_tok, 3)
            else:
                leg["int8_error"] = "timing not scaling with step count"
        except Exception as exc:
            leg["int8_error"] = f"{type(exc).__name__}: {str(exc)[:200]}"
        if on_tpu and leg.get("int8_speedup", 1.0) < 1.0:
            # The whole point of scale-after-accumulate is that int8 never
            # loses to fp; a sub-1.0 point at any batch means the fusion
            # regressed -- fail the bench rather than ship a lie.
            # (Asserted on TPU only: CPU tiny-config decode differences
            # sit inside timer noise.)
            raise RuntimeError(
                f"int8_speedup {leg['int8_speedup']} < 1.0 at batch "
                f"{batch}: the qmatmul scale-after-accumulate fusion "
                f"has regressed")
        out[f"batch{batch}"] = leg
    return out


def bench_serving(on_tpu: bool):
    """Continuous batching vs static re-prefill batching, same open-loop
    trace (workloads/serve.py; docs/SERVING.md).

    Mixed output lengths are what make the win STRUCTURAL: a static batch
    runs to its slowest member while finished rows idle, continuous
    batching re-pages freed slots immediately.  Both arms run the same
    fixed-shape executables, so the tokens/s ratio tracks the
    scheduling-efficiency ratio and the >=1.5x gate is assertable on CPU
    timer noise notwithstanding.  Greedy decode + deterministic traffic
    also lets each arm self-check slot paging: identical requests must
    decode identically from whatever slot they land in
    (count_stale_kv_violations), gated at zero.
    """
    import jax

    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.workloads import serve

    # Big enough that the batched decode step dominates per-tick dispatch
    # overhead (tiny-config steps are dispatch-bound on CPU and would
    # measure the Python scheduler, not the batching policy).
    cfg = llama.LlamaConfig(vocab_size=512, dim=128, n_layers=4, n_heads=4,
                            n_kv_heads=2, ffn_dim=256, max_seq_len=128)
    n_requests, slots = (96, 8) if on_tpu else (64, 8)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    # Bimodal budgets (75% short completions, 25% long generations): the
    # chat-vs-completion shape real traffic has.  One long request strands
    # a static batch of short ones -- the straggler cost continuous
    # batching exists to remove.
    traffic = serve.synthetic_traffic(
        n_requests, seed=7, rate=2.0, vocab=cfg.vocab_size,
        prompt_lens=(4, 16), out_tokens=(2, 16),
        long_frac=0.25, long_out_tokens=(64, 96))

    result = {"requests": n_requests, "slots": slots}
    for policy in ("continuous", "static"):
        svc = serve.DecodeService(params, cfg, slots=slots,
                                  prefill_chunk=16,
                                  queue_cap=max(n_requests, 64),
                                  policy=policy)
        svc.warmup()  # compile outside the timed window
        stats = serve.run_traffic(svc, traffic)["stats"]
        if stats["stale_kv_violations"]:
            raise RuntimeError(
                f"{policy}: {stats['stale_kv_violations']} stale-KV "
                f"violations -- slot paging leaked state across requests")
        result[policy] = {
            "aggregate_tokens_per_sec": stats["aggregate_tokens_per_sec"],
            "token_latency_ms_p50": stats["token_latency_ms_p50"],
            "token_latency_ms_p99": stats["token_latency_ms_p99"],
            "ttft_ms_p50": stats["ttft_ms_p50"],
            "scheduler_ticks": stats["steps"],
            "completed": stats["completed_total"],
        }
    cont = result["continuous"]["aggregate_tokens_per_sec"]
    stat = result["static"]["aggregate_tokens_per_sec"]
    result["continuous_vs_static_speedup"] = round(cont / max(stat, 1e-9), 2)
    if result["continuous_vs_static_speedup"] < 1.5:
        # The headline claim of the serving plane; a miss means the
        # scheduler stopped re-paging freed slots (or started stalling the
        # batch on prefill) -- fail loudly, on every platform.
        raise RuntimeError(
            f"continuous batching {result['continuous_vs_static_speedup']}x "
            f"vs static (< 1.5x): slot reuse is not delivering")
    return result


# ---------------------------------------------------------------------------
# Part 2: control-plane elastic recovery (round-1 metric, kept)
# ---------------------------------------------------------------------------

def bench_recovery_control_plane(trials=5, workers=4):
    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.api.types import (
        ReplicaSpec,
        RestartPolicy,
        RestartScope,
        TPUTrainingJob,
        TrainingJobPhase,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import TrainingJobController
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime

    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    rt = LocalProcRuntime(cs, nodes=2, termination_grace=1.0,
                          log_dir="/tmp/tpu-trainingjob-bench-logs")
    rt.start()
    tc.run(workers=2)

    job = TPUTrainingJob(metadata=ObjectMeta(name="bench", namespace="default"))
    job.spec.replica_specs["worker"] = ReplicaSpec(
        replicas=workers,
        restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
        restart_scope=RestartScope.ALL,
        template=PodTemplateSpec(spec=PodSpec(containers=[
            Container(name="aitj-worker",
                      command=[sys.executable, "-c",
                               "import time; time.sleep(600)"],
                      ports=[ContainerPort(name="aitj-7900",
                                           container_port=7900)])])))
    job.spec.restarting_exit_code = "137,143"
    cs.trainingjobs.create(job)

    def fully_running(expect_restarts):
        j = cs.trainingjobs.get("default", "bench")
        if j.status.phase != TrainingJobPhase.RUNNING:
            return False
        pods = cs.pods.list("default")
        return len(pods) == workers and all(
            p.metadata.labels.get(constants.RESTART_COUNT_LABEL)
            == str(expect_restarts) and p.status.phase == "Running"
            for p in pods)

    samples = []
    try:
        if not _wait(lambda: fully_running(0), 60):
            return {"error": "job never reached Running"}
        for trial in range(trials):
            victim = f"bench-worker-{trial % workers}"
            t0 = time.time()
            rt.preempt_pod("default", victim)
            if _wait(lambda: fully_running(trial + 1), 60):
                samples.append(time.time() - t0)
        # Tear the job down BEFORE stopping: otherwise its workers keep
        # restart-thrashing between the last measurement and shutdown,
        # burning wall-clock and burying the log (VERDICT r3 Weak #8).
        cs.trainingjobs.delete("default", "bench")
        _wait(lambda: not cs.pods.list("default"), 10)
    finally:
        tc.stop()
        rt.stop()
    if not samples:
        return {"error": "no successful recovery trials"}
    return {"p50_s": round(statistics.median(samples), 4),
            "samples": [round(s, 4) for s in samples], "workers": workers}


# ---------------------------------------------------------------------------
# Part 2b: fleet control plane -- keyed parallel reconcile throughput
# ---------------------------------------------------------------------------

def bench_control_plane(jobs=120, api_latency=0.005):
    """Reconcile throughput of the keyed parallel workqueue engine under a
    backlog, thread_num=8 vs the single-worker baseline.

    The fleet harness fires a seeded all-completing schedule with pacing off
    (every create lands immediately -> the queue saturates) and injects
    ``api_latency`` per controller *write* -- the realistic regime where the
    GIL does not serialize workers, because reconciles overlap API round
    trips rather than bytecode.  Identical seed/profile for both runs; the
    speedup is the reconciles/s ratio to convergence.
    """
    from trainingjob_operator_tpu.fleet.churn import (
        FATE_COMPLETE,
        ChurnProfile,
    )
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    profile = ChurnProfile(jobs=jobs, duration=1.0, seed=0, replicas=(1, 2),
                           run_seconds=(0.05, 0.15),
                           fate_weights={FATE_COMPLETE: 1.0})
    runs = {}
    for workers in (1, 8):
        harness = FleetHarness(
            profile, workers=workers, pace=False, api_latency=api_latency,
            resync_period=30.0, gc_interval=30.0, converge_timeout=300.0)
        runs[workers] = harness.run()
    base, par = runs[1], runs[8]
    speedup = (round(par.reconciles_per_s / base.reconciles_per_s, 2)
               if base.reconciles_per_s > 0 else None)
    return {
        "jobs": jobs,
        "api_latency_ms": api_latency * 1000.0,
        "control_plane_reconciles_per_s": round(par.reconciles_per_s, 2),
        "single_worker_reconciles_per_s": round(base.reconciles_per_s, 2),
        "keyed_parallel_speedup": speedup,
        "event_to_visible_ms_p50": par.event_to_visible_ms["p50"],
        "event_to_visible_ms_p99": par.event_to_visible_ms["p99"],
        "workqueue_depth_high_water": par.workqueue_depth_high_water,
        "workqueue_retries_total": par.workqueue_retries_total,
        "workqueue_coalesced_total": par.workqueue_coalesced_total,
        "converged": base.converged and par.converged,
    }


def bench_control_plane_chaos(jobs=120, api_latency=0.005):
    """Event-to-visible latency under the seeded control-plane chaos plane
    vs a fault-free baseline (docs/CHAOS.md).

    Same churn schedule both runs (identical seed/profile, paced so the
    chaos plan's time-shaped faults land mid-flight); the chaos arm rides
    API errors/timeouts/conflicts, latency spikes, watch drops and stale
    lists.  Both arms must converge with zero violations -- surviving the
    faults is the tentpole -- and the chaos p99 must stay within 3x the
    clean p99 (gate_p99_le_3x): retries and relists are allowed to cost
    latency, not availability.
    """
    from trainingjob_operator_tpu.fleet.chaos import ChaosProfile
    from trainingjob_operator_tpu.fleet.churn import ChurnProfile
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    profile = ChurnProfile(jobs=jobs, duration=3.0, seed=0, replicas=(1, 3),
                           run_seconds=(0.05, 0.25))
    runs = {}
    for arm in ("baseline", "chaos"):
        chaos = (ChaosProfile(seed=profile.seed, duration=5.0)
                 if arm == "chaos" else None)
        harness = FleetHarness(
            profile, workers=8, api_latency=api_latency,
            resync_period=30.0, gc_interval=30.0, converge_timeout=300.0,
            chaos_profile=chaos)
        runs[arm] = harness.run()
    base, chaos = runs["baseline"], runs["chaos"]
    base_p99 = base.event_to_visible_ms["p99"]
    chaos_p99 = chaos.event_to_visible_ms["p99"]
    ratio = round(chaos_p99 / base_p99, 2) if base_p99 > 0 else None
    return {
        "jobs": jobs,
        "api_latency_ms": api_latency * 1000.0,
        "baseline_p50_ms": base.event_to_visible_ms["p50"],
        "baseline_p99_ms": base_p99,
        "chaos_p50_ms": chaos.event_to_visible_ms["p50"],
        "chaos_p99_ms": chaos_p99,
        "p99_ratio": ratio,
        "gate_p99_le_3x": ratio is not None and ratio <= 3.0,
        "api_retries_total": chaos.api_retries_total,
        "chaos_faults": (chaos.chaos or {}).get("faults"),
        "informer_relists": (chaos.chaos or {}).get("informer_relists"),
        "unattributed_downtime_ms": chaos.unattributed_downtime_ms,
        "converged": base.converged and chaos.converged,
    }


def bench_node_chaos(jobs=80, flap_grace=1.0):
    """Data-plane failure domains (docs/CHAOS.md): seeded node flaps, a
    permanent node kill and a failure-domain kill against the hardened
    NODE_FAIL path, three arms on one churn schedule:

    - ``baseline``: fault-free (the detect->running reference);
    - ``undamped``: node chaos, flap grace 0 -- every transient NotReady
      fires NODE_FAIL and restarts the group;
    - ``damped``: same plan (identical digest), flap grace above the plan's
      flap durations -- transient flaps are suppressed, only real kills
      restart.

    Gates: every arm converges with zero violations and zero unattributed
    downtime; restart amplification damped/undamped strictly < 1.0 (damping
    must pay for itself); damped event-to-visible p99 within 3x the
    fault-free p99 (the grace delays NODE_FAIL by at most one flap, it must
    not sit on real recoveries).
    """
    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.fleet.chaos import ChaosProfile
    from trainingjob_operator_tpu.fleet.churn import (
        FATE_COMPLETE,
        FATE_DELETE,
        FATE_POD_FAIL,
        FATE_PREEMPT,
        FATE_STEADY,
        ChurnProfile,
    )
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    # Steady-heavy mix: node faults only amplify restarts when they land on
    # pods that are still Running, so most jobs here run until the end.
    profile = ChurnProfile(jobs=jobs, duration=3.0, seed=0, replicas=(1, 3),
                           run_seconds=(0.05, 0.25),
                           fate_weights={FATE_COMPLETE: 0.25,
                                         FATE_STEADY: 0.50,
                                         FATE_PREEMPT: 0.07,
                                         FATE_POD_FAIL: 0.12,
                                         FATE_DELETE: 0.06})
    arms = {
        "baseline": (None, 0.0),
        "undamped": ("chaos", 0.0),
        "damped": ("chaos", flap_grace),
    }
    runs = {}
    for arm, (kind, grace) in arms.items():
        chaos = (ChaosProfile(seed=profile.seed, duration=5.0,
                              node_flaps=6, node_kills=1, domain_kills=1)
                 if kind else None)
        prev = os.environ.get(constants.NODE_FLAP_GRACE_ENV)
        os.environ[constants.NODE_FLAP_GRACE_ENV] = str(grace)
        try:
            harness = FleetHarness(
                profile, workers=8, resync_period=30.0, gc_interval=30.0,
                converge_timeout=300.0, pods_per_node=8, nodes_per_slice=4,
                chaos_profile=chaos)
            runs[arm] = harness.run()
        finally:
            if prev is None:
                os.environ.pop(constants.NODE_FLAP_GRACE_ENV, None)
            else:
                os.environ[constants.NODE_FLAP_GRACE_ENV] = prev
    base, und, damp = runs["baseline"], runs["undamped"], runs["damped"]
    amplification = (round(damp.restarts_total / und.restarts_total, 3)
                     if und.restarts_total else None)
    base_p99 = base.event_to_visible_ms["p99"]
    damp_p99 = damp.event_to_visible_ms["p99"]
    ratio = round(damp_p99 / base_p99, 2) if base_p99 > 0 else None
    return {
        "jobs": jobs,
        "flap_grace_s": flap_grace,
        "plan_digest": (damp.chaos or {}).get("plan_digest"),
        "node_faults": {k: v
                        for k, v in ((damp.chaos or {}).get("faults")
                                     or {}).items()
                        if k in ("node_flap", "node_down", "domain_down")},
        "restarts_undamped": und.restarts_total,
        "restarts_damped": damp.restarts_total,
        "restart_amplification": amplification,
        "gate_amplification_lt_1": (amplification is not None
                                    and amplification < 1.0),
        "baseline_p99_ms": base_p99,
        "damped_p99_ms": damp_p99,
        "p99_ratio": ratio,
        "gate_p99_le_3x": ratio is not None and ratio <= 3.0,
        "unattributed_downtime_ms": max(r.unattributed_downtime_ms
                                        for r in runs.values()),
        "converged": all(r.converged for r in runs.values()),
    }


def bench_slo_plane(jobs=80):
    """Fleet SLO plane on/off A/B on one seeded chaos schedule
    (docs/SLO.md): the observability plane must observe, not perturb.

    Same churn + chaos profile both arms (identical seeds); the ``plane``
    arm additionally runs the tsdb sweeper, the burn-rate engine and the
    sampling span profiler.  Gates:

    - zero breaches on a healthy fleet (default objectives hold under the
      stock chaos magnitudes -- a breach here is a false positive);
    - >=90% of busy worker-thread samples attribute to spans under
      ``sync_job`` (the profiler resolves the reconcile path, not noise);
    - profiler overhead < 5% of wall (sampling must stay cheap);
    - phase counts and the chaos plan digest byte-identical plane-on vs
      plane-off (the plane cannot touch scheduling determinism).
    """
    from trainingjob_operator_tpu.fleet.chaos import ChaosProfile
    from trainingjob_operator_tpu.fleet.churn import ChurnProfile
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    profile = ChurnProfile(jobs=jobs, duration=3.0, seed=0, replicas=(1, 3),
                           run_seconds=(0.05, 0.25))
    runs = {}
    for arm in ("off", "plane"):
        harness = FleetHarness(
            profile, workers=8, resync_period=30.0, gc_interval=30.0,
            converge_timeout=300.0,
            chaos_profile=ChaosProfile(seed=profile.seed, duration=5.0),
            slo_plane=(arm == "plane"), profiler=(arm == "plane"))
        runs[arm] = harness.run()
    off, on = runs["off"], runs["plane"]
    verdicts = on.slo_verdicts or {}
    prof = on.profile_top or {}
    attribution = (prof.get("span_attribution") or {}).get("ratio")
    overhead = prof.get("overhead_ratio")
    return {
        "jobs": jobs,
        "breaches_total": verdicts.get("breaches_total"),
        "gate_zero_false_breaches": verdicts.get("breaches_total") == 0,
        "profiler_samples": prof.get("samples_total"),
        "span_attribution_ratio": attribution,
        "gate_attribution_ge_0_9": (attribution is not None
                                    and attribution >= 0.9),
        "profiler_overhead_ratio": overhead,
        "gate_overhead_lt_5pct": overhead is not None and overhead < 0.05,
        "profile_top": (prof.get("top") or [])[:3],
        "phase_counts_identical": on.phase_counts == off.phase_counts,
        "plan_digest_identical": ((on.chaos or {}).get("plan_digest")
                                  == (off.chaos or {}).get("plan_digest")),
        "converged": off.converged and on.converged,
    }


def bench_request_plane(jobs=80):
    """Request-lifecycle plane on/off A/B on one seeded chaos schedule
    (docs/SERVING.md): the dropped-request audit at fleet scale.

    Same churn + chaos profile both arms; the ``plane`` arm additionally
    annotates every job with synthetic request traffic and runs the
    ledger + reconcile audit.  Gates:

    - zero orphaned requests after the drain-boundary reconcile (every
      id submitted before a scale-in delete or exit-137 kill reached an
      explicit terminal outcome -- completed or audibly evicted);
    - every restart incident bundle that overlapped in-flight requests
      carries the ``requests`` stanza (request downtime is attributed,
      not implied);
    - phase counts and the chaos plan digest byte-identical plane-on vs
      plane-off (auditing the fleet cannot perturb it).
    """
    from trainingjob_operator_tpu.fleet.chaos import ChaosProfile
    from trainingjob_operator_tpu.fleet.churn import ChurnProfile
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    profile = ChurnProfile(jobs=jobs, duration=3.0, seed=0, replicas=(1, 3),
                           run_seconds=(0.05, 0.25))
    runs = {}
    for arm in ("off", "plane"):
        harness = FleetHarness(
            profile, workers=8, resync_period=30.0, gc_interval=30.0,
            converge_timeout=300.0,
            chaos_profile=ChaosProfile(seed=profile.seed, duration=5.0),
            request_obs=(arm == "plane"))
        runs[arm] = harness.run()
    off, on = runs["off"], runs["plane"]
    req = on.requests or {}
    bundles = req.get("incident_bundles") or 0
    stanzaed = req.get("bundles_with_requests") or 0
    return {
        "jobs": jobs,
        "records_total": req.get("records_total"),
        "orphaned_after_reconcile": req.get("orphaned_after_reconcile"),
        "gate_zero_orphans": req.get("orphaned_after_reconcile") == 0,
        "sampled_dropped_total": req.get("sampled_dropped_total"),
        "incident_bundles": bundles,
        "bundles_with_requests": stanzaed,
        "gate_restart_bundles_stanzaed": not on.violations,
        "phase_counts_identical": on.phase_counts == off.phase_counts,
        "plan_digest_identical": ((on.chaos or {}).get("plan_digest")
                                  == (off.chaos or {}).get("plan_digest")),
        "converged": off.converged and on.converged,
    }


# ---------------------------------------------------------------------------
# Part 2c: fleet sim kernel -- scan-vs-event A/B at 1k jobs
# ---------------------------------------------------------------------------

def _bench_sim_steady(pods=2000, tick=0.001, window=5.0):
    """Steady-state kubelet A/B, no controller: ``pods`` Running pods with
    far-future exits, then a fixed measurement window of nothing happening
    -- the regime a long-lived fleet spends nearly all its time in.  The
    scan kernel walks every live pod every tick (O(pods x ticks)); the
    event kernel sleeps to the next armed deadline (O(events)).  Loop CPU
    over the window is the whole difference, measured directly."""
    from trainingjob_operator_tpu.core.objects import (
        Container, ObjectMeta, Pod, PodPhase, PodSpec)
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.runtime.sim import (
        RUN_SECONDS_ANNOTATION, SimRuntime)

    out = {}
    for kernel in ("scan", "event"):
        cs = Clientset()
        sim = SimRuntime(cs, tick=tick, pods_per_node=256, kernel=kernel)
        for i in range(math.ceil(pods / 256)):
            sim.add_node(f"steady-n{i:03d}")
        for i in range(pods):
            pod = Pod(metadata=ObjectMeta(
                          name=f"steady-{i:05d}", namespace="default",
                          annotations={RUN_SECONDS_ANNOTATION: "3600"}),
                      spec=PodSpec(containers=[Container(name="aitj-main")]))
            pod.spec.node_name = f"steady-n{i // 256:03d}"
            cs.pods.create(pod)
        sim.start()
        try:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                running = sum(p.status.phase == PodPhase.RUNNING
                              for p in cs.pods.list("default"))
                if running == pods:
                    break
                time.sleep(0.2)
            else:
                raise RuntimeError(f"{kernel}: steady fleet never started")
            # All Running, every exit ~1 h out: reset the loop meters and
            # let the kernel idle through the window.
            sim.loop_passes = 0
            sim.loop_cpu_seconds = 0.0
            time.sleep(window)
            out[kernel] = {"cpu_seconds": sim.loop_cpu_seconds,
                           "loop_passes": sim.loop_passes}
        finally:
            sim.stop()
    return out


def bench_fleet_sim(jobs=1000):
    """Scan-vs-event sim kernel A/B at 1k jobs (docs/FLEET.md), two legs.

    Leg 1 -- the full fleet: one seeded churn schedule through the real
    controller + sim cluster, once per kernel, pacing off (backlog
    saturation), sim tick 1 ms (the event kernel fires at exact deadlines
    regardless of tick, so matching its timing fidelity charges the scan
    kernel its honest price).  Reports reconciles/s, sim events/s, and
    convergence wall per kernel; the same seed must converge to
    byte-identical phase counts under both (the determinism contract of
    the discrete-event refactor).

    Leg 2 -- steady state: the same replica count parked Running with
    far-future exits, no controller, measuring kubelet loop CPU over a
    fixed window.  This is where the 5x gate lives: a converged fleet is
    almost always in this regime, and the scan kernel still pays the full
    per-tick walk for it while the event kernel sleeps.  Gate:
    scan-kernel steady-state loop CPU >= 5x the event kernel's (i.e. the
    event kernel reconciles the same steady fleet on <= 1/5 the CPU).
    ``TRAININGJOB_SIM_KERNEL=scan`` remains the CLI escape hatch for
    one-off A/Bs outside bench.
    """
    from trainingjob_operator_tpu.fleet.churn import ChurnProfile
    from trainingjob_operator_tpu.fleet.harness import FleetHarness

    profile = ChurnProfile(jobs=jobs, duration=6.0, seed=0, replicas=(1, 3),
                           run_seconds=(0.05, 0.25))
    runs = {}
    for kernel in ("scan", "event"):
        harness = FleetHarness(
            profile, workers=4, pace=False, resync_period=30.0,
            gc_interval=30.0, converge_timeout=1200.0, sim_tick=0.001,
            sim_kernel=kernel)
        runs[kernel] = harness.run()
    scan, event = runs["scan"], runs["event"]

    steady = _bench_sim_steady(pods=event.replicas_total)
    cpu_speedup = (round(steady["scan"]["cpu_seconds"]
                         / steady["event"]["cpu_seconds"], 1)
                   if steady["event"]["cpu_seconds"] > 0 else None)
    return {
        "jobs": jobs,
        "replicas_total": event.replicas_total,
        "event_reconciles_per_s": round(event.reconciles_per_s, 2),
        "scan_reconciles_per_s": round(scan.reconciles_per_s, 2),
        "event_sim_events_per_s": round(event.sim_events_per_s, 2),
        "event_wall_seconds": round(event.wall_seconds, 3),
        "scan_wall_seconds": round(scan.wall_seconds, 3),
        "wall_speedup": (round(scan.wall_seconds / event.wall_seconds, 2)
                         if event.wall_seconds > 0 else None),
        "phase_counts": event.phase_counts,
        "phase_counts_identical": event.phase_counts == scan.phase_counts,
        "converged": scan.converged and event.converged,
        "steady_scan_cpu_seconds": round(steady["scan"]["cpu_seconds"], 3),
        "steady_event_cpu_seconds": round(steady["event"]["cpu_seconds"], 3),
        "steady_cpu_speedup": cpu_speedup,
        "gate_speedup_ge_5x": cpu_speedup is not None and cpu_speedup >= 5.0,
    }


# ---------------------------------------------------------------------------
# Part 3: FULL-workload recovery (VERDICT round 1, item 4): preempt a worker
# of a real JAX job and time preempt -> a training step completes at the new
# width -- includes process restart, JAX re-init, mesh rebuild, orbax restore.
# ---------------------------------------------------------------------------

def bench_recovery_full(trials=3):
    import tempfile

    from trainingjob_operator_tpu.api.types import (
        EdlPolicy,
        ReplicaSpec,
        RestartPolicy,
        RestartScope,
        TPUTrainingJob,
        TrainingJobPhase,
    )
    from trainingjob_operator_tpu.client.clientset import Clientset
    from trainingjob_operator_tpu.cmd.options import OperatorOptions
    from trainingjob_operator_tpu.controller.controller import TrainingJobController
    from trainingjob_operator_tpu.core.objects import (
        Container,
        ContainerPort,
        EnvVar,
        ObjectMeta,
        PodSpec,
        PodTemplateSpec,
    )
    from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime

    samples = []
    trial_notes = []
    for trial in range(trials):
        ckpt_dir = tempfile.mkdtemp(prefix="bench-ckpt-")
        log_dir = tempfile.mkdtemp(prefix="bench-logs-")
        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        # Grace is a ceiling, not a wait: survivors exit as soon as their
        # SIGTERM preemption checkpoint commits (train.GracefulShutdown).
        rt = LocalProcRuntime(cs, nodes=2, termination_grace=10.0,
                              log_dir=log_dir, pods_per_node=1)
        rt.start()
        tc.run(workers=2)
        try:
            job = TPUTrainingJob(metadata=ObjectMeta(name="full",
                                                     namespace="default"))
            job.spec.replica_specs["worker"] = ReplicaSpec(
                replicas=2, min_replicas=1, edl_policy=EdlPolicy.AUTO,
                restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
                restart_scope=RestartScope.ALL,
                template=PodTemplateSpec(spec=PodSpec(containers=[Container(
                    name="aitj-worker",
                    command=[sys.executable, "-m",
                             "trainingjob_operator_tpu.workloads.llama_elastic"],
                    env=[EnvVar("LLAMA_STEPS", "100000"),
                         EnvVar("LLAMA_CKPT_EVERY", "5"),
                         EnvVar("LLAMA_BATCH", "8"),
                         EnvVar("LLAMA_SEQ", "64"),
                         # The honored platform knob: a site hook pins the
                         # axon TPU platform at interpreter start, so a bare
                         # JAX_PLATFORMS env var is ignored;
                         # apply_platform_override's config update wins.
                         EnvVar("TRAININGJOB_JAX_PLATFORM", "cpu"),
                         EnvVar("TRAININGJOB_CHECKPOINT_DIR", ckpt_dir)],
                    ports=[ContainerPort(name="aitj-7900",
                                         container_port=7900)])])))
            job.spec.restarting_exit_code = "137,143"
            cs.trainingjobs.create(job)

            import glob

            def log_files():
                return sorted(glob.glob(
                    os.path.join(log_dir, "*full-worker-*.log")))

            def read_after(offsets):
                # Only bytes appended after the recorded offsets: a restart
                # that happened BEFORE the preemption must not satisfy the
                # recovery predicate (VERDICT r3 Weak #2 -- the 6 ms sample).
                out = []
                for p in log_files():
                    with open(p) as f:
                        f.seek(offsets.get(p, 0))
                        out.append(f.read())
                return "".join(out)

            # Wait until training made progress (a checkpoint exists).
            if not _wait(lambda: re.search(r"step \d+/", read_after({})),
                         timeout=120):
                samples.append(None)
                continue
            time.sleep(1.0)  # let a checkpoint land

            pre_restarts = len(re.findall(r"restart_count=|resumed at step",
                                          read_after({})))

            # Preempt: kill node 1 (its worker dies; elastic shrink to 1).
            offsets = {p: os.path.getsize(p) for p in log_files()}
            t0 = time.time()
            nodes = sorted({p.spec.node_name
                            for p in cs.pods.list("default")})
            rt.fail_node(nodes[-1])

            def resumed_and_stepped():
                log = read_after(offsets)
                m = re.search(r"resumed at step (\d+)", log)
                if not m:
                    return False
                resumed = int(m.group(1))
                # A step strictly after the resume point completed.
                return any(int(s) > resumed for s in
                           re.findall(r"step (\d+)/", log))

            if _wait(resumed_and_stepped, timeout=120):
                samples.append(round(time.time() - t0, 3))
            else:
                samples.append(None)
            if pre_restarts:
                # Surface unexpected pre-preemption churn instead of letting
                # it silently corrupt the measurement.
                trial_notes.append(
                    f"trial {trial}: {pre_restarts} restart marker(s) "
                    f"before preemption")
        finally:
            tc.stop()
            rt.stop()
    ok = [s for s in samples if s is not None]
    if not ok:
        return {"error": "no successful full-recovery trials",
                "samples": samples, "trial_notes": trial_notes}
    return {"p50_s": statistics.median(ok), "samples": samples,
            "trial_notes": trial_notes,
            "note": "preempt -> llama step completes at new width "
                    "(restart + JAX re-init + mesh rebuild + orbax restore), "
                    "CPU localproc; predicate matches only post-preemption "
                    "log bytes"}


def bench_time_to_resume_training(detect_reschedule_s=None):
    """``time_to_resume_training`` scoreboard at >=100M params: every phase
    between the preemption and the next optimizer step, itemized, with the
    overlapped-resume A/B (ISSUE 7 tentpole; supersedes VERDICT r4 #4's
    two-run compile-cache delta).

    Three direct llama_elastic runs at the 124M config (CPU, no operator --
    the control-plane detect+reschedule half is measured separately by
    bench_recovery_control_plane and passed in as ``detect_reschedule_s``):

    - run 1 (COLD): fresh checkpoint dir, trains 2 steps; its
      ``first_step_s`` is trace + cold XLA compile, and it seeds both the
      checkpoint and the persistent compile cache
      (TRAININGJOB_COMPILE_CACHE_DIR).
    - run 2 (WARM, FAST PATH): the defaults -- the restore thread rebuilds
      state from run 1's flat resume image (one sequential read + one
      device_put pass, no tensorstore reassembly) while the compile thread
      loads the executable snapshot run 1 stored (no trace/lower/compile,
      docs/RECOVERY.md).  Its ckpt_stall line measures the snapshot-donate
      d2h copy.
    - run 3 (WARM, SERIAL): TRAININGJOB_RESUME_OVERLAP=0 -- the legacy
      resume pipeline: full orbax restore, THEN trace + AOT compile through
      the HLO-level cache (no resume image, no executable snapshot);
      resume_phases_wall_s ~= restore + compile.  Also runs with
      TRAININGJOB_CKPT_SNAPSHOT=0, so its ckpt_stall line measures the
      synchronous save handoff (placed last so its imageless checkpoint
      never feeds a later restore).

    overlap_speedup = serial (restore_s + compile_s) / overlapped wall:
    what the overlap buys on exactly the two phases it overlaps.

    Skip with TRAININGJOB_BENCH_SKIP_BIG=1 (the cold compile alone is
    minutes on a small host).
    """
    import subprocess
    import tempfile

    if os.environ.get("TRAININGJOB_BENCH_SKIP_BIG") == "1":
        return {"skipped": True}
    ckpt = tempfile.mkdtemp(prefix="bench-ckpt124-")
    base_env = dict(os.environ, LLAMA_CONFIG="124m", LLAMA_CKPT_EVERY="2",
                    LLAMA_BATCH="2", LLAMA_SEQ="64",
                    TRAININGJOB_JAX_PLATFORM="cpu",
                    TRAININGJOB_CHECKPOINT_DIR=ckpt,
                    # Exercise the job-survivable cache knob: all three
                    # runs share one cache dir, as restarted jobs would.
                    TRAININGJOB_COMPILE_CACHE_DIR=os.path.join(
                        ckpt, "compile-cache"))

    def run(steps: int, timeout: float, overlap: bool):
        env = dict(base_env, LLAMA_STEPS=str(steps),
                   TRAININGJOB_RESUME_OVERLAP="1" if overlap else "0",
                   TRAININGJOB_CKPT_SNAPSHOT="1" if overlap else "0")
        t0 = time.perf_counter()
        # CPU-only child (TRAININGJOB_JAX_PLATFORM=cpu): safe to TERM on
        # timeout, it can never hold the TPU tunnel.
        proc = subprocess.run(
            [sys.executable, "-m",
             "trainingjob_operator_tpu.workloads.llama_elastic"],
            env=env, capture_output=True, text=True, timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"llama_elastic rc={proc.returncode}: "
                               f"{(proc.stderr or proc.stdout)[-300:]}")
        comp = {k: float(v) for k, v in
                re.findall(r"(\w+_s)=([0-9.]+)", proc.stdout)}
        m = re.search(r"ckpt_stall mode=(\w+) n=(\d+) "
                      r"avg_ms=([0-9.]+) max_ms=([0-9.]+)", proc.stdout)
        stall = ({"mode": m.group(1), "n": int(m.group(2)),
                  "avg_ms": float(m.group(3)), "max_ms": float(m.group(4))}
                 if m else None)
        return time.perf_counter() - t0, comp, stall

    # Run order matters: the fast-path warm run must RESTORE a checkpoint
    # written by the snapshot pipeline, so the resume image exists beside
    # the orbax commit (docs/RECOVERY.md).  The legacy serial baseline
    # (sync save, no image, orbax restore) runs LAST: its imageless
    # checkpoint never feeds a later restore.
    try:
        _, cold, _ = run(steps=2, timeout=560, overlap=True)
        _, warm, stall_snap = run(steps=4, timeout=300, overlap=True)
        _, serial, stall_sync = run(steps=6, timeout=300, overlap=False)
    except subprocess.TimeoutExpired as exc:
        return {"error": f"124m recovery trial exceeded {exc.timeout:.0f}s "
                         f"on this host; rerun with more CPU"}
    serial_sum = serial.get("restore_s", 0.0) + serial.get("compile_s", 0.0)
    overlap_wall = warm.get("resume_phases_wall_s")
    phases = {
        "detect_reschedule_s": detect_reschedule_s,
        "init_s": warm.get("init_s"), "setup_s": warm.get("setup_s"),
        "restore_s": warm.get("restore_s"),
        "compile_s": warm.get("compile_s"),
        "first_step_s": warm.get("first_step_s"),
    }
    total = sum(v for k, v in phases.items()
                if v is not None and k not in ("restore_s", "compile_s"))
    total += overlap_wall or 0.0
    return {
        "params_m": 124.7,
        "phases": phases,
        # restore+compile enter the total as their overlapped wall, not
        # their sum -- that IS the fast path being scored.
        "resume_phases_wall_s": overlap_wall,
        "serial_restore_plus_compile_s": round(serial_sum, 2),
        "overlap_speedup": (round(serial_sum / overlap_wall, 2)
                            if overlap_wall else None),
        "time_to_resume_training_s": round(total, 2),
        # Warm first step can EXCEED cold: the image restore's device_put
        # is dispatched async, so sharded/replicated materialization of the
        # restored state completes during the first step (still inside the
        # total -- nothing escapes the scoreboard).
        "cold_first_step_s": cold.get("first_step_s"),
        "warm_first_step_s": warm.get("first_step_s"),
        # Cold compile_s is the real trace+lower+compile; warm is the
        # executable-snapshot load -- the whole compile-persistence stack.
        "warm_compile_speedup": (
            round(cold["compile_s"] / warm["compile_s"], 1)
            if cold.get("compile_s") and warm.get("compile_s")
            else None),
        "ckpt_stall_ms_sync": (stall_sync or {}).get("avg_ms"),
        "ckpt_stall_ms_snapshot": (stall_snap or {}).get("avg_ms"),
        "snapshot_stall_speedup": (
            round(stall_sync["avg_ms"] / stall_snap["avg_ms"], 1)
            if stall_sync and stall_snap and stall_snap["avg_ms"] > 0
            else None),
        "under_90s_budget": total < 90.0,
        "note": "direct workload resume at 124M params (CPU); "
                "detect_reschedule_s is the operator control-plane p50 "
                "measured by bench_recovery_control_plane",
    }


def bench_elastic_resize():
    """``elastic_resize`` A/B at the 124M config (ISSUE 9 tentpole):
    downtime -- last step before the resize signal to first step after --
    for the in-place scope=Resize fast path vs the restart-all baseline.

    Both arms run llama_elastic on CPU (8 forced host devices) at elastic
    width 4 and shrink to width 2 mid-run through the generation channel;
    the parent plays the controller, atomically publishing
    ``generation.json`` into the resize dir after the first logged step.

    - FAST: defaults.  The survivor observes the bumped generation, leaves
      the step loop, re-forms the mesh over the narrower device subset and
      redistributes the live params/opt pytrees device-to-device
      (parallel/reshard.py) -- no process restart, no checkpoint
      round-trip.
    - RESTART-ALL: TRAININGJOB_RESIZE_FASTPATH=0, the old contract -- the
      resize signal checkpoints and exits 143, and the parent relaunches
      at width 2 against the same checkpoint dir.  The operator's
      detect+reschedule half (scored by bench_recovery_control_plane) is
      NOT included, so the measured gap is a lower bound on the real
      restart-all cost.

    Both arms anchor on the child's ``resize: generation N observed at
    step I`` line (printed at the same step-loop position in either mode)
    and close on the next ``recovery_timing`` line (printed after the
    first post-resize optimizer step completes).

    All runs share ONE compile-cache dir, and two discarded seed runs
    populate it first (a full fast-path rehearsal, then a plain width-2
    startup), so BOTH measured windows hit a warm executable snapshot at
    width 2 -- the steady state of a fleet whose cache filer outlives jobs
    (docs/RECOVERY.md).  The A/B therefore scores the resize MECHANISM
    (reshard vs save+exit+relaunch+restore), not two cold XLA compiles of
    the same program.  The restart arm relaunches with 4 forced host
    devices -- half the pool, what 2 surviving hosts would bring -- so
    both arms finish on the same width-2, 4-device topology.

    The no-checkpoint-I/O claim is asserted from the workload trace
    (chrome trace_event JSON): the fast-path run must contain a
    ``resize.reshard`` span and NO ``resume.restore`` span at or after its
    ``resize.requod`` span (startup restore of the then-empty dir happens
    before it).

    Skip with TRAININGJOB_BENCH_SKIP_BIG=1 (two cold 124M CPU compiles
    per arm).
    """
    import glob
    import subprocess
    import tempfile
    import threading

    if os.environ.get("TRAININGJOB_BENCH_SKIP_BIG") == "1":
        return {"skipped": True}

    root = tempfile.mkdtemp(prefix="bench-elastic-")
    cache = os.path.join(root, "cache")
    base_xla = os.environ.get("XLA_FLAGS", "")

    def arm_env(tag, replicas, fastpath, devices=8, birth_generation=0):
        d = os.path.join(root, tag)
        xla = (base_xla
               + f" --xla_force_host_platform_device_count={devices}")
        env = dict(os.environ, LLAMA_CONFIG="124m", LLAMA_BATCH="2",
                   LLAMA_SEQ="64", LLAMA_STEPS="6", LLAMA_CKPT_EVERY="2",
                   XLA_FLAGS=xla.strip(),
                   TRAININGJOB_JAX_PLATFORM="cpu",
                   TRAININGJOB_CHECKPOINT_DIR=os.path.join(d, "ckpt"),
                   TRAININGJOB_COMPILE_CACHE_DIR=cache,
                   TRAININGJOB_ELASTIC_REPLICAS=str(replicas),
                   TRAININGJOB_RESIZE_DIR=os.path.join(d, "rdv"),
                   TRAININGJOB_RESIZE_POLL_S="0.05",
                   TRAININGJOB_RESIZE_FASTPATH="1" if fastpath else "0",
                   TRAININGJOB_RENDEZVOUS_GENERATION=str(birth_generation))
        return env

    def run_child(env, timeout, write_gen, ok_rc=(0,)):
        """Stream the child's stdout, timestamping every line; after the
        first completed-step line, publish the shrink generation (atomic
        tmp + rename, same as the controller's publish_generation)."""
        proc = subprocess.Popen(
            [sys.executable, "-m",
             "trainingjob_operator_tpu.workloads.llama_elastic"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            killer = threading.Timer(timeout, proc.kill)
            killer.start()
            lines = []
            wrote = False
            try:
                for raw in proc.stdout:
                    lines.append((time.perf_counter(), raw.rstrip("\n")))
                    if (write_gen and not wrote
                            and re.match(r"step \d+/", lines[-1][1])):
                        rdv = env["TRAININGJOB_RESIZE_DIR"]
                        os.makedirs(rdv, exist_ok=True)
                        tmp = os.path.join(rdv, ".generation.tmp")
                        with open(tmp, "w") as fh:
                            json.dump({"generation": 1, "world": [0, 1]}, fh)
                        os.replace(tmp, os.path.join(rdv, "generation.json"))
                        wrote = True
                rc = proc.wait()
            finally:
                killer.cancel()
        finally:
            # Exception path (broken pipe, interrupt): never leak the child
            # -- kill and reap it so repeated trials can't pile up orphans.
            # kill() no-ops once wait() has reaped the child.
            proc.kill()
            proc.wait()
        if rc not in ok_rc:
            tail = "\n".join(line for _, line in lines[-8:])
            raise RuntimeError(f"llama_elastic rc={rc}: {tail[-400:]}")
        return lines

    sig_pat = re.compile(r"resize: generation \d+ .*observed at step")

    def t_of(lines, pred, after=0.0):
        for t, line in lines:
            if t > after and pred(line):
                return t
        raise RuntimeError("expected line not found in llama_elastic "
                           "output: " + "\n".join(l for _, l in lines[-8:]))

    # -- Seed the shared cache (runs discarded): a full fast-path
    # rehearsal stores the width-4 startup and width-2-subset resize
    # executables; a plain width-2/4-device startup stores the restart
    # arm's relaunch executable.  Measured windows below are then warm on
    # both sides.
    run_child(arm_env("seed-fast", replicas=4, fastpath=True),
              timeout=900, write_gen=True)
    run_child(arm_env("seed-relaunch", replicas=2, fastpath=True,
                      devices=4),
              timeout=900, write_gen=False)

    # -- FAST arm: one process survives its own shrink, traced -------------
    trace_dir = os.path.join(root, "fast", "trace")
    env_fast = arm_env("fast", replicas=4, fastpath=True)
    env_fast.update(TRAININGJOB_TRACE_CONTEXT="bench-elastic:0",
                    TRAININGJOB_TRACE_DIR=trace_dir)
    fast = run_child(env_fast, timeout=900, write_gen=True)
    t_sig = t_of(fast, sig_pat.match)
    downtime_fast = t_of(
        fast, lambda l: l.startswith("recovery_timing"), after=t_sig) - t_sig
    fast_text = "\n".join(line for _, line in fast)
    m = re.search(r"resize_timing generation=\d+ width=\d+ "
                  r"rendezvous_s=([0-9.]+) "
                  r"requod_s=([0-9.]+) reshard_s=([0-9.]+) "
                  r"moved_mb=([0-9.]+) fallback=(\d) "
                  r"compile_s=([0-9.]+)", fast_text)

    # Span audit: reshard happened, and nothing restored a checkpoint at or
    # after the mesh re-form.
    events = []
    for path in glob.glob(os.path.join(trace_dir, "trace-*.json")):
        with open(path) as fh:
            events.extend(json.load(fh).get("traceEvents", []))
    requod_ts = [e["ts"] for e in events if e["name"] == "resize.requod"]
    resharded = any(e["name"] == "resize.reshard" for e in events)
    restores_after = [e for e in events if e["name"] == "resume.restore"
                      and requod_ts and e["ts"] >= min(requod_ts)]

    # -- RESTART-ALL arm: checkpoint, exit 143, relaunch at width 2 --------
    env_restart = arm_env("restart", replicas=4, fastpath=False)
    b1 = run_child(env_restart, timeout=900, write_gen=True, ok_rc=(143,))
    t_sig_b = t_of(b1, sig_pat.match)
    env_relaunch = arm_env("restart", replicas=2, fastpath=False,
                           devices=4, birth_generation=1)
    b2 = run_child(env_relaunch, timeout=900, write_gen=False)
    downtime_restart = t_of(
        b2, lambda l: l.startswith("recovery_timing")) - t_sig_b

    speedup = (downtime_restart / downtime_fast if downtime_fast else None)
    return {
        "params_m": 124.7,
        "downtime_fast_s": round(downtime_fast, 2),
        "downtime_restart_all_s": round(downtime_restart, 2),
        "speedup": round(speedup, 2) if speedup else None,
        "win_2x": bool(speedup and speedup >= 2.0),
        "rendezvous_s": float(m.group(1)) if m else None,
        "requod_s": float(m.group(2)) if m else None,
        "reshard_s": float(m.group(3)) if m else None,
        "moved_mb": float(m.group(4)) if m else None,
        "fell_back": bool(int(m.group(5))) if m else None,
        "resize_compile_s": float(m.group(6)) if m else None,
        "reshard_span": resharded,
        "no_checkpoint_io": resharded and not restores_after,
        "multiprocess": bench_elastic_live_rebootstrap(),
        "note": "in-place scope=Resize shrink 4->2 vs checkpoint+restart "
                "at 124M (CPU); restart arm excludes operator "
                "detect+reschedule, so the speedup is a lower bound",
    }


def bench_elastic_live_rebootstrap():
    """Two-PROCESS live-vs-checkpoint A/B for the re-rendezvous ladder
    (ISSUE 13 tentpole, docs/ELASTIC.md "Live re-rendezvous").

    Two real llama_elastic processes form a distributed client; the parent
    shrinks the world to one process through the generation channel.

    - LIVE arm: defaults.  The survivor (rank 0) tears down only the
      distributed client, re-inits against the bumped-generation
      coordinator, and rides the in-place resize -- downtime is its
      resize signal to its next ``recovery_timing`` line, all in ONE
      process lifetime.
    - CHECKPOINT arm: ``TRAININGJOB_RESIZE_LIVE=0`` forces the checkpoint
      rung -- both processes commit and exit 143 and the survivor is
      relaunched single-process against the same checkpoint dir.

    ``jax.distributed.shutdown`` + re-``initialize`` in one process needs
    jax >= 0.5; on older builds the arms cannot run and the bench reports
    itself skipped rather than measuring a restart in disguise.
    """
    import jax

    if jax.__version_info__ < (0, 5, 0):
        return {"skipped": True,
                "note": f"jax {jax.__version__} < 0.5: distributed client "
                        "teardown/re-init (shutdown + second initialize) "
                        "is not supported in-process; live rung is "
                        "exercised single-process by make resize-smoke"}

    import socket
    import subprocess
    import tempfile
    import threading

    root = tempfile.mkdtemp(prefix="bench-live-rdv-")
    base_xla = os.environ.get("XLA_FLAGS", "")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def proc_env(tag, rank, num, live, birth_generation=0):
        d = os.path.join(root, tag)
        xla = base_xla + " --xla_force_host_platform_device_count=4"
        return dict(os.environ, LLAMA_STEPS="6", LLAMA_CKPT_EVERY="2",
                    LLAMA_BATCH="8", LLAMA_SEQ="32",
                    XLA_FLAGS=xla.strip(),
                    TRAININGJOB_JAX_PLATFORM="cpu",
                    TRAININGJOB_CHECKPOINT_DIR=os.path.join(d, "ckpt"),
                    TRAININGJOB_ELASTIC_REPLICAS=str(num),
                    TRAININGJOB_NUM_PROCESSES=str(num),
                    TRAININGJOB_PROCESS_ID=str(rank),
                    TRAININGJOB_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                    TRAININGJOB_RESIZE_DIR=os.path.join(d, "rdv"),
                    TRAININGJOB_RESIZE_POLL_S="0.05",
                    TRAININGJOB_RESIZE_LIVE="1" if live else "0",
                    TRAININGJOB_RENDEZVOUS_GENERATION=str(birth_generation))

    def run_pair(tag, live, ok_rc=(0,)):
        """Launch ranks 0+1, publish the shrink-to-one doc after rank 0's
        first step, return rank 0's timestamped lines."""
        envs = [proc_env(tag, r, 2, live) for r in (0, 1)]
        rdv = envs[0]["TRAININGJOB_RESIZE_DIR"]
        procs = [subprocess.Popen(
            [sys.executable, "-m",
             "trainingjob_operator_tpu.workloads.llama_elastic"],
            env=e, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for e in envs]
        killers = [threading.Timer(900, p.kill) for p in procs]
        lines = []
        try:
            for k in killers:
                k.start()
            drain = threading.Thread(
                target=lambda: [None for _ in procs[1].stdout], daemon=True)
            drain.start()
            wrote = False
            for raw in procs[0].stdout:
                lines.append((time.perf_counter(), raw.rstrip("\n")))
                if not wrote and re.match(r"step \d+/", lines[-1][1]):
                    os.makedirs(rdv, exist_ok=True)
                    tmp = os.path.join(rdv, ".generation.tmp")
                    with open(tmp, "w") as fh:
                        json.dump({"generation": 1, "world": [0],
                                   "num_processes": 1}, fh)
                    os.replace(tmp, os.path.join(rdv, "generation.json"))
                    wrote = True
            rcs = [p.wait() for p in procs]
        finally:
            for k in killers:
                k.cancel()
            for p in procs:
                p.kill()
                p.wait()
        if rcs[0] not in ok_rc:
            tail = "\n".join(line for _, line in lines[-8:])
            raise RuntimeError(f"rank0 rc={rcs[0]}: {tail[-400:]}")
        return lines

    sig = re.compile(r"resize: generation \d+ .*observed at step")

    def t_of(lines, pred, after=0.0):
        for t, line in lines:
            if t > after and pred(line):
                return t
        raise RuntimeError("expected line not found: "
                           + "\n".join(l for _, l in lines[-8:]))

    # LIVE: rank 0 survives in place.
    live = run_pair("live", live=True)
    t_sig = t_of(live, sig.match)
    down_live = t_of(live, lambda l: l.startswith("recovery_timing"),
                     after=t_sig) - t_sig
    took_live = any(l.startswith("resize_rung") and "rung=live" in l
                    for _, l in live)

    # CHECKPOINT: forced degrade, both exit 143, relaunch rank 0 alone.
    ck = run_pair("ckpt", live=False, ok_rc=(143,))
    t_sig_c = t_of(ck, sig.match)
    relaunch = proc_env("ckpt", 0, 1, live=False, birth_generation=1)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "trainingjob_operator_tpu.workloads.llama_elastic"],
        env=relaunch, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    lines2 = []
    try:
        killer = threading.Timer(900, proc.kill)
        killer.start()
        try:
            for raw in proc.stdout:
                lines2.append((time.perf_counter(), raw.rstrip("\n")))
            proc.wait()
        finally:
            killer.cancel()
    finally:
        proc.kill()
        proc.wait()
    down_ck = t_of(lines2,
                   lambda l: l.startswith("recovery_timing")) - t_sig_c

    speedup = down_ck / down_live if down_live else None
    return {
        "downtime_live_s": round(down_live, 2),
        "downtime_checkpoint_s": round(down_ck, 2),
        "speedup": round(speedup, 2) if speedup else None,
        "win_2x": bool(speedup and speedup >= 2.0),
        "live_rung_taken": took_live,
        "note": "2-process shrink to 1: live coordinator rebootstrap vs "
                "TRAININGJOB_RESIZE_LIVE=0 checkpoint rung (relaunch "
                "excludes operator detect+reschedule)",
    }


def _wait(pred, timeout=60.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def bench_train_sandboxed(timeout_s: float = 900.0):
    """Run bench_train in a subprocess with a hard deadline.

    The axon TPU tunnel can wedge (a SIGKILLed attached process leaves the
    remote side locked; `jax.devices()` then hangs indefinitely).  In-process
    that would eat the driver's whole bench budget (BENCH_r02's rc=124); a
    sandboxed child turns it into a reported error + CPU-metric fallback.
    """
    import subprocess

    # Stage 1: cheap attach probe.  A wedged tunnel hangs jax.devices()
    # forever; detect that in 90 s instead of timing out the whole phase.
    env = dict(os.environ)
    note = None
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('OK')"],
            capture_output=True, text=True, timeout=90, cwd=here)
        tpu_ok = "OK" in (probe.stdout or "")
    except subprocess.TimeoutExpired:
        tpu_ok = False
    if not tpu_ok:
        # Fall back to CPU so the bench still measures SOMETHING comparable
        # (tiny-config metrics) rather than nothing.
        env["TRAININGJOB_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        note = "TPU attach probe failed; train bench ran on CPU fallback"

    code = ("from trainingjob_operator_tpu.workloads.rendezvous import "
            "apply_platform_override; apply_platform_override(); "
            "import json, bench; "
            "print('BENCH_TRAIN_JSON ' + json.dumps(bench.bench_train()))")
    try:
        # cwd=repo root: the child's `import bench` resolves from cwd.
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, env=env,
                              timeout=timeout_s, cwd=here)
    except subprocess.TimeoutExpired:
        return {"error": f"train bench exceeded {timeout_s:.0f}s "
                         f"(TPU tunnel wedged or compile stuck)"}
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_TRAIN_JSON "):
            result = json.loads(line[len("BENCH_TRAIN_JSON "):])
            if note:
                result["note"] = note
            return result
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return {"error": f"train bench rc={proc.returncode}: "
                     f"{' | '.join(tail[-3:])[:500]}"}


def main() -> int:
    out = {}
    out["train"] = bench_train_sandboxed()
    out["recovery_control_plane"] = bench_recovery_control_plane()
    try:
        out["control_plane"] = bench_control_plane()
    except Exception as exc:
        out["control_plane"] = {"error": f"{type(exc).__name__}: "
                                         f"{str(exc)[:300]}"}
    try:
        out["control_plane_chaos"] = bench_control_plane_chaos()
    except Exception as exc:
        out["control_plane_chaos"] = {"error": f"{type(exc).__name__}: "
                                               f"{str(exc)[:300]}"}
    try:
        out["node_chaos"] = bench_node_chaos()
    except Exception as exc:
        out["node_chaos"] = {"error": f"{type(exc).__name__}: "
                                      f"{str(exc)[:300]}"}
    try:
        out["slo_plane"] = bench_slo_plane()
    except Exception as exc:
        out["slo_plane"] = {"error": f"{type(exc).__name__}: "
                                     f"{str(exc)[:300]}"}
    try:
        out["request_plane"] = bench_request_plane()
    except Exception as exc:
        out["request_plane"] = {"error": f"{type(exc).__name__}: "
                                         f"{str(exc)[:300]}"}
    try:
        out["fleet_sim"] = bench_fleet_sim()
    except Exception as exc:
        out["fleet_sim"] = {"error": f"{type(exc).__name__}: "
                                     f"{str(exc)[:300]}"}
    out["recovery_full"] = bench_recovery_full()
    try:
        out["time_to_resume_training"] = bench_time_to_resume_training(
            detect_reschedule_s=out.get("recovery_control_plane",
                                        {}).get("p50_s"))
    except Exception as exc:
        out["time_to_resume_training"] = {"error": f"{type(exc).__name__}: "
                                                   f"{str(exc)[:300]}"}
    try:
        out["elastic_resize"] = bench_elastic_resize()
    except Exception as exc:
        out["elastic_resize"] = {"error": f"{type(exc).__name__}: "
                                          f"{str(exc)[:300]}"}

    train = out.get("train", {})
    rec = out.get("recovery_control_plane", {})
    full = out.get("recovery_full", {})
    if train.get("mfu_pct"):
        primary = {"metric": "llama_train_mfu", "value": train["mfu_pct"],
                   "unit": "%",
                   "vs_baseline": train.get("pallas_speedup")}
    else:
        p50 = rec.get("p50_s")
        primary = {"metric": "elastic_recovery_p50", "value": p50,
                   "unit": "s",
                   "vs_baseline": round(90.0 / p50, 1) if p50 else None}
    primary.update(out)
    primary["recovery_full_p50"] = full.get("p50_s")
    print(json.dumps(primary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
