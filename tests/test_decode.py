"""KV-cache decoding: cache consistency vs the training forward, sampling."""

import sys

import numpy as np
import pytest

from conftest import apply_jax_platform_override

apply_jax_platform_override()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from trainingjob_operator_tpu.models import decode, llama  # noqa: E402


def _f32_tiny():
    # float32 end to end so decode-vs-forward comparisons are tight.
    base = llama.LlamaConfig.tiny()
    return llama.LlamaConfig(**{**base.__dict__, "dtype": "float32"})


class TestCacheConsistency:
    def test_stepwise_decode_matches_teacher_forcing(self):
        # The decisive invariant: feeding the sequence token by token
        # through the KV cache must reproduce the training forward's logits
        # at every position.  Catches rope-offset, mask, and cache-slot
        # bugs in one assertion.
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                    cfg.vocab_size)
        full = llama.forward(params, tokens, cfg)          # [B, 8, V]

        logits0, cache = decode.prefill(params, tokens[:, :1], cfg,
                                        max_len=8)
        np.testing.assert_allclose(np.asarray(logits0),
                                   np.asarray(full[:, 0]), rtol=2e-4,
                                   atol=2e-4)
        for t in range(1, 8):
            step_logits, cache = decode.decode_step(
                params, cache, tokens[:, t], jnp.int32(t), cfg)
            np.testing.assert_allclose(np.asarray(step_logits),
                                       np.asarray(full[:, t]), rtol=2e-4,
                                       atol=2e-4)

    def test_prefill_matches_stepwise(self):
        # Prefilling the whole prompt must leave the same cache state as
        # stepwise decoding it: next-step logits agree.
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0,
                                    cfg.vocab_size)

        logits_a, cache_a = decode.prefill(params, tokens, cfg, max_len=8)
        _, cache_b = decode.prefill(params, tokens[:, :1], cfg, max_len=8)
        logits_b = None
        for t in range(1, 6):
            logits_b, cache_b = decode.decode_step(
                params, cache_b, tokens[:, t], jnp.int32(t), cfg)
        np.testing.assert_allclose(np.asarray(logits_a),
                                   np.asarray(logits_b), rtol=2e-4,
                                   atol=2e-4)
        na, nb = (decode.decode_step(params, c, tokens[:, 0], jnp.int32(6),
                                     cfg)[0] for c in (cache_a, cache_b))
        np.testing.assert_allclose(np.asarray(na), np.asarray(nb),
                                   rtol=2e-4, atol=2e-4)


class TestGenerate:
    def test_greedy_matches_argmax_and_is_deterministic(self):
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0,
                                    cfg.vocab_size)
        out1 = decode.generate(params, prompt, cfg, steps=5)
        out2 = decode.generate(params, prompt, cfg, steps=5)
        assert out1.shape == (2, 5)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        # First sampled token is the argmax of the teacher-forced logits at
        # the last prompt position.
        full = llama.forward(params, prompt, cfg)
        np.testing.assert_array_equal(
            np.asarray(out1[:, 0]),
            np.asarray(jnp.argmax(full[:, -1], axis=-1)))

    def test_temperature_needs_key(self):
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 2), jnp.int32)
        with pytest.raises(ValueError, match="PRNG key"):
            decode.generate(params, prompt, cfg, steps=2, temperature=0.7)
        out = decode.generate(params, prompt, cfg, steps=3, temperature=0.7,
                              key=jax.random.PRNGKey(3))
        assert out.shape == (1, 3)

    def test_top_k_restricts_support(self):
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 3), 0,
                                    cfg.vocab_size)
        # top_k=1 at any temperature is greedy.
        greedy = decode.generate(params, prompt, cfg, steps=4)
        k1 = decode.generate(params, prompt, cfg, steps=4, temperature=5.0,
                             top_k=1, key=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))
        # A tight nucleus behaves likewise at modest temperature.
        p_small = decode.generate(params, prompt, cfg, steps=4,
                                  temperature=0.5, top_p=1e-6,
                                  key=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(p_small),
                                      np.asarray(greedy))
        with pytest.raises(ValueError, match="temperature"):
            decode.generate(params, prompt, cfg, steps=2, top_k=5)
        # top_p=1.0 / top_k>=vocab restrict nothing -> valid with greedy.
        none_restricting = decode.generate(params, prompt, cfg, steps=4,
                                           top_p=1.0,
                                           top_k=cfg.vocab_size + 5)
        np.testing.assert_array_equal(np.asarray(none_restricting),
                                      np.asarray(greedy))

    def test_generate_is_jittable(self):
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 3), 0,
                                    cfg.vocab_size)
        import functools

        fn = jax.jit(functools.partial(decode.generate, config=cfg, steps=4,
                                       max_len=7))
        out = fn(params, prompt)
        eager = decode.generate(params, prompt, cfg, steps=4, max_len=7)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(eager))

    def test_sharded_generate_matches_single_device(self):
        # Decode under a dp x tp mesh: same params, same greedy tokens.
        # The per-step attention/matmuls partition over tp like training;
        # a sharding bug shows up as divergent samples.
        from jax.sharding import NamedSharding, PartitionSpec as P

        from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh
        from trainingjob_operator_tpu.parallel.sharding import shard_pytree

        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 3), 0,
                                    cfg.vocab_size)
        single = decode.generate(params, prompt, cfg, steps=4)

        mesh = make_mesh(MeshSpec.of(dp=2, fsdp=1, tp=2),
                         devices=jax.devices()[:4])
        params_sh = shard_pytree(params, llama.SHARDING_RULES, mesh)
        prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P("dp", None)))
        sharded = decode.generate(params_sh, prompt_sh, cfg, steps=4,
                                  mesh=mesh)
        np.testing.assert_array_equal(np.asarray(sharded),
                                      np.asarray(single))

    def test_rejects_overflow(self):
        cfg = _f32_tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="max_len"):
            decode.generate(params, prompt, cfg, steps=8, max_len=6)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))


class TestRingKVCache:
    """Sliding-window decode uses a ring cache of exactly ``window`` slots
    (memory O(window), not O(max_len)); wrapped slots keep absolute-position
    RoPE so the math matches the training forward."""

    def test_cache_is_window_sized(self):
        import dataclasses

        from trainingjob_operator_tpu.models import decode, llama

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(n_layers=2),
                                  sliding_window=8)
        cache = decode.init_cache(cfg, batch=2, max_len=128)
        assert cache["k"].shape[2] == 8
        # Full causal keeps the full-length cache.
        cfg0 = dataclasses.replace(cfg, sliding_window=0)
        assert decode.init_cache(cfg0, 2, 128)["k"].shape[2] == 128

    def test_teacher_forced_matches_forward_across_many_wraps(self):
        import dataclasses

        from trainingjob_operator_tpu.models import decode, llama

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(n_layers=2),
                                  sliding_window=6, dtype="float32")
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        T = 30  # 5x the window: the ring wraps repeatedly
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, T), 0,
                                    cfg.vocab_size)
        full = llama.forward(params, tokens, cfg)
        # Prefill a LONG prompt (> window) so the ring-placement branch of
        # prefill is exercised too, then teacher-force the rest.
        _, cache = decode.prefill(params, tokens[:, :10], cfg, max_len=T)
        assert cache["k"].shape[2] == 6
        for t in range(10, T):
            lg, cache = decode.decode_step(params, cache, tokens[:, t - 1],
                                           jnp.int32(t - 1), cfg)
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(full[:, t - 1]),
                                       rtol=2e-3, atol=2e-3)


class TestMoEDecode:
    """KV-cache decoding for the MoE family (models/moe_decode.py): the
    routed single-token MLP gathers only the top-k experts' weights, and
    teacher-forced logits match the training forward when no tokens drop."""

    def _cfg(self, **kw):
        import dataclasses

        from trainingjob_operator_tpu.models import moe

        base = moe.MoEConfig.tiny(n_layers=2)
        # Ample capacity: no training-time token drops, so the (dropless)
        # decode math must match the forward exactly.
        return dataclasses.replace(
            base, dtype="float32", capacity_factor=float(
                base.n_experts / base.experts_per_token), **kw)

    def test_teacher_forced_matches_forward(self):
        from trainingjob_operator_tpu.models import moe, moe_decode

        cfg = self._cfg()
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        T = 12
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, T), 0,
                                    cfg.vocab_size)
        full, _aux = moe.forward(params, tokens, cfg)
        _, cache = moe_decode.prefill(params, tokens[:, :4], cfg, max_len=T)
        for t in range(4, T):
            lg, cache = moe_decode.decode_step(
                params, cache, tokens[:, t - 1], jnp.int32(t - 1), cfg)
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(full[:, t - 1]),
                                       rtol=2e-3, atol=2e-3)

    def test_windowed_ring_cache_matches_forward(self):
        from trainingjob_operator_tpu.models import moe, moe_decode

        cfg = self._cfg(sliding_window=6)
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        T = 20
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, T), 0,
                                    cfg.vocab_size)
        full, _aux = moe.forward(params, tokens, cfg)
        _, cache = moe_decode.prefill(params, tokens[:, :8], cfg, max_len=T)
        assert cache["k"].shape[2] == 6  # ring, not max_len
        for t in range(8, T):
            lg, cache = moe_decode.decode_step(
                params, cache, tokens[:, t - 1], jnp.int32(t - 1), cfg)
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(full[:, t - 1]),
                                       rtol=2e-3, atol=2e-3)

    def test_generate_runs(self):
        from trainingjob_operator_tpu.models import moe, moe_decode

        cfg = self._cfg()
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0,
                                    cfg.vocab_size)
        out = np.asarray(moe_decode.generate(params, prompt, cfg, steps=6))
        assert out.shape == (2, 6)
        assert out.min() >= 0 and out.max() < cfg.vocab_size

    def test_prefill_warns_when_capacity_admits_drops(self):
        """capacity_factor < n_experts/experts_per_token means prefill's
        dispatch can drop tokens the dropless decode path would route --
        prefill must say so."""
        import dataclasses
        import warnings

        from trainingjob_operator_tpu.models import moe, moe_decode

        cfg = dataclasses.replace(self._cfg(), capacity_factor=1.0)
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg.vocab_size)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            moe_decode.prefill(params, tokens, cfg, max_len=8)
        assert any(issubclass(w.category, RuntimeWarning)
                   and "capacity_factor" in str(w.message) for w in caught)

    def test_prefill_quiet_with_ample_capacity(self):
        import warnings

        from trainingjob_operator_tpu.models import moe, moe_decode

        cfg = self._cfg()  # capacity_factor == n_experts/experts_per_token
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0,
                                    cfg.vocab_size)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            moe_decode.prefill(params, tokens, cfg, max_len=8)
        assert not [w for w in caught
                    if "capacity_factor" in str(w.message)]
