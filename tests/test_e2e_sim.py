"""End-to-end tests: controller + sim runtime (scheduler/kubelet), all
asynchronous -- nothing drives sync_handler by hand.

This is test-pyramid level (3) (SURVEY.md §4): job lifecycles against a fake
"TPU slice" cluster with fault injection (preemption, node failure).
"""

import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    EndingPolicy,
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUSpec,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)
from trainingjob_operator_tpu.runtime.sim import (
    EXIT_CODE_ANNOTATION,
    RUN_SECONDS_ANNOTATION,
    SimRuntime,
)


from conftest import wait_for  # noqa: E402


@pytest.fixture
def cluster():
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.start()
    tc.run(workers=2)
    yield cs, tc, sim
    tc.stop()
    sim.stop()


def sim_job(name="job", replicas=2, run_seconds="0.2", exit_code="0",
            **replica_kw) -> TPUTrainingJob:
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace="default"))
    template = PodTemplateSpec(
        metadata=ObjectMeta(annotations={RUN_SECONDS_ANNOTATION: run_seconds,
                                         EXIT_CODE_ANNOTATION: exit_code}),
        spec=PodSpec(containers=[
            Container(name="aitj-main",
                      ports=[ContainerPort(name="aitj-7777", container_port=7777)])]))
    job.spec.replica_specs["trainer"] = ReplicaSpec(
        replicas=replicas, template=template, **replica_kw)
    return job


def phase(cs, name="job"):
    return cs.trainingjobs.get("default", name).status.phase


class TestLifecycle:
    def test_job_runs_to_completion(self, cluster):
        cs, tc, sim = cluster
        sim.add_node("n0")
        cs.trainingjobs.create(sim_job(run_seconds="0.15"))
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 5), phase(cs)
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.SUCCEEDED, 5), phase(cs)
        # CleanPodPolicy All: pods drained.
        assert wait_for(lambda: cs.pods.list("default") == [], 2)

    def test_failing_job_fails(self, cluster):
        cs, tc, sim = cluster
        sim.add_node("n0")
        cs.trainingjobs.create(sim_job(run_seconds="0.1", exit_code="1"))
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.FAILED, 5), phase(cs)

    def test_unschedulable_stays_pending(self, cluster):
        cs, tc, sim = cluster  # no nodes
        cs.trainingjobs.create(sim_job())
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.PENDING, 5), phase(cs)
        time.sleep(0.2)
        assert phase(cs) == TrainingJobPhase.PENDING


class TestFaultTolerance:
    def test_preemption_recovery_via_exit_code(self, cluster):
        """Spot-reclaim path: pod killed with 137, policy retries it."""
        cs, tc, sim = cluster
        sim.add_node("n0")
        job = sim_job(run_seconds="30", restart_policy=RestartPolicy.EXIT_CODE,
                      restart_scope=RestartScope.ALL)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 5), phase(cs)
        sim.preempt_pod("default", "job-trainer-1", exit_code=137)
        assert wait_for(
            lambda: cs.trainingjobs.get("default", "job").status.restart_counts.get("trainer", 0) == 1,
            5)
        # Job recovers to Running with fresh pods.
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 10), phase(cs)
        pods = cs.pods.list("default")
        assert len(pods) == 2
        assert all(p.metadata.labels[constants.RESTART_COUNT_LABEL] == "1"
                   for p in pods)

    def test_node_failure_recovery(self, cluster):
        cs, tc, sim = cluster
        sim.add_node("n0")
        sim.add_node("n1")
        job = sim_job(run_seconds="30",
                      restart_policy=RestartPolicy.ON_NODE_FAIL,
                      restart_scope=RestartScope.ALL)
        cs.trainingjobs.create(job)
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 5), phase(cs)
        victim = cs.pods.get("default", "job-trainer-0").spec.node_name
        sim.fail_node(victim)
        assert wait_for(
            lambda: cs.trainingjobs.get("default", "job").status.restart_counts.get("trainer", 0) >= 1,
            5)
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 10), phase(cs)
        # All pods now on the surviving node.
        for p in cs.pods.list("default"):
            assert p.spec.node_name != victim


class TestTPUGang:
    def test_gang_all_or_nothing(self, cluster):
        cs, tc, sim = cluster
        # One TPU node with 4 chips: a 2-host slice (2 pods x 4 chips) cannot
        # fit -- neither pod may be placed.
        sim.add_node("tpu-0", labels={
            constants.GKE_TPU_ACCELERATOR_SELECTOR: "tpu-v5-lite-podslice",
            constants.GKE_TPU_TOPOLOGY_SELECTOR: "2x4",
        }, tpu_chips=4)
        job = sim_job(replicas=2, run_seconds="0.3")
        job.spec.replica_specs["trainer"].tpu = TPUSpec(
            accelerator="tpu-v5-lite-podslice", topology="2x4")
        cs.trainingjobs.create(job)
        assert wait_for(lambda: len(cs.pods.list("default")) == 2, 5)
        time.sleep(0.3)
        assert all(not p.spec.node_name for p in cs.pods.list("default"))
        assert phase(cs) == TrainingJobPhase.PENDING
        # Second TPU host arrives: now the whole gang places and completes.
        sim.add_node("tpu-1", labels={
            constants.GKE_TPU_ACCELERATOR_SELECTOR: "tpu-v5-lite-podslice",
            constants.GKE_TPU_TOPOLOGY_SELECTOR: "2x4",
        }, tpu_chips=4)
        assert wait_for(lambda: phase(cs) == TrainingJobPhase.SUCCEEDED, 10), phase(cs)

    def test_gap_filled_member_of_running_gang_places(self, cluster):
        """A recreated single member of an otherwise-RUNNING gang must still
        schedule (sim counts gang membership over all live pods, not just
        pending ones -- else gap-fill wedges forever)."""
        cs, tc, sim = cluster
        for i in range(2):
            sim.add_node(f"tpu-{i}", labels={
                constants.GKE_TPU_ACCELERATOR_SELECTOR:
                    "tpu-v5-lite-podslice",
                constants.GKE_TPU_TOPOLOGY_SELECTOR: "2x4",
            }, tpu_chips=4)
        job = sim_job(replicas=2, run_seconds="30")
        job.spec.replica_specs["trainer"].tpu = TPUSpec(
            accelerator="tpu-v5-lite-podslice", topology="2x4")
        cs.trainingjobs.create(job)
        assert wait_for(
            lambda: phase(cs) == TrainingJobPhase.RUNNING, 10), phase(cs)
        # Delete one member; the controller gap-fills it and the sim
        # must place the singleton (its sibling keeps running).
        cs.pods.delete("default", "job-trainer-1")
        assert wait_for(
            lambda: (p := {x.name: x for x in cs.pods.list("default")})
            and "job-trainer-1" in p
            and bool(p["job-trainer-1"].spec.node_name), 10)


class TestElasticE2E:
    def test_shrink_on_node_loss_then_reexpand(self):
        """The north-star loop (SURVEY.md §5.3): spot node dies -> group
        shrinks to survivors and keeps training; capacity returns -> probe
        re-expands to full width."""
        cs = Clientset()
        tc = TrainingJobController(cs, options=OperatorOptions(
            resync_period=0.05, scale_up_delay=0.3, scale_pending_time=0.4))
        sim = SimRuntime(cs, pods_per_node=1)
        sim.start()
        tc.run(workers=2)
        try:
            for i in range(3):
                sim.add_node(f"n{i}")
            job = sim_job(replicas=3, run_seconds="60",
                          min_replicas=2, max_replicas=3, edl_policy="Auto",
                          restart_policy=RestartPolicy.ON_NODE_FAIL,
                          restart_scope=RestartScope.REPLICA)
            cs.trainingjobs.create(job)
            assert wait_for(lambda: phase(cs) == TrainingJobPhase.RUNNING, 10), phase(cs)

            t0 = time.time()
            sim.fail_node("n2")
            # Degraded recovery: running again at width 2, no restart budget
            # spent.
            assert wait_for(
                lambda: (phase(cs) == TrainingJobPhase.RUNNING
                         and cs.trainingjobs.get("default", "job")
                         .status.elastic_replicas.get("trainer") == 2), 10)
            recovery = time.time() - t0
            got = cs.trainingjobs.get("default", "job")
            assert got.status.restart_counts.get("trainer", 0) == 0
            assert len([p for p in cs.pods.list("default")
                        if p.metadata.deletion_timestamp is None]) == 2
            assert recovery < 30  # sim-scale sanity; real target is <90s

            # Capacity returns: the probe re-expands to full width.
            sim.recover_node("n2")
            assert wait_for(
                lambda: (phase(cs) == TrainingJobPhase.RUNNING
                         and not cs.trainingjobs.get("default", "job")
                         .status.elastic_replicas), 20)
            pods = [p for p in cs.pods.list("default")
                    if p.metadata.deletion_timestamp is None]
            assert len(pods) == 3
        finally:
            tc.stop()
            sim.stop()
