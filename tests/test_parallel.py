"""Parallel layer tests on the virtual 8-device CPU mesh.

conftest sets XLA_FLAGS=--xla_force_host_platform_device_count=8 and
JAX_PLATFORMS=cpu (SURVEY.md §7: multi-chip designs validated on a virtual
mesh; the driver separately dry-runs the multichip path).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override

apply_jax_platform_override()
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh
from trainingjob_operator_tpu.parallel.ringattention import (
    reference_attention,
    ring_attention_sharded,
)
from trainingjob_operator_tpu.parallel.sharding import (
    batch_spec,
    shard_pytree,
    sharding_pytree,
    spec_for_path,
)


def test_device_count():
    assert jax.device_count() == 8


class TestMesh:
    def test_make_mesh_shapes(self):
        mesh = make_mesh(MeshSpec.of(dp=2, fsdp=2, tp=2))
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 2, 2)

    def test_bad_size_raises(self):
        with pytest.raises(ValueError):
            make_mesh(MeshSpec.of(dp=3, tp=2))

    def test_unknown_axis_raises(self):
        with pytest.raises(ValueError):
            MeshSpec.of(dp=2, banana=4)

    def test_axis_canonical_order(self):
        spec = MeshSpec.of(tp=2, dp=4)  # declared out of order
        assert spec.names == ("dp", "tp")


class TestShardingRules:
    RULES = [
        (r"embed", ("tp", None)),
        (r"attn/w[qkv]", (None, "tp")),
        (r"mlp/w_in", (None, "tp")),
        (r"mlp/w_out", ("tp", None)),
    ]

    def test_first_match_wins_and_default(self):
        assert spec_for_path("tok_embed/w", self.RULES) == P("tp", None)
        assert spec_for_path("layers/0/attn/wq", self.RULES) == P(None, "tp")
        assert spec_for_path("layers/0/norm/scale", self.RULES) == P()

    def test_shard_pytree_places_leaves(self):
        mesh = make_mesh(MeshSpec.of(dp=2, tp=4))
        tree = {"tok_embed": {"w": jnp.zeros((8, 16))},
                "layers": [{"attn": {"wq": jnp.zeros((16, 16))},
                            "norm": {"scale": jnp.zeros((16,))}}]}
        sharded = shard_pytree(tree, self.RULES, mesh)
        emb = sharded["tok_embed"]["w"]
        assert emb.sharding.spec == P("tp", None)
        # tp=4 shards dim0 8 -> 2 per device.
        assert emb.addressable_shards[0].data.shape == (2, 16)
        assert sharded["layers"][0]["norm"]["scale"].sharding.spec == P()

    def test_sharding_pytree_matches(self):
        mesh = make_mesh(MeshSpec.of(dp=2, tp=4))
        tree = {"tok_embed": {"w": jnp.zeros((8, 16))}}
        sh = sharding_pytree(tree, self.RULES, mesh)
        assert sh["tok_embed"]["w"].spec == P("tp", None)

    def test_batch_spec(self):
        mesh = make_mesh(MeshSpec.of(dp=2, fsdp=2, sp=2))
        assert batch_spec(mesh) == P(("dp", "fsdp"))
        assert batch_spec(mesh, sequence_axis=True) == P(("dp", "fsdp"), "sp")


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("dp,sp", [(2, 4), (1, 8), (4, 2)])
    def test_matches_reference(self, causal, dp, sp):
        mesh = make_mesh(MeshSpec.of(dp=dp, sp=sp))
        B, T, H, D = 2 * dp, 16 * sp, 2, 8
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, T, H, D), jnp.float32)
        v = jax.random.normal(kv, (B, T, H, D), jnp.float32)

        expected = reference_attention(q, k, v, causal=causal)

        spec = P("dp" if dp > 1 else None, "sp", None, None)
        qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                      for x in (q, k, v))
        got = ring_attention_sharded(qs, ks, vs, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_jit_compiles_once_and_grads_flow(self):
        mesh = make_mesh(MeshSpec.of(sp=8))
        B, T, H, D = 2, 64, 2, 8
        key = jax.random.PRNGKey(1)
        q = jax.random.normal(key, (B, T, H, D))
        spec = P(None, "sp", None, None)
        qs = jax.device_put(q, NamedSharding(mesh, spec))

        @jax.jit
        def loss(q):
            out = ring_attention_sharded(q, q, q, mesh, causal=True)
            return (out ** 2).sum()

        g = jax.grad(loss)(qs)
        assert g.shape == q.shape
        assert bool(jnp.isfinite(g).all())


class TestCollectives:
    class FakeDev:
        def __init__(self, slice_index):
            self.slice_index = slice_index

    class FakeMesh:
        """Duck-typed mesh over fake devices with slice ids."""

        def __init__(self, arr, names):
            import numpy as _np

            self.devices = _np.array(arr, dtype=object)
            self.axis_names = tuple(names)
            self.shape = dict(zip(names, self.devices.shape))

    def _two_slice_mesh(self):
        # dp=2 crosses slices, tp=2 stays inside each slice.
        d = [[self.FakeDev(0), self.FakeDev(0)],
             [self.FakeDev(1), self.FakeDev(1)]]
        return self.FakeMesh(d, ("dp", "tp"))

    def test_axis_crosses_dcn(self):
        from trainingjob_operator_tpu.parallel import collectives

        mesh = self._two_slice_mesh()
        assert collectives.axis_crosses_dcn(mesh, "dp")
        assert not collectives.axis_crosses_dcn(mesh, "tp")

    def test_require_ici_axis(self):
        from trainingjob_operator_tpu.parallel import collectives

        mesh = self._two_slice_mesh()
        assert collectives.require_ici_axis(mesh, "tp") == 2
        with pytest.raises(ValueError, match="DCN"):
            collectives.require_ici_axis(mesh, "dp")
        with pytest.raises(ValueError, match="no 'sp'"):
            collectives.require_axis(mesh, "sp")

    def test_cpu_mesh_is_all_ici(self):
        from trainingjob_operator_tpu.parallel import collectives

        mesh = make_mesh(MeshSpec.of(dp=2, tp=4))
        assert not collectives.axis_crosses_dcn(mesh, "dp")
        assert collectives.require_ici_axis(mesh, "tp") == 4

    def test_ring_permutation(self):
        from trainingjob_operator_tpu.parallel import collectives

        assert collectives.ring_permutation(3) == ((0, 1), (1, 2), (2, 0))
        assert collectives.ring_permutation(3, reverse=True) == (
            (0, 2), (1, 0), (2, 1))

    def test_hierarchical_psum_matches_joint(self):
        from functools import partial

        from trainingjob_operator_tpu.parallel import collectives

        mesh = make_mesh(MeshSpec.of(dp=2, fsdp=4))
        x = jnp.arange(8.0).reshape(2, 4)
        x = jax.device_put(x, NamedSharding(mesh, P("dp", "fsdp")))
        try:
            from jax import shard_map

            compat = {"check_vma": False}
        except ImportError:
            from jax.experimental.shard_map import shard_map

            compat = {"check_rep": False}
        fn = shard_map(
            partial(collectives.hierarchical_psum, mesh=mesh,
                    axes=("dp", "fsdp")),
            mesh=mesh, in_specs=P("dp", "fsdp"), out_specs=P(), **compat)
        np.testing.assert_allclose(np.asarray(fn(x)), 28.0)


class TestFitSpec:
    def test_truncates_spec_longer_than_rank(self):
        from jax.sharding import PartitionSpec as P

        from trainingjob_operator_tpu.parallel.sharding import fit_spec

        mesh = make_mesh(MeshSpec.of(fsdp=4, tp=2))
        fitted = fit_spec(P(None, "fsdp", "tp"), (16, 16), mesh)
        assert len(fitted) <= 2

    def test_replicates_non_divisible_axes(self):
        from jax.sharding import PartitionSpec as P

        from trainingjob_operator_tpu.parallel.sharding import fit_spec

        mesh = make_mesh(MeshSpec.of(fsdp=4, tp=2))
        fitted = fit_spec(P(None, "fsdp", "tp"), (2, 6, 8), mesh)
        assert fitted == P(None, None, "tp")


class TestVirtualMultislice:
    """Multislice end-to-end on the virtual CPU mesh (VERDICT r3 item 7):
    megascale env -> rendezvous -> mesh_from_rendezvous -> DCN-aware
    collectives, with REAL device/mesh objects, not mocks."""

    @pytest.fixture
    def two_slices(self, monkeypatch):
        import jax

        monkeypatch.setenv(constants.VIRTUAL_DEVICES_PER_SLICE_ENV,
                           str(jax.device_count() // 2))

    def test_mesh_from_megascale_env_puts_dp_on_dcn(self, two_slices):
        import jax

        from trainingjob_operator_tpu.parallel import collectives
        from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
        from trainingjob_operator_tpu.workloads import rendezvous

        rdv = rendezvous.from_env({"MEGASCALE_NUM_SLICES": "2",
                                   "MEGASCALE_SLICE_ID": "0"})
        assert rdv.num_slices == 2
        mesh = mesh_from_rendezvous(rdv, model_parallel=2)
        assert mesh.shape["dp"] == 2
        assert collectives.axis_crosses_dcn(mesh, "dp")
        for axis in mesh.axis_names:
            if axis != "dp" and mesh.shape[axis] > 1:
                assert collectives.require_ici_axis(mesh, axis) > 1
        # fsdp spanning slices is the classic multislice perf bug: forbidden.
        assert not collectives.axis_crosses_dcn(mesh, "fsdp")
        assert jax.device_count() == mesh.size

    def test_hierarchical_psum_executes_on_two_slice_mesh(self, two_slices):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import PartitionSpec

        from trainingjob_operator_tpu.parallel import collectives
        from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
        from trainingjob_operator_tpu.workloads import rendezvous

        rdv = rendezvous.from_env({"MEGASCALE_NUM_SLICES": "2",
                                   "MEGASCALE_SLICE_ID": "0"})
        mesh = mesh_from_rendezvous(rdv)
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:
            from jax.experimental.shard_map import shard_map
        axes = tuple(a for a in mesh.axis_names if mesh.shape[a] > 1)
        inner = tuple(a for a in ("fsdp", "tp", "sp") if a in mesh.axis_names)
        x = jnp.arange(mesh.size, dtype=jnp.float32).reshape(
            mesh.shape["dp"], -1)
        reduced = shard_map(
            lambda v: collectives.hierarchical_psum(v, mesh, axes),
            mesh=mesh, in_specs=PartitionSpec("dp", inner),
            out_specs=PartitionSpec("dp", inner))(x)
        assert np.allclose(np.asarray(reduced),
                           float(np.arange(mesh.size).sum()))

    def test_ici_first_ordering(self, two_slices):
        from trainingjob_operator_tpu.parallel import collectives
        from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
        from trainingjob_operator_tpu.workloads import rendezvous

        rdv = rendezvous.from_env({"MEGASCALE_NUM_SLICES": "2",
                                   "MEGASCALE_SLICE_ID": "0"})
        mesh = mesh_from_rendezvous(rdv)
        # hierarchical_psum sorts ICI axes first; dp (DCN) must come last.
        axes = sorted(("dp", "fsdp"),
                      key=lambda a: collectives.axis_crosses_dcn(mesh, a))
        assert axes[-1] == "dp"


class TestPipelineParallel:
    """GPipe over the pp axis (parallel/pipeline.py): exact vs sequential,
    grads flow, and the llama integration trains on a pp x fsdp x tp mesh."""

    def _pp_mesh(self, pp=4, other=("dp", 2)):
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:other[1] * pp]).reshape(other[1], pp)
        return Mesh(devs, (other[0], "pp"))

    def test_matches_sequential_scan(self):
        import jax
        import jax.numpy as jnp

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        mesh = self._pp_mesh()
        L, B, D = 8, 4, 16
        layers = {"w": jax.random.normal(jax.random.PRNGKey(0),
                                         (L, D, D)) * 0.1,
                  "b": jax.random.normal(jax.random.PRNGKey(1), (L, D)) * 0.1}
        h = jax.random.normal(jax.random.PRNGKey(2), (B, D))

        def block(hh, layer):
            return jnp.tanh(hh @ layer["w"] + layer["b"])

        ref = h
        for i in range(L):
            ref = block(ref, jax.tree.map(lambda x: x[i], layers))
        out = jax.jit(lambda ls, x: gpipe(block, ls, x, mesh, 2))(layers, h)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_grads_match_sequential(self):
        import jax
        import jax.numpy as jnp

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        mesh = self._pp_mesh()
        L, B, D = 4, 4, 8
        layers = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.2
        h = jax.random.normal(jax.random.PRNGKey(1), (B, D))

        def block(hh, w):
            return jnp.tanh(hh @ w)

        def loss_pipe(ls):
            return (gpipe(block, ls, h, mesh, 2) ** 2).sum()

        def loss_seq(ls):
            r = h
            for i in range(L):
                r = block(r, ls[i])
            return (r ** 2).sum()

        g1 = jax.jit(jax.grad(loss_pipe))(layers)
        g2 = jax.jit(jax.grad(loss_seq))(layers)
        assert np.allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)

    def test_llama_pp_matches_dense_and_trains(self):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.parallel.sharding import (
            batch_spec,
            shard_pytree,
        )

        cfg = llama.LlamaConfig.tiny(n_layers=4)
        devs = np.array(jax.devices()).reshape(1, 2, 2, 2)
        mesh = Mesh(devs, ("dp", "pp", "fsdp", "tp"))
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size)

        # Equivalence in f32 (bf16 would only show accumulation-order noise).
        cfg32 = llama.LlamaConfig(**{**cfg.__dict__, "dtype": "float32"})
        dense = llama.forward(params, tokens[:, :-1], cfg32)
        sharded = shard_pytree(params, llama.sharding_rules(pipeline=True),
                               mesh)
        # Stage ownership: stacked layers sharded on pp.
        assert "pp" in str(sharded["layers"]["attn"]["wq"].sharding.spec)
        piped = jax.jit(lambda p, t: llama.forward(
            p, t, cfg32, mesh=mesh, n_microbatches=2))(
                sharded, tokens[:, :-1])
        assert np.allclose(np.asarray(piped), np.asarray(dense),
                           rtol=1e-4, atol=1e-4)

        tx = optax.adam(1e-2)
        opt = tx.init(sharded)
        tb = jax.device_put(tokens, NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(lambda pp_: llama.loss_fn(
                pp_, {"tokens": t}, cfg, mesh=mesh))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        losses = []
        p, o = sharded, opt
        for _ in range(6):
            p, o, l = step(p, o, tb)
            losses.append(float(l))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestPipelineEdgeCases:
    def test_pp1_degenerates_to_scan(self):
        import jax
        import jax.numpy as jnp

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        mesh = make_mesh(MeshSpec.of(dp=8))  # no pp axis at size > 1
        with pytest.raises(ValueError, match="no 'pp'"):
            gpipe(lambda h, l: h, jnp.zeros((4, 2, 2)),
                  jnp.zeros((4, 2)), mesh, 2)

    def test_layers_must_divide_stages(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        devs = np.array(jax.devices()).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "pp"))
        with pytest.raises(ValueError, match="not divisible by pp"):
            gpipe(lambda h, l: h, jnp.zeros((6, 2, 2)),
                  jnp.zeros((4, 2)), mesh, 2)
        with pytest.raises(ValueError, match="microbatches"):
            gpipe(lambda h, l: h, jnp.zeros((4, 2, 2)),
                  jnp.zeros((5, 2)), mesh, 2)

    def test_single_microbatch(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh

        from trainingjob_operator_tpu.parallel.pipeline import gpipe

        devs = np.array(jax.devices()).reshape(2, 4)
        mesh = Mesh(devs, ("dp", "pp"))
        L, B, D = 4, 2, 8
        layers = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.2
        h = jax.random.normal(jax.random.PRNGKey(1), (B, D))

        def block(hh, w):
            return jnp.tanh(hh @ w)

        ref = h
        for i in range(L):
            ref = block(ref, layers[i])
        out = jax.jit(lambda ls, x: gpipe(block, ls, x, mesh, 1))(layers, h)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_partial_manual_shard_map_accepts_check_vma(self):
        """Callers always spell the replication-check kwarg ``check_vma=``;
        the probe translates it to whatever the installed jax accepts
        (``check_rep`` on older versions, dropped when absent), so a version
        skew downgrades to the documented fallback instead of a trace-time
        TypeError."""
        from trainingjob_operator_tpu.parallel.pipeline import (
            partial_manual_shard_map)

        shmap = partial_manual_shard_map()
        if shmap is None:
            pytest.skip("no partial-manual shard_map in this jax")
        mesh = make_mesh(MeshSpec.of(dp=8))
        fn = shmap(lambda x: x * 2.0, mesh=mesh, in_specs=P("dp"),
                   out_specs=P("dp"), axis_names=frozenset({"dp"}),
                   check_vma=False)
        x = jnp.arange(8.0)
        np.testing.assert_allclose(np.asarray(jax.jit(fn)(x)),
                                   np.asarray(x) * 2.0)

    def test_check_vma_kwarg_translation(self):
        """The compat wrapper spells the replication-check kwarg for the
        installed jax: passed through when it accepts ``check_vma``,
        translated to ``check_rep`` on the rename, dropped when absent --
        unit-tested against fakes so every branch runs on any jax."""
        import inspect

        from trainingjob_operator_tpu.parallel.pipeline import (
            _adapt_check_kwarg)

        seen = {}

        def rep_style(f, mesh=None, in_specs=None, out_specs=None,
                      axis_names=None, check_rep=True):
            seen["check_rep"] = check_rep
            return f

        wrapped = _adapt_check_kwarg(
            rep_style, inspect.signature(rep_style).parameters)
        assert wrapped(lambda x: x + 1, check_vma=False)(1) == 2
        assert seen["check_rep"] is False

        def no_check(f, axis_names=None):
            return f

        wrapped = _adapt_check_kwarg(
            no_check, inspect.signature(no_check).parameters)
        # check_vma is silently dropped rather than raising TypeError.
        assert wrapped(lambda x: x * 3, check_vma=False)(2) == 6

        def vma_style(f, axis_names=None, check_vma=True):
            return f

        assert _adapt_check_kwarg(
            vma_style, inspect.signature(vma_style).parameters) is vma_style


class TestPipelineFlashAttention:
    """The pp path runs the real Pallas flash kernel (VERDICT r4 #2): the
    stage body is a partial-manual shard_map over pp, and the kernel nests a
    second partial-manual shard_map over data/tp (flash_attention_pp) --
    attention no longer silently downgrades to attention_xla under pp."""

    def _mesh(self):
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices()).reshape(2, 2, 2)
        return Mesh(devs, ("pp", "fsdp", "tp"))

    def test_pp_uses_pallas_kernel_not_xla_fallback(self, monkeypatch):
        """With attention_xla poisoned, the pipelined forward still runs --
        proof the Pallas kernel (interpret mode) is on the pp path -- and
        matches the dense forward."""
        import importlib

        import jax

        from trainingjob_operator_tpu.parallel.pipeline import (
            partial_manual_shard_map)

        if partial_manual_shard_map() is None:
            # Tracking condition: partial-manual shard_map (axis_names=)
            # landed in jax 0.8; until the runtime has it, gpipe documents
            # the attention_xla fallback this test deliberately poisons.
            pytest.skip("partial-manual shard_map needs jax>=0.8; gpipe "
                        "falls back to attention_xla on this runtime")

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.parallel.sharding import shard_pytree

        # The ops package re-exports the flash_attention FUNCTION under the
        # module's name; reach the module itself for monkeypatching.
        fa = importlib.import_module(
            "trainingjob_operator_tpu.ops.flash_attention")

        monkeypatch.setenv("TRAININGJOB_PALLAS", "interpret")

        def poisoned(*a, **k):
            raise AssertionError("pp path fell back to attention_xla")

        monkeypatch.setattr(fa, "attention_xla", poisoned)

        mesh = self._mesh()
        cfg = llama.LlamaConfig.tiny(n_layers=4)
        cfg32 = llama.LlamaConfig(**{**cfg.__dict__, "dtype": "float32"})
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        dense = llama.forward(params, tokens, cfg32)
        sharded = shard_pytree(params, llama.sharding_rules(pipeline=True),
                               mesh)
        # mb = B/M = 2, divisible by fsdp=2; heads 4 / kv-heads 2 tile tp=2.
        piped = jax.jit(lambda p, t: llama.forward(
            p, t, cfg32, mesh=mesh, n_microbatches=2))(sharded, tokens)
        assert np.allclose(np.asarray(piped), np.asarray(dense),
                           rtol=1e-4, atol=1e-4)

    def test_pp_grads_flow_through_pallas(self, monkeypatch):
        import jax

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.parallel.sharding import shard_pytree

        monkeypatch.setenv("TRAININGJOB_PALLAS", "interpret")
        mesh = self._mesh()
        cfg = llama.LlamaConfig.tiny(n_layers=4)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        sharded = shard_pytree(params, llama.sharding_rules(pipeline=True),
                               mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size)
        loss, grads = jax.jit(jax.value_and_grad(lambda p: llama.loss_fn(
            p, {"tokens": tokens}, cfg, mesh=mesh)))(sharded)
        assert np.isfinite(float(loss))
        flat = jax.tree.leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
        assert any(float(np.abs(np.asarray(g)).max()) > 0 for g in flat)

    def test_untileable_microbatch_falls_back_not_raises(self):
        """mb=1 cannot tile fsdp=2: flash_attention_pp must degrade to the
        XLA path (correct math), never error."""
        import jax

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.parallel.sharding import shard_pytree

        mesh = self._mesh()
        cfg = llama.LlamaConfig.tiny(n_layers=4)
        cfg32 = llama.LlamaConfig(**{**cfg.__dict__, "dtype": "float32"})
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        dense = llama.forward(params, tokens, cfg32)
        sharded = shard_pytree(params, llama.sharding_rules(pipeline=True),
                               mesh)
        piped = jax.jit(lambda p, t: llama.forward(
            p, t, cfg32, mesh=mesh, n_microbatches=4))(sharded, tokens)
        assert np.allclose(np.asarray(piped), np.asarray(dense),
                           rtol=1e-4, atol=1e-4)

    def test_bubble_fraction_formula(self):
        from trainingjob_operator_tpu.parallel.pipeline import bubble_fraction

        assert abs(bubble_fraction(2, 8) - 1 / 9) < 1e-9
        assert abs(bubble_fraction(4, 24) - 3 / 27) < 1e-9

    def test_microbatch_chooser(self):
        """choose_microbatches: explicit requests are honored verbatim;
        the default prefers a flashable count only when the added bubble
        stays bounded (never collapses M for a ~1.1x kernel win)."""
        from trainingjob_operator_tpu.models.llama import choose_microbatches

        # Default, B=8, dp*fsdp=2, pp=2, target 8: M=4 keeps mb=2 tiling
        # the data axes at ~equal bubble.
        assert choose_microbatches(8, 8, 2, 2, explicit=False) == 4
        # B=8, n_data=8, pp=4: only M=1 is flashable -- a 75% bubble; the
        # chooser must refuse the collapse and keep M=8.
        assert choose_microbatches(8, 24, 8, 4, explicit=False) == 8
        # Explicit request: largest divisor <= request, no second-guessing.
        assert choose_microbatches(8, 2, 8, 4, explicit=True) == 2
        # Everything-tiles case: max divisor under the target.
        assert choose_microbatches(16, 8, 1, 2, explicit=False) == 8


class TestMultisliceCompileClean:
    def test_multislice_compiles_without_involuntary_remat(self, capfd,
                                                           monkeypatch):
        """VERDICT r4 #5: the 6-axis multislice train step must compile with
        ZERO "Involuntary full rematerialization" warnings (each one is a
        replicate-then-repartition of a tensor on every step).  Fixed by the
        rmsnorm cotangent pin (models/llama.py pin_act) + the classic
        partitioner default (rendezvous.configure_partitioner)."""
        import jax
        import optax
        from jax.sharding import NamedSharding

        from trainingjob_operator_tpu.api import constants
        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
        from trainingjob_operator_tpu.workloads import rendezvous

        rendezvous.configure_partitioner()
        monkeypatch.setenv(constants.VIRTUAL_DEVICES_PER_SLICE_ENV, "4")
        rdv = rendezvous.from_env({
            "MEGASCALE_NUM_SLICES": "2", "MEGASCALE_SLICE_ID": "0",
            "TRAININGJOB_ELASTIC_REPLICAS": "2"})
        mesh = mesh_from_rendezvous(rdv, model_parallel=2)
        cfg = llama.LlamaConfig.tiny()
        params = shard_pytree(llama.init_params(cfg, jax.random.PRNGKey(0)),
                              llama.SHARDING_RULES, mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(tokens,
                                NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(lambda pp: llama.loss_fn(
                pp, {"tokens": t}, cfg, mesh=mesh))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        capfd.readouterr()  # drain
        p, o, l = step(params, opt, tokens)
        jax.block_until_ready(l)
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err
        assert np.isfinite(float(l))

    def test_pipeline_compiles_without_involuntary_remat(self, capfd):
        """Same guard for the pp path: the gpipe state pin (stage dim on pp
        + microbatch on the data axes) keeps the scan carry's sharding
        stable; without it the partitioner full-remats the [S, mb, T, D]
        state every tick."""
        import jax
        import optax
        from jax.sharding import Mesh, NamedSharding

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.workloads import rendezvous

        rendezvous.configure_partitioner()
        devs = np.array(jax.devices()).reshape(1, 2, 2, 2)
        mesh = Mesh(devs, ("dp", "pp", "fsdp", "tp"))
        cfg = llama.LlamaConfig.tiny(n_layers=4)
        params = shard_pytree(llama.init_params(cfg, jax.random.PRNGKey(0)),
                              llama.sharding_rules(pipeline=True), mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(tokens,
                                NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(lambda pp: llama.loss_fn(
                pp, {"tokens": t}, cfg, mesh=mesh))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        capfd.readouterr()
        p, o, l = step(params, opt, tokens)
        jax.block_until_ready(l)
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err
        assert np.isfinite(float(l))


class TestFitSpecAbsentAxes:
    def test_rule_axes_missing_from_mesh_are_dropped(self):
        from trainingjob_operator_tpu.parallel.sharding import fit_spec

        mesh = make_mesh(MeshSpec.of(dp=2, sp=4))  # no fsdp/tp axis
        assert fit_spec(P(None, "fsdp", "tp"), (2, 8, 8), mesh) == \
            P(None, None, None)
        assert fit_spec(P(("dp", "fsdp"), None), (8, 4), mesh) == \
            P("dp", None)


class TestMoEMultisliceCompileClean:
    def test_moe_multislice_compiles_without_involuntary_remat(self, capfd,
                                                               monkeypatch):
        """Same partitioner hygiene as the Llama family (precast_weights +
        pin_batch_act), verified on the 6-axis multislice mesh."""
        import jax
        import optax
        from jax.sharding import NamedSharding

        from trainingjob_operator_tpu.api import constants
        from trainingjob_operator_tpu.models import moe
        from trainingjob_operator_tpu.parallel.mesh import mesh_from_rendezvous
        from trainingjob_operator_tpu.workloads import rendezvous

        rendezvous.configure_partitioner()
        monkeypatch.setenv(constants.VIRTUAL_DEVICES_PER_SLICE_ENV, "4")
        rdv = rendezvous.from_env({
            "MEGASCALE_NUM_SLICES": "2", "MEGASCALE_SLICE_ID": "0",
            "TRAININGJOB_ELASTIC_REPLICAS": "2"})
        mesh = mesh_from_rendezvous(rdv, model_parallel=2)
        cfg = moe.MoEConfig.tiny()
        params = shard_pytree(moe.init_params(cfg, jax.random.PRNGKey(0)),
                              moe.SHARDING_RULES, mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(tokens,
                                NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(lambda pp: moe.loss_fn(
                pp, {"tokens": t}, cfg, mesh=mesh))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        capfd.readouterr()
        p, o, l = step(params, opt, tokens)
        jax.block_until_ready(l)
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err
        assert np.isfinite(float(l))


class TestRingAttentionBackward:
    """The custom ring backward (second ring pass from saved lse; dK/dV ride
    the rotating KV blocks home) against plain autodiff of the dense
    reference -- exact same math, O(T/sp) residual memory."""

    @pytest.mark.parametrize("axes,hq,hkv", [
        (dict(dp=1, sp=8), 2, 2),
        (dict(dp=2, sp=4), 4, 2),
        # tp-sharded heads inside the ring (the P(batch, sp, tp, None)
        # spec): the per-tp-shard GQA head-block mapping must still match
        # the dense reference's grads.
        (dict(fsdp=2, tp=2, sp=2), 4, 2),
    ])
    def test_grads_match_dense_reference(self, axes, hq, hkv):
        mesh = make_mesh(MeshSpec.of(**axes))
        dp, sp = axes.get("dp", 1) * axes.get("fsdp", 1), axes["sp"]
        B, T, D = 2 * dp, 16 * sp, 8
        key = jax.random.PRNGKey(7)
        kq, kk, kv, kg = jax.random.split(key, 4)
        q = jax.random.normal(kq, (B, T, hq, D), jnp.float32)
        k = jax.random.normal(kk, (B, T, hkv, D), jnp.float32)
        v = jax.random.normal(kv, (B, T, hkv, D), jnp.float32)
        w = jax.random.normal(kg, (B, T, hq, D), jnp.float32)

        def ref_loss(q, k, v):
            kk_ = (jnp.repeat(k, hq // hkv, axis=2) if hq != hkv else k)
            vv_ = (jnp.repeat(v, hq // hkv, axis=2) if hq != hkv else v)
            return (reference_attention(q, kk_, vv_, causal=True) * w).sum()

        g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

        data = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
        spec = P(data if len(data) > 1 else (data[0] if data else None),
                 "sp", None, None)
        qs, ks, vs = (jax.device_put(x, NamedSharding(mesh, spec))
                      for x in (q, k, v))

        def ring_loss(q, k, v):
            return (ring_attention_sharded(q, k, v, mesh, causal=True)
                    * w).sum()

        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(qs, ks, vs)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3)

    def test_attn_remat_anchor_reaches_ring(self):
        """Under the 'attn' policy the ring residuals are saved: the llama sp
        backward must not contain more ring forwards than 'none' does."""
        import re as _re

        from trainingjob_operator_tpu.models import llama

        cfg = llama.LlamaConfig.tiny(n_kv_heads=4)
        mesh = make_mesh(MeshSpec.of(sp=8))
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)

        def n_ppermutes(pol):
            f = jax.grad(lambda pp: llama.loss_fn(
                pp, {"tokens": tokens}, cfg, mesh=mesh,
                sequence_parallel=True, remat=pol))
            return len(_re.findall(r"ppermute",
                                   str(jax.make_jaxpr(f)(params))))

        none, attn, full = (n_ppermutes(p) for p in ("none", "attn", "full"))
        assert attn == none, (attn, none)
        assert full > attn, (full, attn)


class TestSpMeshCompileClean:
    def test_sp_train_step_compiles_without_involuntary_remat(self, capfd):
        """Ring-attention (sp) train step with attn remat: zero involuntary
        full remats.  Requires the vocab-over-(tp, fsdp) embedding layout
        (a D-sharded table forces a replicate-then-repartition of every
        lookup) and the tp-aware ring specs."""
        import jax
        import optax
        from jax.sharding import NamedSharding

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.workloads import rendezvous

        rendezvous.configure_partitioner()
        mesh = make_mesh(MeshSpec.of(fsdp=2, tp=2, sp=2))
        cfg = llama.LlamaConfig.tiny(n_kv_heads=4)
        params = shard_pytree(llama.init_params(cfg, jax.random.PRNGKey(0)),
                              llama.SHARDING_RULES, mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 65), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(tokens,
                                NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(lambda pp: llama.loss_fn(
                pp, {"tokens": t}, cfg, mesh=mesh, sequence_parallel=True,
                remat="attn"))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        capfd.readouterr()
        p, o, l = step(params, opt, tokens)
        jax.block_until_ready(l)
        err = capfd.readouterr().err
        assert "Involuntary full rematerialization" not in err
        assert np.isfinite(float(l))
