"""Control-plane chaos plane tests (docs/CHAOS.md).

Layered like the plane itself: plan determinism (fleet/chaos.py), the
shared retry policy (client/retry.py), fault injection through the
clientset/tracker proxies (client/chaos.py), informer survival of watch
drops with the by-job index regression, stale-list vs quorum-list
semantics, incident chaos-window attribution, and a small seeded fleet
run that must converge clean under chaos.
"""

import threading
import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.chaos import (
    ChaosMonkey,
    ChaosTracker,
    chaos_clientset,
)
from trainingjob_operator_tpu.client.informers import Informer
from trainingjob_operator_tpu.client.retry import (
    ApiTimeoutError,
    ApiUnavailableError,
    RetryPolicy,
    is_transient,
    retry_call,
    retrying_clientset,
)
from trainingjob_operator_tpu.client.tracker import (
    ConflictError,
    ObjectTracker,
)
from trainingjob_operator_tpu.controller.controller import job_index_key
from trainingjob_operator_tpu.core.objects import ObjectMeta, Pod
from trainingjob_operator_tpu.fleet.chaos import (
    FAULT_CONFLICT,
    FAULT_TIMEOUT,
    FAULT_UNAVAILABLE,
    WATCHED_KINDS,
    ChaosGenerator,
    ChaosPlan,
    ChaosProfile,
)
from trainingjob_operator_tpu.fleet.churn import ChurnProfile
from trainingjob_operator_tpu.fleet.harness import FleetHarness
from trainingjob_operator_tpu.obs.incident import IncidentRecorder
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry

from conftest import wait_for  # noqa: E402


def make_pod(name, job=None, namespace="default"):
    labels = {}
    if job is not None:
        labels = {constants.GROUP_NAME_LABEL: constants.GROUP_NAME,
                  constants.JOB_NAME_LABEL: job}
    return Pod(metadata=ObjectMeta(name=name, namespace=namespace,
                                   labels=labels))


def quiet_plan(**overrides) -> ChaosPlan:
    """A plan that injects nothing by itself: tests drive the proxies with
    hand-written decision streams / explicit drop_streams calls."""
    profile = ChaosProfile(seed=0, duration=1.0, latency_spikes=0,
                           watch_drops=0)
    defaults = dict(profile=profile, decisions={}, spikes=(), drops=(),
                    stale=())
    defaults.update(overrides)
    return ChaosPlan(**defaults)


def _retries_metric(verb):
    return METRICS.snapshot().get(
        f'trainingjob_api_retries_total{{verb="{verb}"}}', 0.0)


# -- fleet/chaos.py: seeded plan expansion ------------------------------------

class TestPlanDeterminism:
    def test_same_seed_same_plan_bytes(self):
        p = ChaosProfile(seed=42, duration=3.0, decisions_per_verb=500,
                         stale_decisions=100)
        a, b = ChaosGenerator(p).plan(), ChaosGenerator(p).plan()
        assert a.canonical() == b.canonical()
        assert a.digest() == b.digest()

    def test_different_seed_different_plan(self):
        base = dict(duration=3.0, decisions_per_verb=500, stale_decisions=100)
        a = ChaosGenerator(ChaosProfile(seed=1, **base)).plan()
        b = ChaosGenerator(ChaosProfile(seed=2, **base)).plan()
        assert a.digest() != b.digest()

    def test_plan_shape_matches_profile(self):
        p = ChaosProfile(seed=7, duration=4.0, decisions_per_verb=300,
                         latency_spikes=2, watch_drops=4, stale_decisions=50)
        plan = ChaosGenerator(p).plan()
        assert set(plan.decisions) == {"create", "update", "update_status",
                                       "delete"}
        assert all(len(s) == 300 for s in plan.decisions.values())
        # Conflicts only on the optimistic-concurrency verbs.
        assert FAULT_CONFLICT not in plan.decisions["create"]
        assert FAULT_CONFLICT not in plan.decisions["delete"]
        assert len(plan.spikes) == 2 and len(plan.drops) == 4
        assert all(0.0 <= s.start < s.end for s in plan.spikes)
        # Round-robin drop victims: every watched kind takes a hit.
        assert {d.kind for d in plan.drops} == set(WATCHED_KINDS)
        assert len(plan.stale) == 50


# -- client/retry.py: the shared bounded-retry-with-jitter --------------------

class TestRetryPolicy:
    def test_pause_is_jittered_exponential_and_capped(self):
        pol = RetryPolicy(attempts=5, base_delay=0.1, max_delay=0.3,
                          jitter=0.5)
        for retry, nominal in ((0, 0.1), (1, 0.2), (2, 0.3), (5, 0.3)):
            for _ in range(20):
                p = pol.pause(retry)
                assert nominal * 0.5 <= p <= nominal * 1.5

    def test_retry_call_recovers_and_counts(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ApiUnavailableError("brownout")
            return "ok"

        before = _retries_metric("unit")
        pol = RetryPolicy(attempts=5, base_delay=0.001, max_delay=0.01)
        assert retry_call(flaky, policy=pol, verb="unit") == "ok"
        assert calls["n"] == 3
        assert _retries_metric("unit") - before == 2.0

    def test_retry_call_exhausts_and_raises_last_error(self):
        pol = RetryPolicy(attempts=3, base_delay=0.001, max_delay=0.01)

        def always():
            raise ApiTimeoutError("dead")

        with pytest.raises(ApiTimeoutError):
            retry_call(always, policy=pol, verb="unit2")

    def test_conflict_is_not_transient(self):
        """Conflicts mean a stale read: blind re-submission can never win,
        so the shared policy hands them straight to the re-read loops."""
        assert not is_transient(ConflictError("stale"))
        calls = {"n": 0}

        def conflicted():
            calls["n"] += 1
            raise ConflictError("stale")

        pol = RetryPolicy(attempts=5, base_delay=0.001)
        with pytest.raises(ConflictError):
            retry_call(conflicted, policy=pol, verb="unit3")
        assert calls["n"] == 1

    def test_single_attempt_policy_disables_wrapping(self):
        cs = Clientset()
        assert retrying_clientset(cs, RetryPolicy(attempts=1)) is cs


# -- client/chaos.py: injection through the clientset -------------------------

class TestChaosClientset:
    def test_decisions_apply_in_call_order(self):
        plan = quiet_plan(decisions={
            "create": (FAULT_UNAVAILABLE, "ok", FAULT_TIMEOUT, "ok"),
            "update_status": (FAULT_CONFLICT, "ok"),
        })
        cs = chaos_clientset(Clientset(), ChaosMonkey(plan))
        with pytest.raises(ApiUnavailableError):
            cs.pods.create(make_pod("p0"))
        cs.pods.create(make_pod("p0"))          # decision 2: ok
        with pytest.raises(ApiTimeoutError):
            cs.pods.create(make_pod("p1"))      # decision 3, held then lost
        cs.pods.create(make_pod("p1"))          # decision 4: ok
        # Faulted calls never reached the tracker (pre-commit injection).
        assert cs.tracker.count("Pod") == 2
        # Reads pass through untouched -- no chaos decision is consumed.
        assert cs.pods.get("default", "p0").name == "p0"

    def test_conflict_stream_on_status_writes(self):
        from trainingjob_operator_tpu.api.types import TPUTrainingJob
        plan = quiet_plan(decisions={"update_status": (FAULT_CONFLICT, "ok")})
        cs = chaos_clientset(Clientset(), ChaosMonkey(plan))
        cs.trainingjobs.create(TPUTrainingJob(metadata=ObjectMeta(name="j")))
        got = cs.trainingjobs.get("default", "j")
        got.status.phase = "Running"
        with pytest.raises(ConflictError):
            cs.trainingjobs.update_status(got)
        cs.trainingjobs.update_status(got)       # decision 2: ok
        assert cs.trainingjobs.get("default", "j").status.phase == "Running"

    def test_retrying_clientset_absorbs_injected_faults(self):
        """The production layering: retry above chaos.  Transient injected
        faults are invisible to the caller; only the retry counter moves."""
        plan = quiet_plan(decisions={
            "create": (FAULT_UNAVAILABLE, FAULT_TIMEOUT, "ok"),
        })
        monkey = ChaosMonkey(plan)
        pol = RetryPolicy(attempts=5, base_delay=0.001, max_delay=0.01)
        cs = retrying_clientset(chaos_clientset(Clientset(), monkey), pol)
        before = _retries_metric("create")
        created = cs.pods.create(make_pod("p"))
        assert created.metadata.uid
        assert monkey.faults[FAULT_UNAVAILABLE] == 1
        assert monkey.faults[FAULT_TIMEOUT] == 1
        assert _retries_metric("create") - before == 2.0

    def test_decisions_past_stream_end_are_ok(self):
        plan = quiet_plan(decisions={"create": (FAULT_UNAVAILABLE,)})
        cs = chaos_clientset(Clientset(), ChaosMonkey(plan))
        with pytest.raises(ApiUnavailableError):
            cs.pods.create(make_pod("p"))
        for i in range(5):                      # chaos window over
            cs.pods.create(make_pod(f"q{i}"))
        assert cs.tracker.count("Pod") == 5


# -- stale lists vs quorum reads ----------------------------------------------

class TestStaleList:
    def test_stale_list_serves_previous_snapshot(self):
        monkey = ChaosMonkey(quiet_plan(stale=(False, True, False)))
        tracker = ChaosTracker(ObjectTracker(), monkey)
        tracker.create(make_pod("a"))
        assert len(tracker.list("Pod")) == 1     # decision 1: fresh, snapped
        tracker.create(make_pod("b"))
        stale = tracker.list("Pod")              # decision 2: lagging follower
        assert [p.name for p in stale] == ["a"]
        assert monkey.faults["stale_list"] == 1
        assert len(tracker.list("Pod")) == 2     # decision 3: fresh again

    def test_quorum_list_is_always_exact(self):
        monkey = ChaosMonkey(quiet_plan(stale=(True,) * 10))
        tracker = ChaosTracker(ObjectTracker(), monkey)
        tracker.create(make_pod("a"))
        tracker.list("Pod")                      # seed the snapshot
        tracker.create(make_pod("b"))
        assert len(tracker.quorum_list("Pod")) == 2
        assert monkey.faults["stale_list"] == 0  # quorum never consults chaos

    def test_stale_before_first_snapshot_falls_through_fresh(self):
        monkey = ChaosMonkey(quiet_plan(stale=(True,)))
        tracker = ChaosTracker(ObjectTracker(), monkey)
        tracker.create(make_pod("a"))
        assert len(tracker.list("Pod")) == 1     # nothing older to serve
        assert monkey.faults["stale_list"] == 0


# -- informer watch-drop survival (the by-job index regression) ---------------

class TestInformerWatchDrop:
    def test_drop_gap_relist_heals_store_and_by_job_index(self):
        """Kill the Pod stream, mutate the world during the resumption gap,
        and require the informer's reconnect+relist to heal BOTH the
        handler-visible state and the secondary by-job index -- the exact
        delta-loss window a real apiserver watch break opens."""
        monkey = ChaosMonkey(quiet_plan())
        tracker = ChaosTracker(ObjectTracker(), monkey)
        informer = Informer(tracker, Pod.KIND)
        informer.add_index(constants.JOB_INDEX, job_index_key)
        adds, dels = [], []
        informer.add_event_handler(
            on_add=lambda o: adds.append(o.name),
            on_delete=lambda o: dels.append(o.name))

        tracker.create(make_pod("p0", job="jobA"))
        tracker.create(make_pod("p1", job="jobA"))
        assert wait_for(lambda: sorted(adds) == ["p0", "p1"])
        assert len(informer.by_index(constants.JOB_INDEX,
                                     "default/jobA")) == 2

        tracker.drop_streams(Pod.KIND, gap=0.05)
        # Deltas committed inside the gap flow past the dead stream:
        tracker.delete("Pod", "default", "p0", grace_period=0)
        tracker.create(make_pod("p2", job="jobA"))
        tracker.create(make_pod("p3", job="jobB"))

        # The gap timer fires on_error; the informer reconnects + relists.
        assert wait_for(lambda: informer.relists_total == 1, timeout=10.0)
        assert wait_for(lambda: "p0" in dels and "p2" in adds, timeout=10.0)
        job_a = {p.name for p in informer.by_index(constants.JOB_INDEX,
                                                   "default/jobA")}
        assert job_a == {"p1", "p2"}            # no entry lost, none leaked
        job_b = {p.name for p in informer.by_index(constants.JOB_INDEX,
                                                   "default/jobB")}
        assert job_b == {"p3"}

        # And the reconnected stream is live: post-recovery events flow.
        tracker.create(make_pod("p4", job="jobB"))
        assert wait_for(lambda: "p4" in adds, timeout=10.0)
        assert {p.name for p in informer.by_index(
            constants.JOB_INDEX, "default/jobB")} == {"p3", "p4"}
        informer.stop()

    def test_subscriber_without_on_error_loses_gap_deltas(self):
        """Pin the legacy hazard the hardened informer exists to close: a
        plain watch (no on_error) is silently resubscribed after the gap
        and the deltas committed inside it are simply gone."""
        monkey = ChaosMonkey(quiet_plan())
        tracker = ChaosTracker(ObjectTracker(), monkey)
        seen = []
        tracker.watch("Pod", lambda e: seen.append((e.type, e.obj.name)))
        tracker.create(make_pod("before"))
        tracker.drop_streams("Pod", gap=0.05)
        tracker.create(make_pod("during"))      # lost: stream is down
        # Poll with uniquely named probes until the silent resubscribe (at
        # gap end) makes one visible on the stream again.
        probe = iter(range(10000))
        assert wait_for(
            lambda: (tracker.create(make_pod(f"probe{next(probe)}")) or True)
            and any(n.startswith("probe") for _, n in seen),
            timeout=10.0)
        assert ("ADDED", "during") not in seen

    def test_unsubscribe_during_gap_is_not_resurrected(self):
        monkey = ChaosMonkey(quiet_plan())
        tracker = ChaosTracker(ObjectTracker(), monkey)
        seen = []
        unsub = tracker.watch("Pod", lambda e: seen.append(e.obj.name))
        tracker.drop_streams("Pod", gap=0.05)
        unsub()                                  # caller quit mid-gap
        time.sleep(0.15)
        tracker.create(make_pod("late"))
        time.sleep(0.05)
        assert seen == []


# -- chaos monkey lifecycle ---------------------------------------------------

class TestChaosMonkey:
    def test_windows_only_exist_after_attach(self):
        from trainingjob_operator_tpu.fleet.chaos import LatencySpike, WatchDrop
        plan = quiet_plan(
            spikes=(LatencySpike(start=1.0, end=1.5, delay=0.01),),
            drops=(WatchDrop(at=2.0, gap=0.1, kind="Pod"),))
        monkey = ChaosMonkey(plan)
        assert monkey.windows_abs() == []        # no run clock yet
        monkey.maybe_spike()                     # no-op before attach
        monkey.attach()
        try:
            windows = monkey.windows_abs()
            kinds = sorted(k for k, _, _ in windows)
            assert kinds == ["latency", "watch_drop"]
            for _, start, end in windows:
                assert end > start
        finally:
            monkey.close()

    def test_close_cancels_pending_drops(self):
        from trainingjob_operator_tpu.fleet.chaos import WatchDrop
        plan = quiet_plan(drops=(WatchDrop(at=30.0, gap=0.1, kind="Pod"),))
        monkey = ChaosMonkey(plan)
        tracker = ChaosTracker(ObjectTracker(), monkey)
        fired = threading.Event()
        tracker.watch("Pod", lambda e: None,
                      on_error=lambda err: fired.set())
        monkey.attach()
        monkey.close()
        assert not fired.wait(0.2)               # timer was cancelled


# -- incident chaos-window attribution ----------------------------------------

class TestIncidentChaosAttribution:
    JOB = "default/chaosjob"

    def _restart_window(self, rec, t0):
        rec.on_interruption(self.JOB, "ALL", constants.RESTARTING_REASON,
                            now=t0)
        rec.record_event(self.JOB, constants.RESTARTING_REASON, "restarting",
                         ts=t0 + 0.2)
        rec.on_running(self.JOB, now=t0 + 2.0)

    def test_bundle_carries_clipped_overlapping_windows(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        rec.record_chaos_window("latency", 99.5, 100.5)     # clips to 0.5 s
        rec.record_chaos_window("watch_drop", 101.0, 101.2)  # inside: 0.2 s
        rec.record_chaos_window("latency", 300.0, 301.0)     # disjoint
        self._restart_window(rec, t0=100.0)
        (bundle,) = rec.bundles(self.JOB)
        kinds = [w["kind"] for w in bundle["chaos_windows"]]
        assert sorted(kinds) == ["latency", "watch_drop"]
        spans = {w["kind"]: (w["start"], w["end"])
                 for w in bundle["chaos_windows"]}
        assert spans["latency"] == (100.0, 100.5)
        assert spans["watch_drop"] == (101.0, 101.2)
        assert bundle["chaos_overlap_ms"] == pytest.approx(700.0)

    def test_reassembly_is_byte_stable_with_chaos_windows(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        rec.record_chaos_window("watch_drop", 100.3, 100.9)
        self._restart_window(rec, t0=100.0)
        first = rec.bundle_json(self.JOB)
        assert first is not None and "chaos_windows" in first
        assert rec.reassemble(self.JOB) == first
        assert rec.reassemble(self.JOB) == first

    def test_clear_chaos_windows_stops_attribution(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        rec.record_chaos_window("latency", 99.0, 105.0)
        rec.clear_chaos_windows()
        self._restart_window(rec, t0=100.0)
        (bundle,) = rec.bundles(self.JOB)
        assert bundle["chaos_windows"] == []
        assert bundle["chaos_overlap_ms"] == 0.0


# -- the whole plane: seeded fleet run under chaos ----------------------------

class TestChaosFleet:
    def test_small_chaos_fleet_converges_clean(self):
        """The ISSUE gate in miniature: a seeded churn schedule with the
        apiserver browning out underneath must converge with zero invariant
        violations and zero unattributed downtime, and the run's plan digest
        must equal a from-scratch expansion of the same profile."""
        churn = ChurnProfile(jobs=16, duration=1.0, seed=9, replicas=(1, 3))
        chaos = ChaosProfile(seed=9, duration=3.0)
        harness = FleetHarness(churn, workers=4, resync_period=30.0,
                               gc_interval=30.0, converge_timeout=90.0,
                               chaos_profile=chaos)
        report = harness.run()
        assert report.converged, report.violations[:10]
        assert report.violations == []
        assert report.unattributed_downtime_ms == 0.0
        assert report.chaos is not None
        assert report.chaos["seed"] == 9
        assert (report.chaos["plan_digest"]
                == ChaosGenerator(chaos).plan().digest())
        assert set(report.phase_counts) <= {"Succeed", "Running", "Preempted"}
