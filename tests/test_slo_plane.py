"""Fleet SLO plane (docs/SLO.md): tsdb ring edge cases, burn-rate engine
transitions, incident stamping, the tracer's per-thread span registry, the
sampling profiler, and the /debug endpoint surface (index + error hygiene).

Unit layer first with private instances (a tsdb over its own registry,
driven with explicit timestamps -- sweeps and evaluation are pure functions
of the rings, so the tests pin the delta/clamp/burn arithmetic exactly),
then the live profiler against a real busy thread, then HTTP.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs import trace
from trainingjob_operator_tpu.obs.incident import IncidentRecorder
from trainingjob_operator_tpu.obs.profiler import SpanProfiler
from trainingjob_operator_tpu.obs.slo import SLOEngine, SLOSpec, default_slos
from trainingjob_operator_tpu.obs.trace import Tracer
from trainingjob_operator_tpu.obs.tsdb import TimeSeriesStore
from trainingjob_operator_tpu.utils.metrics import (
    MetricsRegistry,
    serve_metrics,
)

JOB = "default/slojob"


def _tsdb(reg, **kw):
    kw.setdefault("interval", 0.5)
    kw.setdefault("points", 240)
    kw.setdefault("max_series", 2048)
    return TimeSeriesStore(metrics=reg, **kw)


# -- tsdb ring-buffer edge cases ----------------------------------------------

class TestTsdbRings:
    def test_eviction_at_exactly_full_ring(self):
        reg = MetricsRegistry()
        val = [0.0]
        reg.gauge("g_load", lambda: val[0])
        ts = _tsdb(reg, points=4)
        for i in range(5):
            val[0] = float(i)
            ts.sample(now=float(i + 1))
        points = ts.series("g_load")
        # Ring holds exactly `points`; the oldest sweep fell off.
        assert len(points) == 4
        assert points[0] == (2.0, 1.0)
        assert points[-1] == (5.0, 4.0)

    def test_counter_deltaified_and_reset_clamped_at_zero(self):
        reg = MetricsRegistry()
        reg.inc("c_ops", 5.0)
        ts = _tsdb(reg)
        ts.sample(now=1.0)           # first sighting: history, not a delta
        reg.inc("c_ops", 3.0)
        ts.sample(now=2.0)
        reg._counters["c_ops"] = 2.0  # simulate a counter reset/backwards
        ts.sample(now=3.0)
        assert ts.series("c_ops") == [(1.0, 0.0), (2.0, 3.0), (3.0, 0.0)]

    def test_histogram_materializes_delta_and_sampled_stats(self):
        reg = MetricsRegistry()
        reg.observe("h_ms", 10.0)
        reg.observe("h_ms", 30.0)
        ts = _tsdb(reg)
        ts.sample(now=1.0)
        reg.observe("h_ms", 20.0)
        ts.sample(now=2.0)
        assert ts.series("h_ms_count") == [(1.0, 0.0), (2.0, 1.0)]
        assert ts.series("h_ms_max")[-1] == (2.0, 30.0)
        assert ts.series("h_ms_p99") is not None

    def test_cardinality_cap_rejects_counted_once_per_name(self):
        reg = MetricsRegistry()
        for name in ("g_a", "g_b", "g_c"):
            reg.gauge(name, lambda: 1.0)
        ts = _tsdb(reg, max_series=2)
        ts.sample(now=1.0)
        # g_c rejected and counted -- audibly, in the registry too.
        assert ts.dropped_series == 1
        assert any(k.startswith("trainingjob_tsdb_series_dropped_total")
                   for k in reg.snapshot())
        # The drop counter itself becomes a (rejected) series next sweep;
        # counted once, then the dedup set silences repeats forever.
        ts.sample(now=2.0)
        assert ts.dropped_series == 2
        ts.sample(now=3.0)
        ts.sample(now=4.0)
        assert ts.dropped_series == 2
        assert ts.names() == ["g_a", "g_b"]

    def test_sparkline_and_summary(self):
        reg = MetricsRegistry()
        val = [0.0]
        reg.gauge("g_ramp", lambda: val[0])
        ts = _tsdb(reg)
        for i in range(8):
            val[0] = float(i)
            ts.sample(now=float(i))
        text = ts.render_sparklines()
        assert "g_ramp" in text and "[0..7]" in text
        summary = ts.summary()
        assert summary["series_count"] == 1
        assert summary["series"]["g_ramp"] == {"n": 8, "last": 7.0}
        assert ts.series("nope") is None and ts.window("nope", 0.0) == []


# -- burn-rate engine ---------------------------------------------------------

def _engine(reg, ts, incidents=None):
    eng = SLOEngine(tsdb=ts, metrics=reg, incidents=incidents
                    if incidents is not None
                    else IncidentRecorder(metrics=MetricsRegistry()))
    eng.short_s, eng.long_s = 2.0, 6.0
    eng.burn_threshold = 4.0
    return eng


LAT_SPEC = SLOSpec(name="latency", objective="lat_ms stays under 1.0",
                   series_prefix="g_lat", reduce="max", op="<=",
                   threshold=1.0, target=0.99, min_points=4)


class TestSLOEngine:
    def test_breach_and_recovery_transitions_fire_sink_once(self):
        reg = MetricsRegistry()
        val = [5.0]
        reg.gauge("g_lat", lambda: val[0])
        ts = _tsdb(reg)
        rec = IncidentRecorder(metrics=MetricsRegistry())
        eng = _engine(reg, ts, incidents=rec)
        eng.configure((LAT_SPEC,))
        fired = []
        eng.set_event_sink(lambda n, r, m: fired.append((n, r)))

        # 8 bad ticks, 0.5 s apart -> both windows burn at 100x budget.
        for i in range(8):
            ts.sample(now=0.5 * (i + 1))
        eng.evaluate(now=4.0)
        st = eng.verdicts()["slos"]["latency"]
        assert st["breached"] and st["breaches"] == 1
        assert st["burn_short"] == 100.0 and st["burn_long"] == 100.0
        assert fired == [("latency", constants.SLO_BREACH_REASON)]
        assert any(k.startswith("trainingjob_slo_breaches_total")
                   for k in reg.snapshot())
        # Still breached on re-evaluation: no duplicate event.
        eng.evaluate(now=4.0)
        assert len(fired) == 1

        # Good ticks fill the short window -> burn 0 -> recovery.
        val[0] = 0.5
        for i in range(8):
            ts.sample(now=4.5 + 0.5 * i)
        eng.evaluate(now=8.0)
        st = eng.verdicts()["slos"]["latency"]
        assert not st["breached"] and st["recoveries"] == 1
        assert fired[-1] == ("latency", constants.SLO_RECOVERED_REASON)

    def test_no_verdict_below_min_points(self):
        reg = MetricsRegistry()
        reg.gauge("g_lat", lambda: 99.0)  # always bad
        ts = _tsdb(reg)
        eng = _engine(reg, ts)
        eng.configure((LAT_SPEC,))
        for i in range(3):                # min_points is 4
            ts.sample(now=0.5 * (i + 1))
        eng.evaluate(now=1.5)
        assert not eng.verdicts()["slos"]["latency"]["breached"]

    def test_avg_reduce_across_label_sets(self):
        reg = MetricsRegistry()
        reg.gauge("g_lat", lambda: 0.0, job="a")
        reg.gauge("g_lat", lambda: 4.0, job="b")
        ts = _tsdb(reg)
        eng = _engine(reg, ts)
        spec = SLOSpec(name="avg", objective="avg", series_prefix="g_lat",
                       reduce="avg", op="<=", threshold=3.0)
        eng.configure((spec,))
        for i in range(5):
            ts.sample(now=0.5 * (i + 1))
        eng.evaluate(now=2.5)
        st = eng.verdicts()["slos"]["avg"]
        assert st["last"] == 2.0 and not st["breached"]

    def test_default_slos_shape(self):
        names = [s.name for s in default_slos()]
        assert names == ["event_visible_p99", "detect_running_p99",
                         "goodput_floor", "serve_token_p99", "ttft_p99"]


# -- incident stamping --------------------------------------------------------

def _restart_window(rec, t0):
    rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=t0)
    rec.record_event(JOB, constants.RESTARTING_REASON, "restarting",
                     ts=t0 + 0.2)
    rec.on_running(JOB, now=t0 + 1.0)


class TestIncidentStamping:
    def test_overlapping_breach_episode_stamps_bundle(self):
        rec = IncidentRecorder(metrics=MetricsRegistry())
        rec.record_slo_breach("latency", 4.0)
        _restart_window(rec, t0=5.0)          # inside the open episode
        (bundle,) = rec.bundles(JOB)
        assert bundle["slo_breaches"] == ["latency"]

    def test_closed_episode_does_not_stamp_later_bundle(self):
        rec = IncidentRecorder(metrics=MetricsRegistry())
        rec.record_slo_breach("latency", 4.0)
        rec.record_slo_recovered("latency", 8.0)
        _restart_window(rec, t0=10.0)         # entirely after the episode
        (bundle,) = rec.bundles(JOB)
        # Absent key, not an empty list: healthy bundles stay byte-identical
        # to pre-SLO-plane serializations.
        assert "slo_breaches" not in bundle

    def test_clear_resets_episodes(self):
        rec = IncidentRecorder(metrics=MetricsRegistry())
        rec.record_slo_breach("latency", 4.0)
        rec.clear_slo_breaches()
        _restart_window(rec, t0=5.0)
        (bundle,) = rec.bundles(JOB)
        assert "slo_breaches" not in bundle


# -- per-thread span registry (obs/trace.py) ----------------------------------

class TestThreadSpanRegistry:
    def test_nested_stack_root_first_and_exit_restores(self):
        tracer = Tracer()
        ident = threading.get_ident()
        trace.enable_span_registry()
        try:
            with tracer.span("outer"):
                with tracer.span("inner"):
                    assert trace.thread_span_stack(ident) == ("outer",
                                                              "inner")
                assert trace.thread_span_stack(ident) == ("outer",)
            assert trace.thread_span_stack(ident) == ()
        finally:
            trace.disable_span_registry()

    def test_disabled_registry_records_nothing(self):
        tracer = Tracer()
        ident = threading.get_ident()
        with tracer.span("outer"):
            assert trace.thread_span_stack(ident) == ()


# -- sampling profiler --------------------------------------------------------

class TestSpanProfiler:
    def test_attributes_busy_worker_cpu_to_spans(self):
        reg = MetricsRegistry()
        prof = SpanProfiler(metrics=reg, interval_ms=2.0, seed=0)
        tracer = Tracer()
        stop = threading.Event()

        def burn():
            with tracer.span("sync_job"):
                x = 1
                while not stop.is_set():
                    x = (x * 31 + 7) % 1000003

        # Profiler first: the span registry must be live before the burn
        # thread enters its span, or the sample has nothing to attribute.
        prof.start()
        th = threading.Thread(target=burn, daemon=True,
                              name="trainingjob-worker-t0")
        try:
            th.start()
            time.sleep(0.4)
        finally:
            stop.set()
            th.join(timeout=2.0)
            prof.stop()
        rep = prof.report()
        assert rep["samples_total"] > 0 and rep["busy_samples"] > 0
        attr = rep["span_attribution"]
        assert attr["worker_busy"] > 0 and attr["worker_attributed"] > 0
        # The burn thread spends its whole life inside sync_job; only the
        # sliver between thread start and span entry can miss.
        assert attr["ratio"] >= 0.5
        assert any(row["spans"].startswith("sync_job")
                   for row in rep["top"])
        assert "burn" in prof.collapsed()
        assert 0.0 <= rep["overhead_ratio"] < 1.0
        assert any(k.startswith("trainingjob_profiler_samples_total")
                   for k in reg.snapshot())

    def test_noop_until_started_and_reset(self):
        prof = SpanProfiler(metrics=MetricsRegistry(), interval_ms=2.0,
                            seed=0)
        rep = prof.report()
        assert rep["samples_total"] == 0 and not rep["running"]
        prof.reset()
        assert prof.collapsed() == "\n"


# -- /debug endpoint surface --------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


def _get_err(port, path):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(port, path)
    return exc.value.code, exc.value.read().decode()


class TestDebugEndpoints:
    @pytest.fixture
    def server(self):
        reg = MetricsRegistry()
        reg.gauge("g_lat", lambda: 1.0)
        ts = _tsdb(reg)
        ts.sample(now=1.0)
        ts.sample(now=2.0)
        eng = _engine(reg, ts)
        eng.configure((LAT_SPEC,))
        eng.evaluate(now=2.0)
        prof = SpanProfiler(metrics=reg, interval_ms=5.0, seed=0)
        tracer = Tracer()
        with tracer.span("sync_job", job=JOB):
            pass
        srv = serve_metrics(0, reg, tracer=tracer, events_fn=lambda: [],
                            tsdb=ts, slos=eng, profiler=prof)
        yield srv.server_address[1]
        srv.shutdown()

    def test_debug_index_enumerates_routes(self, server):
        status, body = _get(server, "/debug")
        doc = json.loads(body)
        assert status == 200 and doc["count"] == len(doc["routes"])
        by_path = {r["path"]: r for r in doc["routes"]}
        assert by_path["/debug/timeseries"]["enabled"]
        assert by_path["/debug/slo"]["enabled"]
        assert by_path["/debug/profile"]["enabled"]
        assert not by_path["/debug/incidents"]["enabled"]  # not wired here
        assert all(r["description"] for r in doc["routes"])

    def test_timeseries_summary_series_and_sparkline(self, server):
        status, body = _get(server, "/debug/timeseries")
        doc = json.loads(body)
        assert status == 200 and doc["series_count"] == 1
        status, body = _get(server, "/debug/timeseries?series=g_lat")
        doc = json.loads(body)
        assert status == 200 and doc["points"] == [[1.0, 1.0], [2.0, 1.0]]
        status, body = _get(server,
                            "/debug/timeseries?format=sparkline")
        assert status == 200 and "g_lat" in body

    def test_timeseries_unknown_series_404(self, server):
        code, _ = _get_err(server, "/debug/timeseries?series=nope")
        assert code == 404

    def test_timeseries_bad_format_400(self, server):
        code, body = _get_err(server, "/debug/timeseries?format=csv")
        assert code == 400 and "csv" in body

    def test_slo_verdicts_and_bad_format_400(self, server):
        status, body = _get(server, "/debug/slo")
        doc = json.loads(body)
        assert status == 200 and "latency" in doc["slos"]
        assert doc["windows"]["burn_threshold"] == 4.0
        code, body = _get_err(server, "/debug/slo?format=xml")
        assert code == 400 and "xml" in body

    def test_profile_report_collapsed_and_bad_format_400(self, server):
        status, body = _get(server, "/debug/profile")
        assert status == 200 and "span_attribution" in json.loads(body)
        status, _ = _get(server, "/debug/profile?format=collapsed")
        assert status == 200
        code, body = _get_err(server, "/debug/profile?format=pprof")
        assert code == 400 and "pprof" in body

    def test_events_bad_format_400(self, server):
        code, body = _get_err(server, "/debug/events?format=yaml")
        assert code == 400 and "yaml" in body

    def test_unwired_routes_404(self):
        srv = serve_metrics(0, MetricsRegistry())
        port = srv.server_address[1]
        try:
            for path in ("/debug/timeseries", "/debug/slo",
                         "/debug/profile"):
                code, _ = _get_err(port, path)
                assert code == 404
        finally:
            srv.shutdown()
