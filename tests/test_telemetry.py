"""Replica telemetry plane: aggregator semantics, the emitter->sink wire,
the /debug/steps endpoint, and the sim e2e stall acceptance.

Unit layer first (ingest/percentiles/skew/MFU/stall/resume against a private
aggregator + registry, no globals), then the TCP line-protocol wire, then
HTTP (the scrape pattern of test_obs.py), then e2e: a sim job with one
annotation-stalled replica must produce a StepStalled event, a nonzero
straggler-skew sample on /metrics, and a /debug/steps table showing the
lagging rank.
"""

import json
import urllib.error
import urllib.request

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.goodput import GoodputTracker
from trainingjob_operator_tpu.obs.telemetry import (
    TELEMETRY,
    TelemetryAggregator,
    TelemetryEmitter,
    TelemetrySink,
    clear_sink_address,
    peak_flops_for_accelerator,
    publish_sink_address,
    sink_address,
)
from trainingjob_operator_tpu.utils.metrics import (
    METRICS,
    MetricsRegistry,
    serve_metrics,
)

from conftest import wait_for  # noqa: E402

JOB = "default/tjob"


def _agg(**kw):
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("goodput", GoodputTracker(metrics=kw["metrics"]))
    return TelemetryAggregator(**kw)


def _rec(rank=0, step=0, ms=100.0, job=JOB, rtype="worker", **extra):
    rec = {"v": 1, "job": job, "rtype": rtype, "rank": rank, "step": step,
           "ms": ms}
    rec.update(extra)
    return rec


def _feed(agg, ranks=1, steps=10, ms=100.0, t0=1000.0, slow=None,
          slow_factor=3.0, **extra):
    """steps records per rank, 0.1 s apart; ``slow`` rank gets slower steps."""
    now = t0
    for step in range(steps):
        now = t0 + step * 0.1
        for rank in range(ranks):
            step_ms = ms * (slow_factor if rank == slow else 1.0)
            assert agg.ingest(_rec(rank=rank, step=step, ms=step_ms, **extra),
                              now=now)
    return now


# -- aggregator unit layer ----------------------------------------------------

class TestAggregatorIngest:
    def test_percentiles_and_table(self):
        agg = _agg()
        for i, ms in enumerate([10.0] * 9 + [100.0]):
            agg.ingest(_rec(step=i, ms=ms), now=1000.0 + i)
        table = agg.job_table(JOB, now=1010.0)
        row = table["replicas"][0]
        assert row["replica"] == "worker-0"
        assert row["step"] == 9
        assert row["median_ms"] == 10.0
        assert row["p90_ms"] == 100.0

    def test_malformed_records_counted_not_raised(self):
        reg = MetricsRegistry()
        agg = _agg(metrics=reg)
        bad = [
            {},                                   # no fields at all
            {"job": "nojslash", "step": 1, "ms": 5},  # job not ns/name
            _rec(step=-1),                        # negative step
            _rec(ms=0.0),                         # non-positive duration
            _rec(rank=-2),                        # negative rank
            {"job": JOB, "step": "x", "ms": 5},   # non-numeric step
        ]
        for rec in bad:
            assert agg.ingest(rec, now=1.0) is False
        assert agg.job_table(JOB) is None
        snap = reg.snapshot()
        assert snap["trainingjob_telemetry_malformed_total"] == len(bad)

    def test_pacer_dedup_tokens_per_sec_not_summed(self):
        # 4 SPMD ranks each report 1000 tokens per 100 ms step: the job rate
        # is one rank's rate (10k tokens/s), not 4x.
        agg = _agg()
        _feed(agg, ranks=4, steps=10, ms=100.0, tokens=1000)
        assert agg.tokens_per_sec(JOB) == pytest.approx(10000.0)

    def test_pacer_feeds_goodput_productive_steps(self):
        gp = GoodputTracker(metrics=MetricsRegistry())
        agg = _agg(goodput=gp)
        gp.on_running(JOB, 1000.0)
        _feed(agg, ranks=2, steps=10, ms=100.0)
        gp.on_complete(JOB, 1002.0)
        # 10 pacer steps x 0.1 s = 1 s productive over 2 s running.
        assert gp.ratio(JOB) == pytest.approx(0.5, abs=0.01)

    def test_straggler_skew_slowest_over_median(self):
        agg = _agg()
        _feed(agg, ranks=4, steps=10, slow=3, slow_factor=3.0)
        assert agg.straggler_skew(JOB, "worker") == pytest.approx(3.0)
        assert agg.straggler_skew(JOB, "nope") == 0.0

    def test_mfu_from_spec_peak(self):
        agg = _agg()
        # 100 ms/step at 2e12 FLOPs/step = 2e13 FLOP/s achieved.
        _feed(agg, steps=10, ms=100.0, flops=2e12)
        agg.set_peak_flops(JOB, 8e13)
        assert agg.mfu(JOB) == pytest.approx(0.25)

    def test_mfu_from_record_peak_and_unknown_is_none(self):
        agg = _agg()
        _feed(agg, steps=10, ms=100.0, flops=1e12, peak_flops=4e13)
        assert agg.mfu(JOB) == pytest.approx(0.25)
        agg2 = _agg()
        _feed(agg2, steps=10, ms=100.0)  # no flops, no peak
        assert agg2.mfu(JOB) is None

    def test_accelerator_peak_table(self):
        assert peak_flops_for_accelerator("tpu-v5-lite-podslice") > 0
        assert peak_flops_for_accelerator("tpu-v4-podslice") > 0
        assert peak_flops_for_accelerator("warehouse-gpu") == 0.0


class TestStallWatchdog:
    def test_stall_fires_event_and_counter_then_resume(self):
        reg = MetricsRegistry()
        agg = _agg(metrics=reg)
        events = []
        agg.set_event_sink(lambda k, r, m: events.append((k, r, m)))
        now = _feed(agg, ranks=2, steps=10, ms=100.0)

        # Not yet: below threshold (max(8 x 0.1 s, 2 s floor) = 2 s).
        agg.check_stalls(now=now + 1.0)
        assert not events

        agg.check_stalls(now=now + 3.0)
        reasons = [r for _, r, _ in events]
        assert reasons.count(constants.STEP_STALLED_REASON) == 2
        assert agg.stalled_count(JOB) == 2
        assert "worker-0" in events[0][2] and "stuck at step 9" in events[0][2]
        snap = reg.snapshot()
        key = ('trainingjob_steps_stalled_total'
               '{job="default/tjob",rtype="worker"}')
        assert snap[key] == 2.0
        # No re-fire while still stalled.
        agg.check_stalls(now=now + 10.0)
        assert len(events) == 2

        # Progress: StepResumed, stalled gauge falls back to 0.
        agg.ingest(_rec(rank=0, step=10), now=now + 11.0)
        agg.ingest(_rec(rank=1, step=10), now=now + 11.0)
        resumed = [r for _, r, _ in events
                   if r == constants.STEP_RESUMED_REASON]
        assert len(resumed) == 2
        assert agg.stalled_count(JOB) == 0

    def test_needs_three_steps_before_arming(self):
        agg = _agg()
        events = []
        agg.set_event_sink(lambda k, r, m: events.append(r))
        agg.ingest(_rec(step=0), now=1000.0)
        agg.ingest(_rec(step=1), now=1000.1)
        agg.check_stalls(now=9999.0)
        assert not events

    def test_interruption_suspends_and_clears_replicas(self):
        agg = _agg()
        events = []
        agg.set_event_sink(lambda k, r, m: events.append(r))
        now = _feed(agg, ranks=2, steps=10)
        agg.on_interruption(JOB)
        # Replicas renumber across a restart/resize: stale per-rank state
        # must not page while the drain kills pods on purpose.
        agg.check_stalls(now=now + 100.0)
        assert not events
        assert agg.job_table(JOB)["replicas"] == []

    def test_completed_job_drops_late_records(self):
        agg = _agg()
        _feed(agg, steps=5)
        agg.on_complete(JOB)
        assert agg.ingest(_rec(step=99), now=2000.0)  # accepted, dropped
        assert agg.job_table(JOB)["replicas"][0]["step"] == 4

    def test_forget_removes_gauges(self):
        reg = MetricsRegistry()
        agg = _agg(metrics=reg)
        _feed(agg, steps=5, tokens=100)
        assert any("trainingjob_tokens_per_sec" in k
                   for k in reg.snapshot())
        agg.forget(JOB)
        assert not any("trainingjob_tokens_per_sec" in k
                       for k in reg.snapshot())
        assert agg.job_table(JOB) is None


class TestStatusLine:
    def test_snapshot_and_cache(self):
        agg = _agg()
        _feed(agg, steps=10, ms=100.0, tokens=1000, t0=1000.0)
        line = agg.status_line(JOB, now=1001.0)
        assert "step 9" in line and "tokens/s" in line
        # Cached: new steps don't show until the refresh window passes.
        agg.ingest(_rec(step=50), now=1002.0)
        assert agg.status_line(JOB, now=1002.0) == line
        fresh = agg.status_line(JOB, now=1001.0 + agg.status_refresh_seconds)
        assert "step 50" in fresh

    def test_empty_for_unknown_job(self):
        assert _agg().status_line("ns/none") == ""


# -- sink address publication (rendezvous env injection) ----------------------

class TestSinkAddressPublication:
    def test_publish_clear_owner_scoped(self):
        try:
            publish_sink_address("127.0.0.1:1111", owner="a")
            assert sink_address() == "127.0.0.1:1111"
            clear_sink_address(owner="b")  # not the publisher: no-op
            assert sink_address() == "127.0.0.1:1111"
            clear_sink_address(owner="a")
            assert sink_address() == ""
        finally:
            clear_sink_address()

    def test_pod_env_gets_telemetry_addr(self):
        from trainingjob_operator_tpu.api.types import (
            ReplicaSpec,
            TPUTrainingJob,
        )
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.controller.controller import (
            TrainingJobController,
        )
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ObjectMeta,
            Pod,
            PodSpec,
            PodTemplateSpec,
        )

        tc = TrainingJobController(Clientset())
        job = TPUTrainingJob(metadata=ObjectMeta(name="envj",
                                                 namespace="default"))
        spec = ReplicaSpec(replicas=1, template=PodTemplateSpec(
            spec=PodSpec(containers=[Container(name="aitj-main")])))
        job.spec.replica_specs["worker"] = spec

        def build_env():
            pod = Pod(metadata=ObjectMeta(name="p", namespace="default"),
                      spec=PodSpec(containers=[Container(name="aitj-main")]))
            tc.set_env(pod, job, spec, "worker", "0", "0")
            return {e.name: e.value for e in pod.spec.containers[0].env}

        try:
            clear_sink_address()
            assert constants.TELEMETRY_ADDR_ENV not in build_env()
            publish_sink_address("127.0.0.1:2222", owner="t")
            assert build_env()[constants.TELEMETRY_ADDR_ENV] == "127.0.0.1:2222"
        finally:
            clear_sink_address()


# -- the TCP wire -------------------------------------------------------------

class TestEmitterSinkWire:
    def test_records_flow_and_garbage_is_counted(self, monkeypatch):
        import socket

        reg = MetricsRegistry()
        agg = _agg(metrics=reg)
        sink = TelemetrySink(aggregator=agg, publish=False).start()
        try:
            monkeypatch.setenv(constants.TELEMETRY_ADDR_ENV, sink.address)
            monkeypatch.setenv(constants.JOB_NAMESPACE_ENV, "default")
            monkeypatch.setenv(constants.JOB_NAME_ENV, "wirejob")
            monkeypatch.setenv(constants.REPLICA_NAME_ENV, "Worker")
            monkeypatch.setenv(constants.REPLICA_INDEX_ENV, "1")
            em = TelemetryEmitter(units_per_step=64.0)
            assert em.enabled
            for i in range(5):
                em.emit(i, 12.5, loss=3.0 - i * 0.1)
            em.close()
            def last_step():
                rows = (agg.job_table("default/wirejob")
                        or {"replicas": []})["replicas"]
                return rows[0]["step"] if rows else -1

            # Wait for the *last* record: the sink drains the stream
            # record by record after the emitter has already closed.
            assert wait_for(lambda: last_step() == 4, 5)
            row = agg.job_table("default/wirejob")["replicas"][0]
            assert row["rtype"] == "worker" and row["rank"] == 1
            assert row["loss"] == pytest.approx(2.6)

            # Garbage on the wire: counted, never raises, sink stays up.
            host, _, port = sink.address.rpartition(":")
            with socket.create_connection((host, int(port)), timeout=2) as s:
                s.sendall(b"not json at all\n{}\n")
            assert wait_for(
                lambda: reg.snapshot().get(
                    "trainingjob_telemetry_malformed_total", 0) >= 2, 5)
        finally:
            sink.stop()

    def test_emitter_disabled_without_env(self, monkeypatch):
        monkeypatch.delenv(constants.TELEMETRY_ADDR_ENV, raising=False)
        em = TelemetryEmitter()
        assert not em.enabled
        em.emit(0, 1.0)  # no-op, no error
        em.close()

    def test_emitter_survives_dead_sink(self, monkeypatch):
        sink = TelemetrySink(aggregator=_agg(), publish=False).start()
        addr = sink.address
        sink.stop()
        monkeypatch.setenv(constants.TELEMETRY_ADDR_ENV, addr)
        monkeypatch.setenv(constants.JOB_NAMESPACE_ENV, "default")
        monkeypatch.setenv(constants.JOB_NAME_ENV, "deadjob")
        em = TelemetryEmitter(retry_seconds=0.0)
        for i in range(3):
            em.emit(i, 1.0)  # connection refused: swallowed
        em.close()

    def test_sink_publishes_and_unpublishes_address(self):
        try:
            sink = TelemetrySink(aggregator=_agg()).start()
            assert sink_address() == sink.address
            sink.stop()
            assert sink_address() == ""
        finally:
            clear_sink_address()


# -- /debug/steps + query-param edge cases ------------------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestDebugStepsEndpoint:
    @pytest.fixture
    def server(self):
        from trainingjob_operator_tpu.obs.trace import Tracer

        agg = _agg()
        _feed(agg, ranks=3, steps=10, ms=50.0, tokens=256, slow=2)
        tracer = Tracer()
        with tracer.span("sync_job", job=JOB):
            pass
        srv = serve_metrics(0, MetricsRegistry(), tracer=tracer,
                            events_fn=lambda: [], telemetry=agg)
        yield srv.server_address[1]
        srv.shutdown()

    def test_job_table_json(self, server):
        status, body = _get(server, f"/debug/steps?job={JOB}")
        doc = json.loads(body)
        assert status == 200 and doc["job"] == JOB
        assert [r["replica"] for r in doc["replicas"]] == ["worker-0",
                                                           "worker-1",
                                                           "worker-2"]
        assert doc["straggler_skew"]["worker"] == pytest.approx(3.0)

    def test_job_list_without_param(self, server):
        status, body = _get(server, "/debug/steps")
        doc = json.loads(body)
        assert status == 200 and doc == {"count": 1, "jobs": [JOB]}

    def test_text_format(self, server):
        status, body = _get(server, f"/debug/steps?job={JOB}&format=text")
        assert status == 200
        assert body.splitlines()[0].startswith("replica")
        assert "worker-2" in body

    def test_unknown_job_404_not_500(self, server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/debug/steps?job=no/such")
        assert exc.value.code == 404

    def test_404_without_telemetry_provider(self):
        srv = serve_metrics(0, MetricsRegistry())
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(srv.server_address[1], "/debug/steps")
            assert exc.value.code == 404
        finally:
            srv.shutdown()

    def test_traces_junk_limit_and_unknown_format(self, server):
        # ?limit=junk is an explicit 400 naming the bad value, never a 500
        # and never a silent fallback (docs/OBSERVABILITY.md).
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/debug/traces?limit=junk")
        assert exc.value.code == 400
        assert "junk" in exc.value.read().decode()
        # Unknown ?format= likewise 400s with the accepted values.
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server, "/debug/traces?format=starlight")
        assert exc.value.code == 400
        assert "starlight" in exc.value.read().decode()

    def test_events_with_no_matches_is_empty_not_error(self, server):
        status, body = _get(server, "/debug/events?job=absent/job")
        assert status == 200
        assert json.loads(body) == {"count": 0, "events": []}


# -- e2e: sim job with an injected stalled replica ----------------------------

class TestStallE2E:
    @pytest.fixture
    def cluster(self):
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.cmd.options import OperatorOptions
        from trainingjob_operator_tpu.controller.controller import (
            TrainingJobController,
        )
        from trainingjob_operator_tpu.runtime.sim import SimRuntime

        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        sim = SimRuntime(cs)
        sim.add_node("n0")
        sim.start()
        tc.run(workers=2)
        yield cs, tc, sim
        tc.stop()
        sim.stop()

    def test_stalled_replica_event_skew_and_step_table(self, cluster):
        from trainingjob_operator_tpu.api.types import (
            ReplicaSpec,
            TPUTrainingJob,
            TrainingJobPhase,
        )
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ContainerPort,
            ObjectMeta,
            PodSpec,
            PodTemplateSpec,
        )
        from trainingjob_operator_tpu.runtime.sim import (
            RUN_SECONDS_ANNOTATION,
            STALL_AT_STEP_ANNOTATION,
            STALL_RANK_ANNOTATION,
            STEP_MS_ANNOTATION,
            STRAGGLER_FACTOR_ANNOTATION,
            STRAGGLER_RANK_ANNOTATION,
            TOKENS_PER_STEP_ANNOTATION,
        )

        cs, tc, sim = cluster
        key = "default/stalljob"
        TELEMETRY.forget(key)
        job = TPUTrainingJob(
            metadata=ObjectMeta(name="stalljob", namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=3,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    RUN_SECONDS_ANNOTATION: "30",
                    STEP_MS_ANNOTATION: "20",
                    TOKENS_PER_STEP_ANNOTATION: "512",
                    STRAGGLER_RANK_ANNOTATION: "1",
                    STRAGGLER_FACTOR_ANNOTATION: "2.0",
                    STALL_RANK_ANNOTATION: "2",
                    STALL_AT_STEP_ANNOTATION: "10",
                }),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7745",
                                                   container_port=7745)])])))
        cs.trainingjobs.create(job)
        try:
            assert wait_for(
                lambda: cs.trainingjobs.get("default", "stalljob")
                .status.phase == TrainingJobPhase.RUNNING, 10)

            # Acceptance 1: the watchdog raises StepStalled for the frozen
            # rank (stall floor 2 s: rank 2 stops advancing at step 10).
            assert wait_for(
                lambda: any(
                    ev.reason == constants.STEP_STALLED_REASON
                    for ev in cs.events.list("default")), 15)
            ev = next(ev for ev in cs.events.list("default")
                      if ev.reason == constants.STEP_STALLED_REASON)
            assert "trainer-2" in ev.message

            # Acceptance 2: nonzero straggler-skew sample on /metrics.
            line = next(
                (ln for ln in METRICS.render_prometheus().splitlines()
                 if ln.startswith('trainingjob_straggler_skew{'
                                  'job="default/stalljob"')), None)
            assert line is not None
            assert float(line.split()[-1]) >= 2.0

            # Acceptance 3: the live step table shows the lagging rank.
            table = TELEMETRY.job_table(key)
            rows = {r["replica"]: r for r in table["replicas"]}
            assert rows["trainer-2"]["stalled"] is True
            # stall-at-step 10 = ten records reported, last step index 9.
            assert rows["trainer-2"]["step"] == 9
            assert rows["trainer-0"]["step"] > rows["trainer-2"]["step"]
            assert rows["trainer-1"]["step"] < rows["trainer-0"]["step"]
            assert table["tokens_per_sec"] > 0

            # The Running condition message carries the snapshot.
            fresh = cs.trainingjobs.get("default", "stalljob")
            running = next(c for c in fresh.status.conditions
                           if c.type == TrainingJobPhase.RUNNING)
            assert wait_for(
                lambda: "tokens/s" in next(
                    c for c in cs.trainingjobs.get("default", "stalljob")
                    .status.conditions
                    if c.type == TrainingJobPhase.RUNNING).message, 10), \
                running.message
        finally:
            cs.trainingjobs.delete("default", "stalljob")
            TELEMETRY.forget(key)
