"""Request-lifecycle plane (docs/SERVING.md): the per-request ledger's
monotonic-id audit, tail-sampling retention, the request wire shape's
validation at the aggregator, the incident ``requests`` stanza, and the
/debug/requests + /debug/serve endpoint surface.

Unit layer first with private instances (a RequestLedger with explicit
ring/window knobs, driven with explicit timestamps -- the audit and the
sampler are pure functions of the records, so the tests pin the
contig/sparse/hwm arithmetic and the slowest-k policy exactly), then the
aggregator's malformed-record hygiene, then the render handlers (called
directly with parse_qs-shaped params, like the slo-plane endpoint tests).
"""

import json

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.incident import IncidentRecorder
from trainingjob_operator_tpu.obs.reqtrace import (
    REQTRACE,
    REQUEST_OUTCOMES,
    RequestLedger,
)
from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
from trainingjob_operator_tpu.utils.metrics import (
    METRICS,
    MetricsRegistry,
    _render_requests,
    _render_serve,
)

JOB = "default/reqjob"


def _ledger(ring=4, window=16):
    led = RequestLedger(ring=ring, window=window)
    led.start()
    return led


def _rec(rid, outcome="completed", epoch="e0", hwm=None, ttft=40.0,
         tpot=5.0, arrival=100.0, ts=101.0, phase_ms=None):
    """One already-validated terminal record, ledger-shaped."""
    return {
        "request_outcome": outcome,
        "request_id": rid,
        "request_epoch": epoch,
        "submitted_hwm": rid if hwm is None else hwm,
        "ttft_ms": ttft,
        "tpot_ms": tpot,
        "tokens": 8,
        "arrival": arrival,
        "ts": ts,
        "phase_ms": phase_ms or {"queued": 10.0, "prefill": 30.0,
                                 "decode": 35.0},
    }


# -- the dropped-request audit ------------------------------------------------

class TestAudit:
    def test_contiguous_terminals_leave_no_orphans(self):
        led = _ledger()
        for rid in range(5):
            led.record(JOB, _rec(rid))
        assert led.reconcile(now=200.0) == 0
        s = led.job_summary(JOB)
        assert s["records_total"] == 5
        assert s["outcomes"] == {"completed": 5}
        assert s["open_ids"] == 0

    def test_hwm_gap_is_filed_as_orphaned(self):
        led = _ledger()
        # ids 0 and 4 reached terminal state; the record for 4 proves ids
        # 1-3 were submitted (submitted_hwm) but they never reported.
        led.record(JOB, _rec(0))
        led.record(JOB, _rec(4, hwm=4))
        s = led.job_summary(JOB)
        assert s["open_ids"] == 3
        assert led.reconcile(now=200.0) == 3
        s = led.job_summary(JOB)
        assert s["orphaned"] == 3
        assert s["open_ids"] == 0
        # Idempotent: filed orphans joined the terminal set.
        assert led.reconcile(now=201.0) == 0

    def test_hwm_alone_orphans_a_never_reporting_stream(self):
        led = _ledger()
        # The only record says hwm=2: ids 0-1 died with their replica.
        led.record(JOB, _rec(2, hwm=2))
        assert led.reconcile(now=200.0) == 2

    def test_epochs_are_separate_streams(self):
        led = _ledger()
        # Same ids in a new epoch (post-restart id reset) are a NEW
        # stream, not duplicates and not a regression.
        led.record(JOB, _rec(0, epoch="e0"))
        led.record(JOB, _rec(0, epoch="e1"))
        led.record(JOB, _rec(1, epoch="e1"))
        s = led.job_summary(JOB)
        assert s["streams"] == 2
        assert s["records_total"] == 3
        assert led.reconcile(now=200.0) == 0

    def test_duplicate_terminal_first_record_wins(self):
        led = _ledger()
        led.record(JOB, _rec(0, outcome="completed"))
        led.record(JOB, _rec(0, outcome="evicted"))
        s = led.job_summary(JOB)
        assert s["outcomes"] == {"completed": 1}
        assert s["records_total"] == 1

    def test_plane_off_is_a_strict_noop(self):
        led = RequestLedger(ring=4, window=16)  # never started
        assert led.record(JOB, _rec(0)) is False
        assert led.jobs() == []
        assert led.reconcile(now=200.0) == 0
        assert led.job_summary(JOB) is None

    def test_orphan_filing_survives_stop(self):
        # The harness stops the plane, then reconciles + reports: retained
        # state must stay readable and auditable after stop().
        led = _ledger()
        led.record(JOB, _rec(3, hwm=3))
        led.stop()
        assert led.reconcile(now=200.0) == 3
        assert led.job_summary(JOB)["orphaned"] == 3


# -- tail-sampling retention --------------------------------------------------

class TestRetention:
    def test_ring_at_exactly_full_drops_nothing(self):
        led = _ledger(ring=3)
        for rid in range(3):
            led.record(JOB, _rec(rid))
        s = led.job_summary(JOB)
        assert s["retained"] == 3
        assert s["sampled_dropped"] == 0

    def test_overflow_keeps_the_slowest_and_counts_the_drop(self):
        job = "default/reqring"
        key = ('trainingjob_reqtrace_sampled_dropped_total'
               '{job="default/reqring"}')
        before = METRICS.snapshot().get(key, 0)
        led = _ledger(ring=2)
        led.record(job, _rec(0, phase_ms={"decode": 10.0}))
        led.record(job, _rec(1, phase_ms={"decode": 500.0}))
        led.record(job, _rec(2, phase_ms={"decode": 200.0}))
        spans = led.retained_list(job)
        assert [r["request_id"] for r in spans] == [1, 2]  # slowest two
        s = led.job_summary(job)
        assert s["retained"] == 2
        assert s["sampled_dropped"] == 1
        # The drop is audible on the metric surface, not just in-object.
        assert METRICS.snapshot().get(key, 0) == before + 1
        # The percentile window still saw ALL three records.
        assert s["ttft_ms_p50"] == 40.0

    def test_orphans_outrank_any_slow_request(self):
        led = _ledger(ring=2)
        led.record(JOB, _rec(0, phase_ms={"decode": 9999.0}))
        led.record(JOB, _rec(1, phase_ms={"decode": 9998.0}, hwm=3))
        led.reconcile(now=200.0)  # files ids 2-3 as orphaned
        outcomes = [r["request_outcome"] for r in led.retained_list(JOB)]
        assert outcomes.count("orphaned") == 2  # evidence beats latency

    def test_percentiles_absent_until_a_record_carries_them(self):
        led = _ledger()
        assert led.ttft_percentiles(JOB) is None          # never seen
        led.record(JOB, _rec(0, ttft=None, tpot=None))
        assert led.ttft_percentiles(JOB) is None          # no TTFT yet
        assert "ttft_ms_p50" not in led.job_summary(JOB)  # absent, not 0
        led.record(JOB, _rec(1, ttft=80.0, tpot=6.0))
        assert led.ttft_percentiles(JOB) == (80.0, 80.0)
        assert led.tpot_percentiles(JOB) == (6.0, 6.0)


# -- incident stanza + chrome export ------------------------------------------

class TestWindowAndExport:
    def test_window_overlap_and_worst_ttft(self):
        led = _ledger()
        led.record(JOB, _rec(0, arrival=100.0, ts=101.0, ttft=40.0))
        led.record(JOB, _rec(1, arrival=150.0, ts=151.0, ttft=90.0))
        stanza = led.window(JOB, 100.5, 120.0)
        assert stanza["in_flight"] == 1
        assert stanza["outcomes"] == {"completed": 1}
        assert stanza["worst_ttft_ms"] == 40.0
        assert led.window(JOB, 500.0, 600.0) == {}  # absent, not zeros

    def test_evictions_bind_to_a_late_opening_incident(self):
        led = _ledger()
        # The kill flushed this eviction at t=101; detection latency
        # (watch drop -> relist) opened the incident at t=103.  A plain
        # interval overlap would miss the failure's own footprint.
        led.record(JOB, _rec(0, outcome="evicted", arrival=100.0, ts=101.0))
        stanza = led.window(JOB, 103.0, 110.0)
        assert stanza["outcomes"] == {"evicted": 1}
        # Completed records get NO such grace: they are traffic, not
        # failure evidence.
        led.record(JOB, _rec(1, outcome="completed", arrival=100.0,
                             ts=101.0))
        assert led.window(JOB, 103.0, 110.0)["in_flight"] == 1

    def test_chrome_export_is_perfetto_shaped(self):
        led = _ledger()
        led.record(JOB, _rec(0, arrival=100.0, phase_ms={
            "queued": 10.0, "prefill": 30.0, "decode": 60.0}))
        seq = led.retained_list(JOB)[0]["seq"]
        doc = led.export_chrome(JOB, seq)
        assert doc["displayTimeUnit"] == "ms"
        names = [ev["name"] for ev in doc["traceEvents"]]
        assert names == ["queued", "prefill", "decode"]
        assert all(ev["ph"] == "X" for ev in doc["traceEvents"])
        # Phases are consecutive on the request's track: each event
        # starts exactly where the previous one ended.
        evs = doc["traceEvents"]
        for prev, cur in zip(evs, evs[1:]):
            assert cur["ts"] == pytest.approx(prev["ts"] + prev["dur"])
        assert led.export_chrome(JOB, 999) is None

    def test_restart_bundle_carries_requests_stanza(self):
        REQTRACE.reset()
        REQTRACE.start()
        try:
            REQTRACE.record(JOB, _rec(0, outcome="evicted",
                                      arrival=99.0, ts=99.8, ttft=70.0))
            rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64,
                                   keep=4)
            rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON,
                                now=100.0)
            rec.on_running(JOB, now=102.0)
            rec.record_step(JOB, step=5, ms=100.0, now=103.0)
            (bundle,) = rec.bundles(JOB)
            assert bundle["requests"]["in_flight"] == 1
            assert bundle["requests"]["outcomes"] == {"evicted": 1}
            assert bundle["requests"]["worst_ttft_ms"] == 70.0
            first = rec.bundle_json(JOB)
            # The stanza was frozen at assembly: byte-stable re-assembly
            # even after the live ledger is wiped.
            REQTRACE.reset()
            assert rec.reassemble(JOB) == first
            assert rec.reassemble(JOB) == first
        finally:
            REQTRACE.stop()
            REQTRACE.reset()

    def test_plane_off_bundle_has_no_requests_key(self):
        REQTRACE.reset()  # plane never started: window() is empty
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON,
                            now=100.0)
        rec.on_running(JOB, now=102.0)
        (bundle,) = rec.bundles(JOB)
        assert "requests" not in bundle


# -- the wire shape at the aggregator -----------------------------------------

class TestWireValidation:
    def _agg(self):
        reg = MetricsRegistry()
        led = _ledger()
        return TelemetryAggregator(metrics=reg, reqtrace=led), reg, led

    def _malformed(self, reg):
        return reg.snapshot().get("trainingjob_telemetry_malformed_total", 0)

    def test_valid_record_feeds_metrics_and_ledger(self):
        agg, reg, led = self._agg()
        assert agg.ingest({"job": JOB, "request_outcome": "completed",
                           "request_id": 0, "request_epoch": "e0",
                           "submitted_hwm": 0, "tokens": 8,
                           "ttft_ms": 40.0, "tpot_ms": 5.0,
                           "arrival": 100.0,
                           "phase_ms": {"queued": 10.0}}, now=101.0)
        snap = reg.snapshot()
        assert snap[('trainingjob_requests_total'
                     '{job="default/reqjob",outcome="completed"}')] == 1
        assert snap[('trainingjob_request_ttft_ms'
                     '{job="default/reqjob"}_count')] == 1
        assert led.job_summary(JOB)["records_total"] == 1
        assert self._malformed(reg) == 0

    @pytest.mark.parametrize("bad", [
        {"request_outcome": "completed"},                   # no job/id/epoch
        {"job": JOB, "request_outcome": "vanished",         # unknown outcome
         "request_id": 0, "request_epoch": "e0"},
        {"job": JOB, "request_outcome": "completed",        # id not an int
         "request_id": "zero", "request_epoch": "e0"},
        {"job": JOB, "request_outcome": "completed",        # empty epoch
         "request_id": 0, "request_epoch": ""},
        {"job": JOB, "request_outcome": "completed",        # hwm < id
         "request_id": 5, "request_epoch": "e0", "submitted_hwm": 3},
        {"job": JOB, "request_outcome": "completed",        # negative ttft
         "request_id": 0, "request_epoch": "e0", "ttft_ms": -1.0},
        {"job": JOB, "request_outcome": "completed",        # negative phase
         "request_id": 0, "request_epoch": "e0",
         "phase_ms": {"queued": -5.0}},
        {"job": "nonamespace", "request_outcome": "completed",
         "request_id": 0, "request_epoch": "e0"},           # not ns/name
    ])
    def test_malformed_is_counted_not_crashed(self, bad):
        agg, reg, led = self._agg()
        assert agg.ingest(bad, now=101.0) is False
        assert self._malformed(reg) == 1
        assert led.jobs() == []  # nothing reached the ledger

    def test_orphaned_is_reconcile_only_on_the_wire_too(self):
        # A live client claiming "orphaned" is lying: only reconcile()
        # files that outcome (REQUEST_OUTCOMES documents it; the wire
        # accepts it since the shape is valid -- but the audit invariant
        # is that schedulers never send it).
        assert "orphaned" in REQUEST_OUTCOMES


# -- endpoint surface ---------------------------------------------------------

class TestEndpoints:
    def test_requests_unknown_job_is_404(self):
        led = _ledger()
        status, _, _ = _render_requests(led, {"job": ["default/ghost"]})
        assert status == 404

    def test_requests_bad_format_is_400(self):
        led = _ledger()
        status, _, body = _render_requests(led, {"format": ["xml"]})
        assert status == 400
        assert "xml" in body

    def test_requests_bad_id_is_400(self):
        led = _ledger()
        led.record(JOB, _rec(0))
        status, _, body = _render_requests(
            led, {"job": [JOB], "id": ["latest"]})
        assert status == 400
        assert "latest" in body

    def test_requests_sampled_away_id_is_404(self):
        led = _ledger()
        led.record(JOB, _rec(0))
        status, _, _ = _render_requests(led, {"job": [JOB], "id": ["999"]})
        assert status == 404

    def test_requests_summary_job_and_span_views(self):
        led = _ledger()
        led.record(JOB, _rec(0))
        status, ctype, body = _render_requests(led, {})
        assert status == 200 and ctype == "application/json"
        assert json.loads(body)["jobs_reporting"] == 1
        status, _, body = _render_requests(led, {"job": [JOB]})
        doc = json.loads(body)
        assert doc["summary"]["records_total"] == 1
        seq = doc["retained"][0]["seq"]
        status, _, body = _render_requests(
            led, {"job": [JOB], "id": [str(seq)], "format": ["chrome"]})
        assert status == 200
        assert json.loads(body)["displayTimeUnit"] == "ms"

    def test_serve_columns_absent_is_dash_never_zero(self):
        agg = TelemetryAggregator(metrics=MetricsRegistry())
        agg.ingest({"job": JOB, "serve_queue_depth": 2.0,
                    "serve_slots": 4.0}, now=100.0)
        led = _ledger()  # ledger never saw this job
        status, _, body = _render_serve(
            agg, {"job": [JOB], "format": ["text"]}, reqtrace=led)
        assert status == 200
        row = next(ln for ln in body.splitlines() if "ttft_ms_p99" in ln)
        assert row.split()[-1] == "-"
        status, _, body = _render_serve(agg, {"job": [JOB]}, reqtrace=led)
        assert json.loads(body)["serve"]["ttft_ms_p99"] is None
        # Once the ledger reports, the columns materialize.
        led.record(JOB, _rec(0, ttft=40.0, tpot=5.0))
        status, _, body = _render_serve(agg, {"job": [JOB]}, reqtrace=led)
        doc = json.loads(body)["serve"]
        assert doc["ttft_ms_p99"] == 40.0
        assert doc["tpot_ms_p50"] == 5.0
