"""Controller integration tests against the in-memory cluster.

This is the test pyramid level (2) the reference never had (SURVEY.md §4):
controller vs. fake clients with hand-seeded pods in every phase, node
readiness flips, exit-code matrices, preemption annotations, time limits, and
restart-scope waits.  Reconciles are driven synchronously via sync_handler for
determinism.
"""

import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    EndingPolicy,
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUSpec,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.controller.garbage_collection import GarbageCollector
from trainingjob_operator_tpu.core.objects import (
    Condition,
    ConditionStatus,
    Container,
    ContainerPort,
    ContainerState,
    ContainerStatus,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodPhase,
    PodSpec,
    PodTemplateSpec,
    make_ready_node,
)


def make_env():
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions())
    return cs, tc


def make_job(name="job", replicas=2, namespace="default", **replica_kw) -> TPUTrainingJob:
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace=namespace))
    job.spec.replica_specs["trainer"] = ReplicaSpec(
        replicas=replicas,
        template=PodTemplateSpec(spec=PodSpec(containers=[
            Container(name="aitj-main", image="img",
                      ports=[ContainerPort(name="aitj-2222", container_port=2222)])
        ])),
        **replica_kw,
    )
    return job


def sync(tc, job, n=1):
    for _ in range(n):
        tc.sync_handler(f"{job.metadata.namespace}/{job.metadata.name}")


def get_job(cs, name="job", namespace="default"):
    return cs.trainingjobs.get(namespace, name)


def pods_of(cs, namespace="default"):
    return sorted(cs.pods.list(namespace), key=lambda p: p.name)


def set_pod_running(cs, pod_name, node="node-0", namespace="default"):
    pod = cs.pods.get(namespace, pod_name)
    pod.spec.node_name = node
    pod.status.phase = PodPhase.RUNNING
    pod.status.start_time = time.time()
    pod.status.container_statuses = [
        ContainerStatus(name="aitj-main",
                        state=ContainerState(running_started_at=time.time()))]
    cs.pods.update(pod)


def set_pod_terminated(cs, pod_name, exit_code, node="node-0", namespace="default"):
    pod = cs.pods.get(namespace, pod_name)
    pod.spec.node_name = node
    pod.status.phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED
    pod.status.container_statuses = [
        ContainerStatus(name="aitj-main",
                        state=ContainerState(terminated_exit_code=exit_code,
                                             terminated_reason="Completed" if exit_code == 0 else "Error"))]
    cs.pods.update(pod)


class TestPodCreation:
    def test_creates_pods_and_services_with_identity(self):
        cs, tc = make_env()
        cs.trainingjobs.create(make_job(replicas=2))
        sync(tc, make_job())
        pods = pods_of(cs)
        assert [p.name for p in pods] == ["job-trainer-0", "job-trainer-1"]
        p0 = pods[0]
        assert p0.metadata.labels[constants.REPLICA_NAME_LABEL] == "trainer"
        assert p0.metadata.labels[constants.REPLICA_INDEX_LABEL] == "0"
        assert p0.metadata.labels[constants.GROUP_NAME_LABEL] == constants.GROUP_NAME
        assert p0.metadata.labels[constants.JOB_NAME_LABEL] == "job"
        ref = p0.metadata.controller_of()
        assert ref is not None and ref.kind == constants.KIND
        svcs = sorted(cs.services.list("default"), key=lambda s: s.name)
        assert [s.name for s in svcs] == ["job-trainer-0", "job-trainer-1"]
        assert svcs[0].spec.cluster_ip == "None"
        assert svcs[0].spec.ports[0].port == 2222
        # Second sync is idempotent.
        sync(tc, make_job())
        assert len(pods_of(cs)) == 2

    def test_rendezvous_env_injection(self):
        # Reference contract: pod.go:548-652.
        cs, tc = make_env()
        cs.trainingjobs.create(make_job(replicas=2))
        sync(tc, make_job())
        env = {e.name: e.value for e in pods_of(cs)[1].spec.containers[0].env}
        assert env["TRAINER_INSTANCES"] == "job-trainer-0.default,job-trainer-1.default"
        assert env["TRAINER_INSTANCES_NUM"] == "2"
        assert env["TRAINER_PORTS"] == "2222"
        assert env["TRAINER_HOSTS"] == "job-trainer-0.default:2222,job-trainer-1.default:2222"
        assert env["TRAINER_HOSTS_NUM"] == "2"
        assert env[constants.REPLICA_NAME_ENV] == "trainer"
        assert env[constants.REPLICA_INDEX_ENV] == "1"
        assert env[constants.REPLICA_RESTART_COUNT_ENV] == "0"
        assert env[constants.SERVICE_ENV] == "job-trainer-1.default"
        assert env[constants.JOB_NAME_ENV] == "job"
        assert env[constants.PORTS_ENV] == "2222"
        # TPU-native bootstrap set (SURVEY.md §5.8).
        assert env[constants.NUM_PROCESSES_ENV] == "2"
        assert env[constants.PROCESS_ID_ENV] == "1"
        assert env[constants.COORDINATOR_ADDRESS_ENV] == "job-trainer-0.default:2222"
        assert env[constants.TPU_WORKER_ID_ENV] == "1"

    def test_pod_restart_policy_forced_never(self):
        # Reference: pod.go:532-535.
        cs, tc = make_env()
        cs.trainingjobs.create(make_job(restart_policy=RestartPolicy.ON_FAILURE))
        sync(tc, make_job())
        assert all(p.spec.restart_policy == "Never" for p in pods_of(cs))

    def test_gap_filling(self):
        cs, tc = make_env()
        cs.trainingjobs.create(make_job(replicas=3))
        sync(tc, make_job())
        cs.pods.delete("default", "job-trainer-1")
        sync(tc, make_job())
        assert [p.name for p in pods_of(cs)] == [
            "job-trainer-0", "job-trainer-1", "job-trainer-2"]


class TestPhaseMachine:
    def test_pending_then_running(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=2))
        sync(tc, make_job())
        job = get_job(cs)
        assert job.status.phase == TrainingJobPhase.PENDING
        assert job.status.start_time is not None
        for p in pods_of(cs):
            set_pod_running(cs, p.name)
        sync(tc, make_job())
        job = get_job(cs)
        assert job.status.phase == TrainingJobPhase.RUNNING
        assert job.status.start_running_time is not None
        assert job.status.replica_statuses["trainer"].active == 2
        conds = [c.type for c in job.status.conditions]
        assert conds == [TrainingJobPhase.PENDING, TrainingJobPhase.RUNNING]
        # Older condition flipped to False.
        assert job.status.conditions[0].status == ConditionStatus.FALSE

    def test_complete_policy_all(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=2))
        sync(tc, make_job())
        set_pod_terminated(cs, "job-trainer-0", 0)
        sync(tc, make_job())
        assert get_job(cs).status.phase != TrainingJobPhase.SUCCEEDED
        set_pod_terminated(cs, "job-trainer-1", 0)
        sync(tc, make_job())
        job = get_job(cs)
        # CleanPodPolicy All (default): pods deleted, phase parked in
        # annotation until drained (status.go:176-187).
        assert job.status.phase == TrainingJobPhase.TERMINATING
        assert TrainingJobPhase.SUCCEEDED in job.metadata.annotations
        sync(tc, make_job())
        job = get_job(cs)
        assert job.status.phase == TrainingJobPhase.SUCCEEDED
        assert job.status.end_time is not None
        assert pods_of(cs) == []

    def test_complete_policy_any(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=2, complete_policy=EndingPolicy.ANY))
        sync(tc, make_job())
        set_pod_terminated(cs, "job-trainer-1", 0)
        sync(tc, make_job(), n=2)
        assert get_job(cs).status.phase == TrainingJobPhase.SUCCEEDED

    def test_complete_policy_rank0(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=2, complete_policy=EndingPolicy.RANK0))
        sync(tc, make_job())
        set_pod_terminated(cs, "job-trainer-1", 0)
        sync(tc, make_job(), n=2)
        assert get_job(cs).status.phase != TrainingJobPhase.SUCCEEDED
        set_pod_terminated(cs, "job-trainer-0", 0)
        sync(tc, make_job(), n=2)
        assert get_job(cs).status.phase == TrainingJobPhase.SUCCEEDED

    def test_fail_policy_any_with_clean_none_keeps_pods(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=2)
        job.spec.clean_pod_policy = CleanPodPolicy.NONE
        cs.trainingjobs.create(job)
        sync(tc, make_job())
        set_pod_running(cs, "job-trainer-0")
        set_pod_terminated(cs, "job-trainer-1", 1)
        sync(tc, make_job())
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.FAILED
        assert len(pods_of(cs)) == 2  # kept (status.go:262-270)

    def test_fail_policy_all(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=2, fail_policy=EndingPolicy.ALL))
        sync(tc, make_job())
        set_pod_terminated(cs, "job-trainer-0", 1)
        sync(tc, make_job())
        assert get_job(cs).status.phase != TrainingJobPhase.FAILED
        set_pod_terminated(cs, "job-trainer-1", 1)
        sync(tc, make_job(), n=2)
        assert get_job(cs).status.phase in (TrainingJobPhase.TERMINATING,
                                            TrainingJobPhase.FAILED)
        sync(tc, make_job())
        assert get_job(cs).status.phase == TrainingJobPhase.FAILED


class TestRestartMachine:
    def _failing_job(self, cs, tc, scope=RestartScope.ALL, replicas=2,
                     policy=RestartPolicy.ON_FAILURE, limit=None, exit_code=1,
                     restarting_exit_code=""):
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=replicas, restart_policy=policy,
                       restart_scope=scope, restart_limit=limit,
                       fail_policy=EndingPolicy.RANK0)
        job.spec.restarting_exit_code = restarting_exit_code
        cs.trainingjobs.create(job)
        sync(tc, job)
        for p in pods_of(cs):
            set_pod_running(cs, p.name)
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        set_pod_terminated(cs, "job-trainer-1", exit_code)
        return job

    def test_on_failure_restart_two_phase(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, scope=RestartScope.ALL)
        sync(tc, job)
        got = get_job(cs)
        # Phase 1: deletes issued, Terminating with restart marker
        # (controller.go:362-366).
        assert got.status.phase == TrainingJobPhase.TERMINATING
        assert got.status.restart_replica_name == "trainer"
        assert got.status.restart_counts["trainer"] == 1
        assert pods_of(cs) == []  # no finalizer -> deleted immediately
        # Phase 2: pods drained -> Restarting, marker cleared
        # (status.go:114-143).
        sync(tc, job)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.RESTARTING
        assert got.status.restart_replica_name == ""
        # Phase 3: pods recreated with bumped restart count.
        sync(tc, job)
        pods = pods_of(cs)
        assert len(pods) == 2
        assert pods[0].metadata.labels[constants.RESTART_COUNT_LABEL] == "1"
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env[constants.REPLICA_RESTART_COUNT_ENV] == "1"

    def test_restart_scope_pod_deletes_only_failed(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, scope=RestartScope.POD)
        sync(tc, job)
        remaining = [p.name for p in pods_of(cs)]
        assert remaining == ["job-trainer-0"]

    def test_restart_wait_blocks_reconcile_until_drained(self):
        cs, tc = make_env()
        # Register a finalizer so deletes are graceful (pods linger).
        finalizing = []
        cs.tracker.register_finalizer("Pod", lambda o: finalizing.append(o.name))
        job = self._failing_job(cs, tc, scope=RestartScope.ALL)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.TERMINATING
        assert len(pods_of(cs)) == 2  # still terminating
        sync(tc, job)
        # Still waiting: no recreation, no phase flip.
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.TERMINATING
        assert got.status.restart_replica_name == "trainer"
        for name in list(finalizing):
            cs.tracker.finalize_delete("Pod", "default", name)
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RESTARTING
        sync(tc, job)
        assert len(pods_of(cs)) == 2

    def test_restart_limit_exhausted_falls_through_to_fail(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, scope=RestartScope.ALL, limit=0,
                                policy=RestartPolicy.ON_FAILURE)
        # fail_policy RANK0 and rank1 failed -> not ended; but restart is
        # blocked by limit, so pod stays Failed and the group keeps running
        # until a policy triggers.
        sync(tc, job)
        got = get_job(cs)
        assert got.status.restart_counts["trainer"] == 0
        assert len(pods_of(cs)) == 2  # nothing deleted

    def test_exit_code_policy_retryable(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, policy=RestartPolicy.EXIT_CODE,
                                exit_code=137, restarting_exit_code="137,128")
        sync(tc, job)
        assert get_job(cs).status.restart_counts["trainer"] == 1

    def test_exit_code_policy_non_retryable_fails(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, policy=RestartPolicy.EXIT_CODE,
                                exit_code=2, restarting_exit_code="137,128",
                                replicas=2)
        # fail_policy RANK0: rank 1 failing doesn't end the job; no restart.
        sync(tc, job)
        got = get_job(cs)
        assert got.status.restart_counts["trainer"] == 0
        # Now fail rank 0 with a non-retryable code -> job fails.
        set_pod_terminated(cs, "job-trainer-0", 2)
        sync(tc, job, n=3)
        assert get_job(cs).status.phase == TrainingJobPhase.FAILED

    def test_never_policy_no_restart(self):
        cs, tc = make_env()
        job = self._failing_job(cs, tc, policy=RestartPolicy.NEVER,
                                exit_code=1, replicas=2)
        sync(tc, job)
        assert get_job(cs).status.restart_counts["trainer"] == 0


class TestNodeFailure:
    def test_node_fail_restarts_with_force_delete(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.nodes.create(make_ready_node("node-1"))
        job = make_job(replicas=2, restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.POD)
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        set_pod_running(cs, "job-trainer-1", node="node-1")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Node-1 dies.  Register a finalizer to prove force-delete bypasses it.
        cs.tracker.register_finalizer("Pod", lambda o: None)
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.restart_counts["trainer"] == 1
        # Force delete (grace 0) removed it despite the finalizer
        # (pod.go:210-213,469).
        assert [p.name for p in pods_of(cs)] == ["job-trainer-0"]

    def test_node_fail_without_policy_fails_job(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=1, restart_policy=RestartPolicy.NEVER)
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        sync(tc, job)
        node = cs.nodes.get_node("node-0")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job, n=2)
        got = get_job(cs)
        assert got.status.phase in (TrainingJobPhase.TERMINATING,
                                    TrainingJobPhase.NODE_FAIL)
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.NODE_FAIL


class TestPreemption:
    def test_preempted_annotation_short_circuits(self):
        # Reference: pod.go:160-165 + annotation-drain (status.go:176-187).
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=2)
        cs.trainingjobs.create(job)
        sync(tc, job)
        fresh = get_job(cs)
        fresh.metadata.annotations[TrainingJobPhase.PREEMPTED] = "preempted by scheduler"
        cs.trainingjobs.update(fresh)
        sync(tc, job, n=3)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.PREEMPTED
        assert pods_of(cs) == []


class TestTimeLimit:
    def test_timeout_terminates(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=1)
        job.spec.time_limit = 1
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Backdate start_running_time past the limit.
        fresh = get_job(cs)
        fresh.status.start_running_time = time.time() - 10
        cs.trainingjobs.update(fresh)
        sync(tc, job, n=3)
        assert get_job(cs).status.phase == TrainingJobPhase.TIMEOUT


class TestValidationGate:
    def test_invalid_spec_fails_job(self):
        cs, tc = make_env()
        job = TPUTrainingJob(metadata=ObjectMeta(name="bad", namespace="default"))
        job.spec.replica_specs["w"] = ReplicaSpec()  # no containers
        cs.trainingjobs.create(job)
        tc.sync_handler("default/bad")
        got = cs.trainingjobs.get("default", "bad")
        assert got.status.phase == TrainingJobPhase.FAILED
        assert any(e.reason == "ValidationFailed" for e in cs.events.list())


class TestMultiReplicaGroups:
    def make_ps_worker_job(self, cs):
        job = TPUTrainingJob(metadata=ObjectMeta(name="psjob", namespace="default"))
        for rname, n in (("pserver", 2), ("trainer", 2)):
            job.spec.replica_specs[rname] = ReplicaSpec(
                replicas=n,
                template=PodTemplateSpec(spec=PodSpec(containers=[
                    Container(name=f"aitj-{rname}",
                              ports=[ContainerPort(name="aitj-5000", container_port=5000)])
                ])),
            )
        # Job completes when trainers complete; pserver never exits.
        job.spec.replica_specs["trainer"].complete_policy = EndingPolicy.ALL
        job.spec.complete_policy = EndingPolicy.ANY
        cs.trainingjobs.create(job)
        return job

    def test_cross_group_env_and_completion(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = self.make_ps_worker_job(cs)
        tc.sync_handler("default/psjob")
        pods = pods_of(cs)
        assert len(pods) == 4
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        # Every group sees every other group's host lists (pod.go:553-599).
        assert env["PSERVER_INSTANCES_NUM"] == "2"
        assert env["TRAINER_INSTANCES_NUM"] == "2"
        for p in pods:
            if "trainer" in p.name:
                set_pod_terminated(cs, p.name, 0)
            else:
                set_pod_running(cs, p.name)
        tc.sync_handler("default/psjob")
        tc.sync_handler("default/psjob")
        got = cs.trainingjobs.get("default", "psjob")
        assert got.status.phase in (TrainingJobPhase.TERMINATING,
                                    TrainingJobPhase.SUCCEEDED)


class TestTPUProvisioning:
    def test_tpu_node_selectors_resources_and_gang_labels(self):
        cs, tc = make_env()
        job = make_job(replicas=4)
        job.spec.replica_specs["trainer"].tpu = TPUSpec(
            accelerator="tpu-v5-lite-podslice", topology="4x4", preemptible=True)
        cs.trainingjobs.create(job)
        sync(tc, job)
        pods = pods_of(cs)
        assert len(pods) == 4
        p = pods[0]
        sel = p.spec.node_selector
        assert sel[constants.GKE_TPU_ACCELERATOR_SELECTOR] == "tpu-v5-lite-podslice"
        assert sel[constants.GKE_TPU_TOPOLOGY_SELECTOR] == "4x4"
        assert sel[constants.GKE_SPOT_SELECTOR] == "true"
        assert p.spec.containers[0].resources["limits"][constants.TPU_RESOURCE] == 4
        env = {e.name: e.value for e in p.spec.containers[0].env}
        assert env[constants.TPU_TOPOLOGY_ENV] == "4x4"
        assert env[constants.TPU_WORKER_HOSTNAMES_ENV].startswith("job-trainer-0.default")
        # 4x4 = one slice of 4 hosts -> all pods in gang slice0.
        assert all(pp.metadata.labels[constants.SLICE_ID_LABEL] == "0" for pp in pods)

    def test_multislice_env(self):
        cs, tc = make_env()
        job = make_job(replicas=4)
        job.spec.replica_specs["trainer"].tpu = TPUSpec(
            accelerator="tpu-v5-lite-podslice", topology="2x4", slice_count=2)
        cs.trainingjobs.create(job)
        sync(tc, job)
        pods = pods_of(cs)
        env0 = {e.name: e.value for e in pods[0].spec.containers[0].env}
        env3 = {e.name: e.value for e in pods[3].spec.containers[0].env}
        # 2x4 = 8 chips = 2 hosts/slice; pods 0-1 slice0, 2-3 slice1.
        assert env0[constants.SLICE_ID_ENV] == "0"
        assert env3[constants.SLICE_ID_ENV] == "1"
        assert env0[constants.NUM_SLICES_ENV] == "2"
        assert pods[3].metadata.labels[constants.SLICE_ID_LABEL] == "1"


class TestGarbageCollection:
    def test_orphan_pod_collected(self):
        cs, tc = make_env()
        pod = Pod(metadata=ObjectMeta(
            name="orphan", namespace="default",
            labels={constants.GROUP_NAME_LABEL: constants.GROUP_NAME},
            owner_references=[OwnerReference(kind=constants.KIND, name="gone",
                                             uid="dead", controller=True)]))
        cs.pods.create(pod)
        gc = GarbageCollector(cs, tc.trainingjob_lister)
        gc.clean_garbage_pods()
        assert cs.pods.list() == []

    def test_owned_pod_kept(self):
        cs, tc = make_env()
        cs.trainingjobs.create(make_job())
        sync(tc, make_job())
        gc = GarbageCollector(cs, tc.trainingjob_lister)
        gc.clean_garbage_pods()
        assert len(pods_of(cs)) == 2

    def test_unlabeled_pod_ignored(self):
        cs, tc = make_env()
        cs.pods.create(Pod(metadata=ObjectMeta(name="random", namespace="default")))
        gc = GarbageCollector(cs, tc.trainingjob_lister)
        gc.clean_garbage_pods()
        assert len(cs.pods.list()) == 1


class TestElasticWidth:
    def test_effective_replicas_drives_pod_count(self):
        cs, tc = make_env()
        job = make_job(replicas=4, min_replicas=2, max_replicas=4)
        cs.trainingjobs.create(job)
        sync(tc, job)
        assert len(pods_of(cs)) == 4
        # Controller decides to degrade to width 2 (elastic record in status).
        fresh = get_job(cs)
        fresh.status.elastic_replicas["trainer"] = 2
        cs.trainingjobs.update(fresh)
        # Pods 2,3 are removed by the elastic path before reconcile; simulate
        # capacity loss by deleting them, then ensure no gap-filling past
        # width 2.
        cs.pods.delete("default", "job-trainer-2")
        cs.pods.delete("default", "job-trainer-3")
        sync(tc, job)
        assert [p.name for p in pods_of(cs)] == ["job-trainer-0", "job-trainer-1"]
        env = {e.name: e.value for e in pods_of(cs)[0].spec.containers[0].env}
        # Env for new pods would reflect the degraded width via
        # effective_replicas; existing pods keep their env (restart applies it).


class TestEndToEndLoop:
    def test_threaded_run_completes_job(self):
        """The full loop: run() workers + informer events, no manual syncs."""
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        tc.options.resync_period = 0.05
        tc.run(workers=2)
        try:
            cs.trainingjobs.create(make_job(replicas=2))
            deadline = time.time() + 5
            while time.time() < deadline:
                if len(cs.pods.list("default")) == 2:
                    break
                time.sleep(0.01)
            assert len(cs.pods.list("default")) == 2
            for p in pods_of(cs):
                set_pod_terminated(cs, p.name, 0)
            deadline = time.time() + 5
            while time.time() < deadline:
                if get_job(cs).status.phase == TrainingJobPhase.SUCCEEDED:
                    break
                time.sleep(0.01)
            assert get_job(cs).status.phase == TrainingJobPhase.SUCCEEDED
        finally:
            tc.stop()


class TestReviewRegressions2:
    """Regressions for the controller-round code-review findings."""

    def test_tpu_replicas_default_to_geometry(self):
        from trainingjob_operator_tpu.api.defaults import set_defaults
        job = make_job(replicas=None)
        job.spec.replica_specs["trainer"].replicas = None
        job.spec.replica_specs["trainer"].tpu = TPUSpec(topology="4x4", slice_count=2)
        set_defaults(job)
        assert job.spec.replica_specs["trainer"].replicas == 8

    def test_tpu_replicas_geometry_mismatch_rejected(self):
        from trainingjob_operator_tpu.api.validation import validate_job
        job = make_job(replicas=3)
        job.spec.replica_specs["trainer"].tpu = TPUSpec(topology="4x4")
        assert any("does not match the TPU geometry" in e for e in validate_job(job))

    def test_replicas_zero_respected(self):
        from trainingjob_operator_tpu.controller.naming import effective_replicas
        from trainingjob_operator_tpu.api.defaults import set_defaults
        job = make_job(replicas=0)
        set_defaults(job)
        assert effective_replicas(job, "trainer") == 0
        cs, tc = make_env()
        cs.trainingjobs.create(job)
        sync(tc, job)
        assert pods_of(cs) == []

    def test_conflict_retry_preserves_external_annotations(self):
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        cs.trainingjobs.create(make_job(replicas=1))
        sync(tc, make_job())
        # Controller holds a stale copy while an external actor annotates.
        stale = get_job(cs)
        external = get_job(cs)
        external.metadata.annotations[TrainingJobPhase.PREEMPTED] = "spot reclaim"
        cs.trainingjobs.update(external)
        stale.status.phase = TrainingJobPhase.RUNNING
        tc.update_trainingjob_phase(stale)
        got = get_job(cs)
        assert got.metadata.annotations.get(TrainingJobPhase.PREEMPTED) == "spot reclaim"

    def test_informer_replays_preexisting_objects(self):
        cs = Clientset()
        cs.trainingjobs.create(make_job())
        # Controller constructed AFTER the job exists: must still reconcile it
        # without waiting for resync.
        tc = TrainingJobController(cs)
        item, _ = tc.work_queue.get(timeout=1.0)
        assert item == "default/job"

    def test_event_retention_cap(self):
        from trainingjob_operator_tpu.utils.events import EventRecorder
        cs = Clientset()
        rec = EventRecorder(cs, "test")
        rec.MAX_EVENTS = 10
        job = make_job()
        for i in range(25):
            # analyzer: allow[event-reason-drift]: synthetic reason; the
            # test exercises retention, not the reason registry.
            rec.event(job, EventRecorder.NORMAL, "R", f"m{i}")
        assert len(cs.events.list()) == 10


class TestEventSeq:
    """The lock-guarded (epoch, shard, seq) sequencer that retired the
    registry's last shard_hostile singleton (the bare itertools.count)."""

    def test_keys_unique_and_ordered_under_contention(self):
        import threading
        from trainingjob_operator_tpu.utils.events import EventSeq

        seq = EventSeq()
        keys, lock = [], threading.Lock()

        def grab(n=200):
            got = [seq.next_key() for _ in range(n)]
            with lock:
                keys.extend(got)

        threads = [threading.Thread(target=grab) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(keys) == 8 * 200
        assert len(set(keys)) == len(keys)          # uniqueness
        assert sorted(k[2] for k in keys) == list(range(len(keys)))

    def test_suffixes_sort_in_allocation_order(self):
        from trainingjob_operator_tpu.utils.events import EventSeq

        seq = EventSeq()
        suffixes = [seq.next_suffix() for _ in range(50)]
        assert suffixes == sorted(suffixes)         # fixed-width sortable
        assert len(set(suffixes)) == 50

    def test_configure_orders_across_epochs_and_shards(self):
        from trainingjob_operator_tpu.utils.events import EventSeq

        seq = EventSeq()
        first = seq.next_suffix()
        seq.configure(shard=3)
        shard3 = seq.next_suffix()
        seq.configure(epoch=1, shard=0)
        epoch1 = seq.next_suffix()
        # Lexicographic order == (epoch, shard, seq) order.
        assert first < shard3 < epoch1
        assert seq.next_key() == (1, 0, 3)

    def test_event_names_carry_the_sequencer_suffix(self):
        from trainingjob_operator_tpu.utils.events import EventRecorder

        cs = Clientset()
        rec = EventRecorder(cs, "test")
        job = make_job()
        for _ in range(3):
            # analyzer: allow[event-reason-drift]: synthetic reason; the
            # test exercises naming, not the reason registry.
            rec.event(job, EventRecorder.NORMAL, "R", "m")
        names = sorted(e.name for e in cs.events.list())
        assert len(set(names)) == 3
        # name = <job>.<epoch>-<shard>-<seq>.<uid8>: the suffix between
        # the first and last dot is the fixed-width sequencer key.
        for name in names:
            mid = name.split(".")[1]
            epoch, shard, seq = mid.split("-")
            assert (len(epoch), len(shard), len(seq)) == (3, 2, 6)


class TestElastic:
    """Elastic resize (EdlPolicy Auto): the north-star capability the
    reference declares but never implements (SURVEY.md §2.6, §5.3)."""

    def _running_elastic_job(self, cs, tc, replicas=3, min_replicas=1,
                             **extra):
        for i in range(replicas):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=replicas, min_replicas=min_replicas,
                       max_replicas=replicas,
                       edl_policy="Auto",
                       restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.REPLICA, **extra)
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(replicas):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        return job

    def test_node_fail_shrinks_to_survivors(self):
        cs, tc = make_env()
        job = self._running_elastic_job(cs, tc)
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        # Shrink, not restart: width recorded, group drained, no restart
        # budget consumed.
        assert got.status.elastic_replicas["trainer"] == 2
        assert got.status.scaling_replica_name == "trainer"
        assert got.status.phase == TrainingJobPhase.SCALING
        assert got.status.restart_counts["trainer"] == 0
        assert pods_of(cs) == []
        sync(tc, job)  # drain observed -> marker cleared
        assert get_job(cs).status.scaling_replica_name == ""
        sync(tc, job)  # recreate at new width
        pods = pods_of(cs)
        assert [p.name for p in pods] == ["job-trainer-0", "job-trainer-1"]
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env[constants.ELASTIC_REPLICAS_ENV] == "2"
        assert env[constants.NUM_PROCESSES_ENV] == "2"
        assert env["TRAINER_INSTANCES_NUM"] == "2"

    def test_shrink_floor_is_min_replicas(self):
        cs, tc = make_env()
        job = self._running_elastic_job(cs, tc, replicas=2, min_replicas=2)
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        # At the floor: the ordinary restart machinery fires instead.
        assert got.status.elastic_replicas == {}
        assert got.status.restart_counts["trainer"] == 1

    def test_edl_manual_never_shrinks(self):
        cs, tc = make_env()
        for i in range(2):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2, min_replicas=1, max_replicas=2,
                       edl_policy="Manual",
                       restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.POD)
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(2):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.elastic_replicas == {}
        assert got.status.restart_counts["trainer"] == 1

    def test_starvation_shrink(self):
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.05
        cs.nodes.create(make_ready_node("node-0"))
        cs.nodes.create(make_ready_node("node-1"))
        job = make_job(replicas=3, min_replicas=2, max_replicas=3,
                       edl_policy="Auto")
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        set_pod_running(cs, "job-trainer-1", node="node-1")
        # Pod 2 stays Pending-unschedulable past the grace window.
        pod = cs.pods.get("default", "job-trainer-2")
        pod.status.conditions = [Condition(
            type="PodScheduled", status=ConditionStatus.FALSE,
            reason="Unschedulable", message="0/2 nodes available")]
        cs.pods.update(pod)
        time.sleep(0.1)  # past the scale_pending_time grace window
        sync(tc, job)
        got = get_job(cs)
        assert got.status.elastic_replicas["trainer"] == 2
        assert got.status.scaling_replica_name == "trainer"
        sync(tc, job, n=2)
        assert [p.name for p in pods_of(cs)] == ["job-trainer-0", "job-trainer-1"]
        # Out-of-range service removed along with the width change.
        svc_names = sorted(s.metadata.name for s in cs.services.list("default"))
        assert "job-trainer-2" not in svc_names

    def test_reexpand_probe_commit(self):
        """Probe flow: degraded group arms a reservation, which schedules ->
        the resize commits and the group re-rendezvouses at full width."""
        cs, tc = make_env()
        tc.options.scale_up_delay = 0.01
        job = self._running_elastic_job(cs, tc)
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job, n=3)  # shrink, drain, recreate at 2
        for i in range(2):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Capacity returns.
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.TRUE
        cs.nodes.update(node)
        time.sleep(0.02)  # past the re-expand backoff
        sync(tc, job)
        got = get_job(cs)
        # Probe armed: reservation requested, running group untouched.
        assert got.status.scale_probes == {"trainer": 3}
        assert got.status.elastic_replicas == {"trainer": 2}
        sync(tc, job)  # reservation pod created
        assert [p.name for p in pods_of(cs)] == [
            "job-trainer-0", "job-trainer-1", "job-trainer-2"]
        # Still Running at width 2 while the reservation is pending.
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Reservation schedules -> commit: drain for re-rendezvous.
        pod = cs.pods.get("default", "job-trainer-2")
        pod.spec.node_name = "node-2"
        cs.pods.update(pod)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.scale_probes == {}
        assert got.status.elastic_replicas == {}
        assert got.status.scaling_replica_name == "trainer"
        sync(tc, job, n=2)
        assert len(pods_of(cs)) == 3
        for i in range(3):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.RUNNING
        assert got.status.scale_up_attempts == {}

    def test_reexpand_probe_failure_nondestructive(self):
        """A probe that finds no capacity is discarded without touching the
        running group, and the backoff doubles."""
        cs, tc = make_env()
        tc.options.scale_up_delay = 0.01
        tc.options.scale_pending_time = 0.03
        job = self._running_elastic_job(cs, tc)
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job, n=3)
        for i in range(2):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        time.sleep(0.02)
        sync(tc, job)  # probe armed
        assert get_job(cs).status.scale_probes == {"trainer": 3}
        sync(tc, job)  # reservation created
        # Reservation starves: unschedulable past the grace window.
        pod = cs.pods.get("default", "job-trainer-2")
        pod.status.conditions = [Condition(
            type="PodScheduled", status=ConditionStatus.FALSE,
            reason="Unschedulable", message="0/2 nodes available")]
        cs.pods.update(pod)
        time.sleep(0.05)
        sync(tc, job)
        got = get_job(cs)
        # Probe discarded; running pods untouched; attempt counted.
        assert got.status.scale_probes == {}
        assert got.status.elastic_replicas == {"trainer": 2}
        assert got.status.scale_up_attempts == {"trainer": 1}
        assert got.status.scaling_replica_name == ""
        assert [p.name for p in pods_of(cs)] == [
            "job-trainer-0", "job-trainer-1"]
        assert got.status.phase == TrainingJobPhase.RUNNING

    def test_max_replicas_expansion_target(self):
        """maxReplicas > replicas is live: the probe targets max width."""
        cs, tc = make_env()
        tc.options.scale_up_delay = 0.01
        for i in range(3):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2, min_replicas=1, max_replicas=3,
                       edl_policy="Auto")
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(2):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # No prior resize -> no probe (last_scale_times empty): stable.
        sync(tc, job)
        assert get_job(cs).status.scale_probes == {}
        # After any resize event the group grows toward max when capacity
        # allows: simulate a degraded record.
        fresh = get_job(cs)
        fresh.status.elastic_replicas["trainer"] = 2
        fresh.status.last_scale_times["trainer"] = time.time() - 10
        cs.trainingjobs.update(fresh)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.scale_probes == {"trainer": 3}

    def test_no_shrink_after_success(self):
        # A resize discards finished work; once any pod succeeded the group
        # falls back to the ordinary machinery.
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.01
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=3, min_replicas=1, max_replicas=3,
                       edl_policy="Auto")
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_terminated(cs, "job-trainer-0", exit_code=0)
        pod = cs.pods.get("default", "job-trainer-1")
        pod.status.conditions = [Condition(
            type="PodScheduled", status=ConditionStatus.FALSE,
            reason="Unschedulable", message="0/1 nodes available")]
        cs.pods.update(pod)
        time.sleep(0.05)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.elastic_replicas == {}
        assert cs.pods.get("default", "job-trainer-0") is not None

    def test_shrink_floor_never_below_one(self):
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.01
        job = make_job(replicas=2, min_replicas=0, max_replicas=2,
                       edl_policy="Auto")
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(2):
            pod = cs.pods.get("default", f"job-trainer-{i}")
            pod.status.conditions = [Condition(
                type="PodScheduled", status=ConditionStatus.FALSE,
                reason="Unschedulable", message="0/0 nodes available")]
            cs.pods.update(pod)
        time.sleep(0.05)
        sync(tc, job)
        got = get_job(cs)
        # min_replicas=0 clamps to 1, never 0 (which could neither re-expand
        # nor be told apart from completion).
        assert got.status.elastic_replicas.get("trainer") == 1

    def test_multi_group_resize_restarts_all_groups(self):
        # Every group's env cross-references the resized group's host list;
        # a resize must re-rendezvous all of them.
        cs, tc = make_env()
        for i in range(3):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2, min_replicas=1, max_replicas=2,
                       edl_policy="Auto",
                       restart_policy=RestartPolicy.ON_NODE_FAIL)
        job.spec.replica_specs["pserver"] = ReplicaSpec(
            replicas=1,
            template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(name="aitj-main", image="img",
                          ports=[ContainerPort(name="aitj-2223",
                                               container_port=2223)])])))
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-pserver-0", node="node-0")
        set_pod_running(cs, "job-trainer-0", node="node-1")
        set_pod_running(cs, "job-trainer-1", node="node-2")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.elastic_replicas["trainer"] == 1
        assert pods_of(cs) == []  # pserver drained too
        sync(tc, job, n=2)
        pods = [p.name for p in pods_of(cs)]
        assert pods == ["job-pserver-0", "job-trainer-0"]
        # The recreated pserver sees the degraded trainer world.
        env = {e.name: e.value
               for e in cs.pods.get("default", "job-pserver-0")
               .spec.containers[0].env}
        assert env["TRAINER_INSTANCES_NUM"] == "1"

    def test_reservation_pod_marked(self):
        # Probe reservations carry the canary env so real workloads idle
        # instead of crashing the rendezvous.
        cs, tc = make_env()
        tc.options.scale_up_delay = 0.01
        job = self._running_elastic_job(cs, tc)
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job, n=3)
        for i in range(2):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        time.sleep(0.02)
        sync(tc, job, n=2)  # arm probe + create reservation
        res = cs.pods.get("default", "job-trainer-2")
        env = {e.name: e.value for e in res.spec.containers[0].env}
        assert env[constants.RESERVATION_ENV] == "1"
        base = cs.pods.get("default", "job-trainer-0")
        base_env = {e.name for e in base.spec.containers[0].env}
        assert constants.RESERVATION_ENV not in base_env

    def test_reexpand_partial_capacity_commits_partial_width(self):
        """Probe to full width with only part of the capacity back: commit
        the replicas that landed instead of discarding them."""
        cs, tc = make_env()
        tc.options.scale_up_delay = 0.01
        tc.options.scale_pending_time = 0.03
        job = self._running_elastic_job(cs, tc)  # width 3 on node-0..2
        for name in ("node-1", "node-2"):
            node = cs.nodes.get_node(name)
            node.status.conditions[0].status = ConditionStatus.FALSE
            cs.nodes.update(node)
        sync(tc, job, n=3)  # shrink to 1, drain, recreate
        set_pod_running(cs, "job-trainer-0", node="node-0")
        sync(tc, job)
        assert get_job(cs).status.elastic_replicas == {"trainer": 1}
        # Only node-1 comes back.
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.TRUE
        cs.nodes.update(node)
        time.sleep(0.02)
        sync(tc, job)  # arm probe to 3
        assert get_job(cs).status.scale_probes == {"trainer": 3}
        sync(tc, job)  # reservations 1 and 2 created
        pod = cs.pods.get("default", "job-trainer-1")
        pod.spec.node_name = "node-1"
        cs.pods.update(pod)
        pod = cs.pods.get("default", "job-trainer-2")
        pod.status.conditions = [Condition(
            type="PodScheduled", status=ConditionStatus.FALSE,
            reason="Unschedulable", message="0/2 nodes available")]
        cs.pods.update(pod)
        time.sleep(0.05)
        sync(tc, job)
        got = get_job(cs)
        # Partial commit: width 2 (landed reservation), not a discard.
        assert got.status.elastic_replicas == {"trainer": 2}
        assert got.status.scaling_replica_name == "trainer"
        sync(tc, job, n=2)
        assert [p.name for p in pods_of(cs)] == [
            "job-trainer-0", "job-trainer-1"]


class TestRound4Regressions:
    """VERDICT r3 fixes: classification order, env precedence, the
    ImagePullBackOff-after-Running wedge, reservation TTL injection."""

    def test_dead_pod_on_dead_node_shrinks_not_restarts(self):
        # A pod that died BECAUSE its node died (exit 137 + node NotReady)
        # is capacity loss -> elastic shrink, not a full-width exit-code
        # restart stranding a replacement Unschedulable (VERDICT r3 item 2
        # diagnosis: the 47 s bench samples).
        cs, tc = make_env()
        for i in range(2):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2, min_replicas=1, max_replicas=2,
                       edl_policy="Auto",
                       restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
                       restart_scope=RestartScope.ALL)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        set_pod_running(cs, "job-trainer-1", node="node-1")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Node-1 dies AND its pod's kill is observed in the same sync.
        set_pod_terminated(cs, "job-trainer-1", 137, node="node-1")
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.elastic_replicas == {"trainer": 1}
        assert got.status.phase == TrainingJobPhase.SCALING
        assert got.status.restart_counts.get("trainer", 0) == 0

    def test_template_env_wins_over_injected(self):
        # A template-supplied env var must not be clobbered by the injected
        # default (stale shared checkpoint dirs leaked state across jobs).
        from trainingjob_operator_tpu.core.objects import EnvVar

        cs, tc = make_env()
        job = make_job(replicas=1)
        job.spec.replica_specs["trainer"].template.spec.containers[0].env = [
            EnvVar(constants.CHECKPOINT_DIR_ENV, "/custom/ckpt")]
        cs.trainingjobs.create(job)
        sync(tc, job)
        env = [e for e in pods_of(cs)[0].spec.containers[0].env
               if e.name == constants.CHECKPOINT_DIR_ENV]
        assert [e.value for e in env] == ["/custom/ckpt"]

    def test_waiting_error_after_running_restarts(self):
        # ImagePullBackOff entered AFTER the job reached Running (image GC +
        # node reboot): the reference wedges forever (pod.go:355-378 needs a
        # live Creating condition); we time the error from first observation.
        cs, tc = make_env()
        tc.options.creating_duration_time = 0.05
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=1, restart_policy=RestartPolicy.ON_FAILURE)
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        pod = cs.pods.get("default", "job-trainer-0")
        pod.status.container_statuses = [ContainerStatus(
            name="aitj-main",
            state=ContainerState(waiting_reason="ImagePullBackOff"))]
        cs.pods.update(pod)
        sync(tc, job)  # first observation recorded; no restart yet
        assert get_job(cs).status.restart_counts.get("trainer", 0) == 0
        time.sleep(0.1)
        sync(tc, job)  # past creating_duration_time -> restart
        assert get_job(cs).status.restart_counts.get("trainer", 0) == 1

    def test_reservation_pod_gets_ttl_env(self):
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.05
        tc.options.scale_up_delay = 0.05
        for i in range(2):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2, min_replicas=1, max_replicas=2,
                       edl_policy="Auto",
                       restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.REPLICA)
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        set_pod_running(cs, "job-trainer-1", node="node-1")
        sync(tc, job)
        # Lose node-1 -> shrink to 1 -> drain -> recreate -> probe back up.
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job, n=3)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.TRUE
        cs.nodes.update(node)
        time.sleep(0.1)
        sync(tc, job, n=2)  # arm probe + create reservation
        res = cs.pods.get("default", "job-trainer-1")
        env = {e.name: e.value for e in res.spec.containers[0].env}
        assert env.get(constants.RESERVATION_ENV) == "1"
        assert float(env[constants.RESERVATION_TTL_ENV]) >= 120.0

    def test_non_elastic_dead_pod_on_dead_node_still_restarts(self):
        # The NODE_FAIL-first reorder is elastic-only: a non-elastic job's
        # failed pod on a dead node must still take the exit-code restart
        # path (was: returned NODE_FAIL with is_restart=False and wedged).
        cs, tc = make_env()
        cs.nodes.create(make_ready_node("node-0"))
        job = make_job(replicas=1, restart_policy=RestartPolicy.EXIT_CODE,
                       restart_scope=RestartScope.POD)
        job.spec.restarting_exit_code = "137"
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        set_pod_terminated(cs, "job-trainer-0", 137, node="node-0")
        node = cs.nodes.get_node("node-0")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        assert get_job(cs).status.restart_counts.get("trainer", 0) == 1


class TestGangAtomicity:
    """SURVEY §7 hard-part (a): multi-host slices are all-or-nothing.
    Improves on the reference's per-index gap fill (pod.go:186-193), which
    would leave a partial gang pinning TPU hosts forever."""

    def _tpu_job(self, replicas=4, slice_count=1, **kw):
        # topology 4x4 = 16 chips = 4 TPU-VM hosts per slice.
        job = make_job(replicas=replicas,
                       tpu=TPUSpec(accelerator="tpu-v5-lite-podslice",
                                   topology="4x4", slice_count=slice_count),
                       **kw)
        return job

    def test_partial_gang_released_whole(self):
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.05
        for i in range(3):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = self._tpu_job(restart_policy=RestartPolicy.ON_NODE_FAIL)
        cs.trainingjobs.create(job)
        sync(tc, job)
        pods = pods_of(cs)
        assert len(pods) == 4
        first_uids = {p.metadata.uid for p in pods}
        # 3 of 4 hosts placed; host 3 starves (no TPU capacity).
        for i in range(3):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        pod = cs.pods.get("default", "job-trainer-3")
        pod.status.conditions = [Condition(
            type="PodScheduled", status=ConditionStatus.FALSE,
            reason="Unschedulable",
            message="0/3 nodes available: insufficient google.com/tpu")]
        cs.pods.update(pod)
        time.sleep(0.1)  # past scale_pending_time
        sync(tc, job)
        # Whole gang released: the 3 placed pods no longer hold their hosts.
        assert pods_of(cs) == []
        assert get_job(cs).status.phase != TrainingJobPhase.RUNNING
        sync(tc, job)  # atomic retry: all 4 recreated fresh
        pods = pods_of(cs)
        assert len(pods) == 4
        assert first_uids.isdisjoint({p.metadata.uid for p in pods})

    def test_fully_unplaced_gang_not_torn_down(self):
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.05
        job = self._tpu_job(restart_policy=RestartPolicy.ON_NODE_FAIL)
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(4):
            pod = cs.pods.get("default", f"job-trainer-{i}")
            pod.status.conditions = [Condition(
                type="PodScheduled", status=ConditionStatus.FALSE,
                reason="Unschedulable", message="0/0 nodes available")]
            cs.pods.update(pod)
        time.sleep(0.1)
        sync(tc, job)
        # Nothing placed -> nothing held -> keep waiting, don't churn.
        assert len(pods_of(cs)) == 4

    def test_two_slice_job_loses_one_slice_shrinks_whole_slice(self):
        # VERDICT r3 item 3: elastic unit is the slice.  A 2-slice job
        # losing one host of slice 1 drops the WHOLE slice and
        # re-rendezvouses as a 1-slice job (narrower DCN-dp), never
        # stranding a sub-slice.
        cs, tc = make_env()
        for i in range(8):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = self._tpu_job(replicas=8, slice_count=2, min_replicas=4,
                            edl_policy="Auto",
                            restart_policy=RestartPolicy.ON_NODE_FAIL,
                            restart_scope=RestartScope.ALL)
        cs.trainingjobs.create(job)
        sync(tc, job)
        assert len(pods_of(cs)) == 8
        for i in range(8):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        # Lose the node of host 5 (slice 1).
        node = cs.nodes.get_node("node-5")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.SCALING
        assert got.status.elastic_replicas == {"trainer": 4}  # one slice
        assert got.status.restart_counts.get("trainer", 0) == 0
        sync(tc, job, n=2)  # drain observed; recreate at one slice
        pods = pods_of(cs)
        assert [p.name for p in pods] == [f"job-trainer-{i}" for i in range(4)]
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env[constants.NUM_SLICES_ENV] == "1"  # effective DCN-dp width
        assert env[constants.NUM_PROCESSES_ENV] == "4"
        assert pods[0].metadata.labels[constants.SLICE_ID_LABEL] == "0"

    def test_min_width_rounds_up_to_whole_slice(self):
        from trainingjob_operator_tpu.api.types import ReplicaSpec as RS

        cs, tc = make_env()
        spec = RS(replicas=8, min_replicas=4,
                  tpu=TPUSpec(topology="4x4", slice_count=2))
        assert tc._min_width(spec) == 4
        spec = RS(replicas=8, min_replicas=3,
                  tpu=TPUSpec(topology="4x4", slice_count=2))
        assert tc._min_width(spec) == 4  # 3 hosts is not a runnable unit

    def test_gang_release_backs_off(self):
        # A persistent one-host-short cluster must not delete/recreate the
        # slice at scale_pending_time period forever: releases back off
        # exponentially and reset only when the group runs at full width.
        cs, tc = make_env()
        tc.options.scale_pending_time = 0.05
        for i in range(3):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=4,
                       tpu=TPUSpec(accelerator="tpu-v5-lite-podslice",
                                   topology="4x4"),
                       restart_policy=RestartPolicy.ON_NODE_FAIL)
        cs.trainingjobs.create(job)
        sync(tc, job)

        def strand_pod_3():
            for i in range(3):
                set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
            pod = cs.pods.get("default", "job-trainer-3")
            pod.status.conditions = [Condition(
                type="PodScheduled", status=ConditionStatus.FALSE,
                reason="Unschedulable", message="0/3 nodes available")]
            cs.pods.update(pod)

        strand_pod_3()
        time.sleep(0.1)
        sync(tc, job)
        assert pods_of(cs) == []  # release 1 fired
        key = "default/job/trainer"
        last, attempts = tc._gang_release_backoff[key]
        assert attempts == 1
        # An immediate retry is suppressed (inside the backoff window).
        assert tc._release_partial_gangs(
            get_job(cs), "trainer", "trainer", 4, [3], [], last + 0.01) is None
        # Once the group runs at full width, the backoff resets.
        sync(tc, job)  # recreate all 4
        for i in range(4):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i % 3}")
        sync(tc, job)
        assert key not in tc._gang_release_backoff


class TestControllerRestart:
    def test_new_controller_resumes_mid_scaling_drain(self):
        """Controller crash/restart mid-elastic-drain: a fresh controller
        (empty expectations, no in-memory state) must pick the job up from
        its status and finish the resize -- the CR carries the contract."""
        cs, tc = make_env()
        for i in range(3):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=3, min_replicas=1, max_replicas=3,
                       edl_policy="Auto",
                       restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.REPLICA)
        cs.trainingjobs.create(job)
        sync(tc, job)
        for i in range(3):
            set_pod_running(cs, f"job-trainer-{i}", node=f"node-{i}")
        sync(tc, job)
        node = cs.nodes.get_node("node-2")
        node.status.conditions[0].status = ConditionStatus.FALSE
        cs.nodes.update(node)
        sync(tc, job)  # shrink decided; pods deleted; drain in flight
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.SCALING
        assert got.status.elastic_replicas == {"trainer": 2}

        # "Crash": a brand-new controller instance over the same cluster.
        tc2 = TrainingJobController(cs, options=OperatorOptions())
        sync(tc2, job, n=3)
        pods = pods_of(cs)
        assert [p.name for p in pods] == ["job-trainer-0", "job-trainer-1"]
        env = {e.name: e.value for e in pods[0].spec.containers[0].env}
        assert env[constants.NUM_PROCESSES_ENV] == "2"
        for p in pods:
            set_pod_running(cs, p.name, node="node-0")
        sync(tc2, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
