"""Discrete-event sim kernel tests: TimerQueue ordering/cancel semantics,
watch-event timer re-arming, the created-then-deleted-in-window reap
regression (PR 6), and scan-vs-event phase-count parity on a seeded fleet.

The lifecycle tests drive ``SimRuntime(kernel="event")`` directly with raw
pods (node preset, no controller): the point is the kernel's timer plumbing,
not job semantics -- ``test_e2e_sim.py`` covers those end to end.
"""

import json

import pytest

from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.tracker import NotFoundError
from trainingjob_operator_tpu.core.objects import (
    Container,
    ObjectMeta,
    Pod,
    PodPhase,
    PodSpec,
)
from trainingjob_operator_tpu.fleet.churn import ChurnProfile
from trainingjob_operator_tpu.fleet.harness import FleetHarness
from trainingjob_operator_tpu.runtime.events import TimerQueue
from trainingjob_operator_tpu.runtime.sim import (
    EXIT_CODE_ANNOTATION,
    RUN_SECONDS_ANNOTATION,
    START_DELAY_ANNOTATION,
    SimRuntime,
    resolve_kernel,
)

from conftest import wait_for  # noqa: E402


class TestTimerQueue:
    def test_pops_in_deadline_order(self):
        q = TimerQueue()
        q.arm("c", "exit", 3.0)
        q.arm("a", "exit", 1.0)
        q.arm("b", "exit", 2.0)
        assert q.next_deadline() == 1.0
        assert [k for k, _, _ in q.pop_due(10.0)] == ["a", "b", "c"]

    def test_equal_deadlines_pop_in_arm_order(self):
        """(deadline, seq) tie-break: same instant -> arm order, every run.

        Seeded fleet determinism rides on this; a dict-order or id()-order
        fallback would shuffle same-tick placements between runs."""
        def build():
            q = TimerQueue()
            for key in ("x", "m", "a", "z", "b"):
                q.arm(key, "start", 5.0)
            return [k for k, _, _ in q.pop_due(5.0)]

        first = build()
        assert first == ["x", "m", "a", "z", "b"]  # arm order, not sorted
        assert all(build() == first for _ in range(5))

    def test_rearm_supersedes_old_deadline(self):
        q = TimerQueue()
        q.arm("p", "exit", 1.0)
        q.arm("p", "exit", 9.0)  # deadline moved (preempt cleared, say)
        assert q.depth() == 1
        assert q.pop_due(5.0) == []  # old entry is a tombstone
        assert q.next_deadline() == 9.0
        assert q.pop_due(9.0) == [("p", "exit", 9.0)]

    def test_arm_reports_new_earliest(self):
        q = TimerQueue()
        assert q.arm("a", "exit", 5.0)       # first is always earliest
        assert not q.arm("b", "exit", 7.0)   # behind a: no wake needed
        assert q.arm("c", "exit", 1.0)       # new front: wake the kernel

    def test_cancel_and_cancel_all(self):
        q = TimerQueue()
        q.arm("p", "start", 1.0)
        q.arm("p", "exit", 2.0)
        q.arm("r", "exit", 3.0)
        q.cancel("p", "start")
        assert not q.armed("p", "start")
        assert q.armed("p", "exit")
        q.cancel_all("p")
        assert q.depth() == 1
        assert [k for k, _, _ in q.pop_due(10.0)] == ["r"]

    def test_next_deadline_skips_tombstones(self):
        q = TimerQueue()
        q.arm("a", "exit", 1.0)
        q.arm("b", "exit", 2.0)
        q.cancel("a", "exit")
        assert q.next_deadline() == 2.0
        q.cancel("b", "exit")
        assert q.next_deadline() is None

    def test_pop_due_respects_limit_and_now(self):
        q = TimerQueue()
        for i in range(6):
            q.arm(f"p{i}", "step", float(i))
        assert len(q.pop_due(10.0, limit=4)) == 4
        assert len(q.pop_due(4.5)) == 1  # p4; p5 due at 5.0 stays armed
        assert q.depth() == 1

    def test_compaction_keeps_live_timers(self):
        """Re-arm storms leave tombstones; compaction must drop only those."""
        q = TimerQueue()
        q.arm("keep", "exit", 5000.0)
        for i in range(1000):
            q.arm("storm", "step", float(i))  # 999 tombstones
        assert q.depth() == 2
        assert len(q._heap) < 1000  # compaction actually ran
        assert q.pop_due(999.0) == [("storm", "step", 999.0)]
        assert q.next_deadline() == 5000.0


def raw_pod(name, node="n0", run_seconds="0.1", exit_code="0",
            start_delay=None):
    annotations = {RUN_SECONDS_ANNOTATION: run_seconds,
                   EXIT_CODE_ANNOTATION: exit_code}
    if start_delay is not None:
        annotations[START_DELAY_ANNOTATION] = start_delay
    pod = Pod(metadata=ObjectMeta(name=name, namespace="default",
                                  annotations=annotations),
              spec=PodSpec(containers=[Container(name="aitj-main")]))
    pod.spec.node_name = node
    return pod


@pytest.fixture
def event_sim():
    cs = Clientset()
    sim = SimRuntime(cs, kernel="event")
    sim.add_node("n0")
    sim.start()
    yield cs, sim
    sim.stop()


def pod_phase(cs, name):
    try:
        return cs.pods.get("default", name).status.phase
    except NotFoundError:
        return None


def pod_gone(cs, name):
    return pod_phase(cs, name) is None


class TestEventKernelLifecycle:
    def test_pod_runs_and_exits_on_timers(self, event_sim):
        """Create -> start timer -> self-echo arms the exit timer -> done."""
        cs, sim = event_sim
        cs.pods.create(raw_pod("p0", run_seconds="0.1"))
        assert wait_for(lambda: pod_phase(cs, "p0") == PodPhase.RUNNING, 5)
        assert wait_for(lambda: pod_phase(cs, "p0") == PodPhase.SUCCEEDED, 5)
        assert sim.events_total > 0

    def test_preempt_rearms_exit_timer(self, event_sim):
        """An API event must retarget a pending deadline: the pod's exit
        timer sits 30 s out; the preempt re-arms it to fire now."""
        cs, sim = event_sim
        cs.pods.create(raw_pod("victim", run_seconds="30"))
        assert wait_for(lambda: pod_phase(cs, "victim") == PodPhase.RUNNING, 5)
        sim.preempt_pod("default", "victim", exit_code=137)
        assert wait_for(lambda: pod_phase(cs, "victim") == PodPhase.FAILED, 5)

    def test_delete_cancels_timers_and_finalizes(self, event_sim):
        """DELETED watch event cancels the (far-future) exit timer and the
        grace timer finalizes the pod -- nothing waits on the 30 s exit."""
        cs, sim = event_sim
        cs.pods.create(raw_pod("doomed", run_seconds="30"))
        assert wait_for(lambda: pod_phase(cs, "doomed") == PodPhase.RUNNING, 5)
        cs.pods.delete("default", "doomed")
        assert wait_for(lambda: pod_gone(cs, "doomed"), 5)

    def test_created_then_deleted_in_window_never_wedges(self, event_sim):
        """PR 6 reap regression: a pod created and deleted before its start
        timer fires must still be finalized, and the kernel must keep
        serving later pods (no wedged state entry, no leaked timers)."""
        cs, sim = event_sim
        cs.pods.create(raw_pod("blink", run_seconds="30", start_delay="0.5"))
        cs.pods.delete("default", "blink")  # still Pending, start unfired
        assert wait_for(lambda: pod_gone(cs, "blink"), 5)
        # The kernel is still live: a follow-up pod runs to completion...
        cs.pods.create(raw_pod("after", run_seconds="0.05"))
        assert wait_for(lambda: pod_phase(cs, "after") == PodPhase.SUCCEEDED, 5)
        # ...and the queue drains back to the watchdog heartbeat alone.
        assert wait_for(lambda: sim._timers.depth() == 1, 5), sim._timers.depth()


class TestKernelSelection:
    def test_explicit_arg_wins(self, monkeypatch):
        monkeypatch.setenv("TRAININGJOB_SIM_KERNEL", "event")
        assert resolve_kernel("scan") == "scan"

    def test_env_overrides_default(self, monkeypatch):
        monkeypatch.setenv("TRAININGJOB_SIM_KERNEL", "scan")
        assert resolve_kernel() == "scan"
        monkeypatch.delenv("TRAININGJOB_SIM_KERNEL")
        assert resolve_kernel() == "event"

    def test_unknown_kernel_rejected(self):
        with pytest.raises(ValueError):
            resolve_kernel("quantum")


class TestKernelParity:
    def test_seeded_200_job_phase_counts_identical(self):
        """The tentpole's determinism contract: the same seeded 200-job
        churn run lands byte-identical per-fate phase counts under both
        kernels (same profile as ``make fleet-smoke``).  ~2 x 11 s on the
        single-core CI box -- the one deliberately slow tier-1 test here."""
        profile = ChurnProfile(jobs=200, duration=3.0, seed=0,
                               replicas=(1, 4))
        counts = {}
        for kernel in ("scan", "event"):
            harness = FleetHarness(profile, workers=4, resync_period=30.0,
                                   gc_interval=30.0, converge_timeout=120.0,
                                   sim_kernel=kernel)
            report = harness.run()
            assert report.converged, (kernel, report.violations[:10])
            assert report.violations == [], (kernel, report.violations[:10])
            assert report.sim_kernel == kernel
            counts[kernel] = json.dumps(report.phase_counts, sort_keys=True)
        assert counts["scan"] == counts["event"]
