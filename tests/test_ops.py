"""Pallas kernel tests: real kernels in interpreter mode on CPU
(TRAININGJOB_PALLAS=interpret) checked against the XLA references."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override

apply_jax_platform_override()
import jax.numpy as jnp


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setenv("TRAININGJOB_PALLAS", "interpret")


def qkv(B=2, T=64, H=4, Hkv=4, D=16, seed=0, dtype=jnp.float32):
    key = jax.random.PRNGKey(seed)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, D), dtype)
    k = jax.random.normal(kk, (B, T, Hkv, D), dtype)
    v = jax.random.normal(kv, (B, T, Hkv, D), dtype)
    return q, k, v


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv()
        got = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        want = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_gqa_heads(self):
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(H=4, Hkv=2)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        want = reference_attention(q, jnp.repeat(k, 2, axis=2),
                                   jnp.repeat(v, 2, axis=2), causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_uneven_blocks(self):
        # block_q != block_k and blocks not dividing evenly into the causal
        # diagonal exercise the per-block masking.
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(T=48)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=8)
        want = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_causal_block_q_smaller_than_block_k(self):
        # Regression: with block_q % block_k != 0 and block_k > block_q the
        # causal KV-block count must be ceil((qi+1)*block_q / block_k);
        # counting from the block start skipped diagonal KV blocks.
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(T=96 * 4)
        got = flash_attention(q, k, v, causal=True, block_q=96, block_k=128)
        want = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_reference(self):
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(T=32)

        def f_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True, block_q=16,
                                    block_k=16) ** 2).sum()

        def f_ref(q, k, v):
            return (reference_attention(q, k, v, causal=True) ** 2).sum()

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-4)

    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("Hkv", [4, 2])
    def test_bwd_kernel_grads(self, causal, Hkv):
        """The Pallas dq/dkv kernels (interpret mode) against the reference
        vjp: GQA group-sum, non-causal, uneven blocks, non-divisible T."""
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(T=40, H=4, Hkv=Hkv)
        cot = jax.random.normal(jax.random.PRNGKey(9), q.shape)

        def rep(x):
            return jnp.repeat(x, 4 // Hkv, axis=2) if Hkv != 4 else x

        _, vjp = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, block_q=16, block_k=8), q, k, v)
        got = vjp(cot)
        _, rvjp = jax.vjp(lambda a, b, c: reference_attention(
            a, rep(b), rep(c), causal=causal), q, k, v)
        want = rvjp(cot)
        for name, a, b in zip("qkv", got, want):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4,
                err_msg=f"d{name} (causal={causal}, Hkv={Hkv})")

    def test_bf16_io_f32_stats(self):
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        q, k, v = qkv(dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
        assert got.dtype == jnp.bfloat16
        want = reference_attention(q.astype(jnp.float32),
                                   k.astype(jnp.float32),
                                   v.astype(jnp.float32), causal=True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want), rtol=2e-2, atol=2e-2)


class TestRMSNorm:
    def test_matches_reference(self):
        from trainingjob_operator_tpu.ops import rmsnorm

        x = jax.random.normal(jax.random.PRNGKey(0), (4, 10, 32))
        scale = jax.random.normal(jax.random.PRNGKey(1), (32,)) + 1.0
        got = rmsnorm(x, scale)
        xf = np.asarray(x, np.float64)
        want = (xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-5)
                * np.asarray(scale, np.float64))
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5,
                                   atol=2e-5)

    def test_grads_flow(self):
        from trainingjob_operator_tpu.ops import rmsnorm

        x = jax.random.normal(jax.random.PRNGKey(2), (8, 16))
        scale = jnp.ones((16,))
        g = jax.grad(lambda x_, s_: (rmsnorm(x_, s_) ** 2).sum(),
                     argnums=(0, 1))(x, scale)
        assert all(bool(jnp.isfinite(gi).all()) for gi in g)


class TestDispatch:
    def test_cpu_defaults_to_xla_reference(self, monkeypatch):
        monkeypatch.setenv("TRAININGJOB_PALLAS", "auto")
        from trainingjob_operator_tpu import ops

        assert ops.use_pallas() is False  # tests run on CPU

    def test_off_switch(self, monkeypatch):
        monkeypatch.setenv("TRAININGJOB_PALLAS", "off")
        from trainingjob_operator_tpu import ops

        assert ops.use_pallas() is False


class TestFlashPadding:
    def test_seq_not_divisible_by_blocks(self):
        # T=40 with 16/16 blocks: pads to 48, masks the 8 phantom keys,
        # slices the phantom query rows -- regression for silent row drop.
        from trainingjob_operator_tpu.ops import flash_attention
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        for causal in (True, False):
            q, k, v = qkv(T=40)
            got = flash_attention(q, k, v, causal=causal,
                                  block_q=16, block_k=16)
            want = reference_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=2e-5)

    def test_sharded_wrapper_matches(self):
        from trainingjob_operator_tpu.ops.flash_attention import (
            flash_attention_sharded)
        from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh
        from trainingjob_operator_tpu.parallel.ringattention import (
            reference_attention)

        mesh = make_mesh(MeshSpec.of(dp=2, tp=4))
        q, k, v = qkv(B=4, T=32, H=4, Hkv=4)
        got = flash_attention_sharded(q, k, v, mesh, causal=True)
        want = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)


class TestSlidingWindow:
    """Mistral-style sliding-window attention: the kernels skip KV blocks
    outside the band; math matches a banded-mask reference."""

    def _banded_reference(self, q, k, v, window):
        # [B, T, H, D] inputs; full-mask reference with the band applied.
        import jax.numpy as jnp

        B, T, H, D = q.shape
        Hkv = k.shape[2]
        if H != Hkv:
            k = jnp.repeat(k, H // Hkv, axis=2)
            v = jnp.repeat(v, H // Hkv, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * (D ** -0.5)
        rows = jnp.arange(T)[:, None]
        cols = jnp.arange(T)[None, :]
        mask = (cols <= rows) & (cols > rows - window)
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                          preferred_element_type=jnp.float32).astype(q.dtype)

    @pytest.mark.parametrize("T,window,bq,bk", [
        (64, 16, 16, 16),   # band spans multiple KV blocks
        (64, 8, 16, 16),    # band inside one block
        (48, 33, 16, 8),    # window not a block multiple; uneven blocks
    ])
    def test_kernel_matches_banded_reference(self, interpret_mode, T, window,
                                             bq, bk):
        from trainingjob_operator_tpu.ops.flash_attention import (
            flash_attention)

        B, H, Hkv, D = 2, 4, 2, 16
        key = jax.random.PRNGKey(3)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, T, Hkv, D), jnp.float32)
        v = jax.random.normal(kv, (B, T, Hkv, D), jnp.float32)
        got = flash_attention(q, k, v, causal=True, window=window,
                              block_q=bq, block_k=bk)
        want = self._banded_reference(q, k, v, window)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)

    def test_kernel_grads_match_banded_reference(self, interpret_mode):
        from trainingjob_operator_tpu.ops.flash_attention import (
            flash_attention)

        B, T, H, Hkv, D, W = 1, 32, 2, 2, 8, 12
        key = jax.random.PRNGKey(5)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, H, D), jnp.float32)
        k = jax.random.normal(kk, (B, T, Hkv, D), jnp.float32)
        v = jax.random.normal(kv, (B, T, Hkv, D), jnp.float32)

        g1 = jax.grad(lambda *a: (flash_attention(
            *a, causal=True, window=W, block_q=8, block_k=8) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda *a: (self._banded_reference(*a, W) ** 2).sum(),
                      argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3)

    def test_window_requires_causal(self):
        from trainingjob_operator_tpu.ops.flash_attention import (
            flash_attention)

        q = jnp.zeros((1, 8, 2, 4))
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, q, q, causal=False, window=4)

    def test_llama_and_decode_agree_under_window(self):
        """Train-path (flash) and decode-path (cache mask) sliding windows
        are the same attention pattern: teacher-forced decode logits match
        the forward's."""
        import dataclasses

        from trainingjob_operator_tpu.models import decode, llama

        cfg = dataclasses.replace(llama.LlamaConfig.tiny(n_layers=2),
                                  sliding_window=6, dtype="float32")
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                    cfg.vocab_size)
        full = llama.forward(params, tokens, cfg)  # [B, T, V]
        _, cache = decode.prefill(params, tokens[:, :1], cfg, max_len=12)
        logits_t = []
        for t in range(1, 12):
            lg, cache = decode.decode_step(params, cache, tokens[:, t - 1],
                                           jnp.int32(t - 1), cfg)
            logits_t.append(lg)
        # decode_step at position t-1 predicts token t: compare with the
        # forward's logits at position t-1.
        for t, lg in enumerate(logits_t, start=1):
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(full[:, t - 1]),
                                       rtol=2e-3, atol=2e-3)
