"""obs/ subsystem: span tracer, structured logs, goodput accounting, the
debug HTTP endpoints, and the end-to-end reconcile traces.

Unit layer first (tracer semantics, exporters, the no-op fast path's
zero-lock guarantee), then HTTP via the scrape pattern of
test_examples_and_metrics.py, then e2e: a localproc job whose reconcile
trace has a root ``sync_job`` span with children, and a sim job whose
completed goodput ratio lands on /metrics.
"""

import contextvars
import io
import json
import logging
import sys
import threading
import urllib.error
import urllib.request

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.goodput import GOODPUT, GoodputTracker
from trainingjob_operator_tpu.obs.logs import (
    ContextTextFormatter,
    JsonFormatter,
    configure_logging,
    get_logger,
)
from trainingjob_operator_tpu.obs.trace import (
    ERROR,
    NOOP_SPAN,
    TRACER,
    Tracer,
    current_context,
    current_span,
    group_traces,
    spans_from_jsonl,
    tracer_from_env,
)
from trainingjob_operator_tpu.utils.metrics import (
    METRICS,
    MetricsRegistry,
    _Histogram,
    serve_metrics,
)

from conftest import wait_for  # noqa: E402


# -- tracer unit layer -------------------------------------------------------

class TestSpanParenting:
    def test_nested_spans_auto_parent_and_flush_one_trace(self):
        t = Tracer()
        with t.span("root", job="default/j1") as root:
            assert current_span() is root
            assert current_context() == f"{root.trace_id}:{root.span_id}"
            with t.span("child") as child:
                assert child.trace_id == root.trace_id
                assert child.parent_id == root.span_id
                with t.span("grandchild") as gc:
                    assert gc.parent_id == child.span_id
        assert current_span() is None
        assert current_context() == ""
        traces = t.traces()
        assert len(traces) == 1
        tr = traces[0]
        assert tr["root"] == "root"
        assert tr["trace_id"] == root.trace_id
        assert [s["name"] for s in tr["spans"]] == [
            "grandchild", "child", "root"]
        root_rec = tr["spans"][-1]
        assert root_rec["parent_id"] is None
        assert root_rec["attributes"]["job"] == "default/j1"

    def test_sibling_roots_make_separate_traces(self):
        t = Tracer()
        with t.span("a"):
            pass
        with t.span("b"):
            pass
        assert [tr["root"] for tr in t.traces()] == ["b", "a"]  # newest first

    def test_exception_marks_error_and_propagates(self):
        t = Tracer()
        with pytest.raises(ValueError):
            with t.span("boom"):
                raise ValueError("nope")
        span = t.traces()[0]["spans"][0]
        assert span["status"] == ERROR
        assert span["attributes"]["exception"] == "ValueError: nope"

    def test_set_attribute_and_status_chain(self):
        t = Tracer()
        with t.span("x") as sp:
            sp.set_attribute("k", 1).set_status(ERROR)
        span = t.traces()[0]["spans"][0]
        assert span["attributes"]["k"] == 1 and span["status"] == ERROR


class TestCrossThread:
    def test_fresh_thread_does_not_inherit_context(self):
        t = Tracer()
        seen = {}

        def worker():
            seen["current"] = current_span()
            with t.span("detached"):
                pass

        with t.span("root") as root:
            th = threading.Thread(target=worker, daemon=True)
            th.start()
            th.join(5)
        traces = {tr["root"]: tr for tr in t.traces()}
        assert seen["current"] is None
        assert traces["detached"]["trace_id"] != traces["root"]["trace_id"]

    def test_explicit_parent_joins_the_trace_across_threads(self):
        t = Tracer()

        def worker(parent):
            with t.span("cross", parent=parent):
                pass

        with t.span("root") as root:
            th = threading.Thread(target=worker, args=(root,), daemon=True)
            th.start()
            th.join(5)
        tr = t.traces()[0]
        names = {s["name"]: s for s in tr["spans"]}
        assert set(names) == {"root", "cross"}
        assert names["cross"]["parent_id"] == names["root"]["span_id"]

    def test_copied_context_carries_the_current_span(self):
        t = Tracer()
        seen = {}

        def worker():
            seen["current"] = current_span()

        with t.span("root") as root:
            ctx = contextvars.copy_context()
            th = threading.Thread(target=lambda: ctx.run(worker), daemon=True)
            th.start()
            th.join(5)
        assert seen["current"] is root


class TestRingAndCaps:
    def test_finished_ring_evicts_oldest(self):
        t = Tracer(max_traces=3)
        for i in range(5):
            with t.span(f"r{i}"):
                pass
        assert [tr["root"] for tr in t.traces()] == ["r4", "r3", "r2"]
        assert t.traces(limit=1)[0]["root"] == "r4"
        t.clear()
        assert t.traces() == []

    def test_span_cap_drops_descendants_but_keeps_root(self):
        t = Tracer()
        t.MAX_SPANS_PER_TRACE = 3
        with t.span("root"):
            for i in range(5):
                with t.span(f"c{i}"):
                    pass
        tr = t.traces()[0]
        names = [s["name"] for s in tr["spans"]]
        assert names == ["c0", "c1", "c2", "root"]
        assert tr["dropped_spans"] == 2

    def test_env_style_parent_adopts_trace_id_as_local_root(self):
        t = Tracer()
        with t.span("remote", parent="aaaa:bbbb"):
            pass
        tr = t.traces()[0]
        assert tr["trace_id"] == "aaaa"
        assert tr["spans"][0]["parent_id"] == "bbbb"


class TestExporters:
    def _sample(self):
        t = Tracer()
        with t.span("root", job="default/j1"):
            with t.span("child"):
                pass
        with t.span("other"):
            pass
        return t

    def test_jsonl_round_trip(self):
        t = self._sample()
        spans = spans_from_jsonl(t.export_jsonl())
        grouped = group_traces(spans)
        original = {tr["trace_id"]: tr["spans"] for tr in t.traces()}
        assert set(grouped) == set(original)
        for tid, sp in grouped.items():
            assert [s["name"] for s in sp] == [s["name"] for s in original[tid]]

    def test_chrome_export_event_shape(self):
        t = self._sample()
        doc = json.loads(t.export_chrome())
        events = doc["traceEvents"]
        assert len(events) == 3
        for ev in events:
            # The Chrome trace_event contract Perfetto needs.
            assert {"ph", "ts", "pid", "tid", "name"} <= set(ev)
            assert ev["ph"] == "X"
            assert ev["dur"] >= 0
            assert "trace_id" in ev["args"]
        assert {ev["name"] for ev in events} == {"root", "child", "other"}

    def test_empty_exports(self):
        t = Tracer()
        assert t.export_jsonl() == ""
        assert json.loads(t.export_chrome())["traceEvents"] == []


class _CountingLock:
    """Lock wrapper counting acquisitions -- proves the no-op fast path."""

    def __init__(self):
        self.acquisitions = 0
        self._lock = threading.Lock()

    def __enter__(self):
        self.acquisitions += 1
        return self._lock.__enter__()

    def __exit__(self, *exc):
        return self._lock.__exit__(*exc)


class TestDisabledTracer:
    def test_disabled_span_is_the_shared_noop_singleton(self):
        t = Tracer(enabled=False)
        sp = t.span("x", a=1)
        assert sp is NOOP_SPAN
        assert sp.set_attribute("k", 1).set_status("error") is NOOP_SPAN

    def test_disabled_span_path_takes_zero_lock_acquisitions(self):
        t = Tracer(enabled=False)
        t._lock = _CountingLock()
        for _ in range(100):
            with t.span("reconcile", job="default/j1") as sp:
                sp.set_attribute("pods", 3)
        assert t._lock.acquisitions == 0
        assert current_span() is None  # contextvar untouched too

    def test_tracer_from_env(self):
        t, parent = tracer_from_env({})
        assert not t.enabled and parent == ""
        t, parent = tracer_from_env(
            {constants.TRACE_CONTEXT_ENV: "aaaa:bbbb"})
        assert t.enabled and parent == "aaaa:bbbb"
        assert t.service == "trainingjob-workload"
        with t.span("train.run", parent=parent):
            pass
        tr = t.traces()[0]
        assert tr["trace_id"] == "aaaa"
        assert tr["spans"][0]["parent_id"] == "bbbb"


# -- structured logging ------------------------------------------------------

def _capture(formatter):
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(formatter)
    logger = logging.getLogger("trainingjob.test_obs")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    logger.handlers = [handler]
    return logger, buf


class TestStructuredLogs:
    def test_json_lines_carry_bound_fields_and_live_trace_id(self):
        base, buf = _capture(JsonFormatter())
        log = get_logger("trainingjob.test_obs", job="default/j1",
                         rtype="trainer")
        t = Tracer()
        with t.span("sync_job") as sp:
            log.info("reconciled %d pods", 3)
        rec = json.loads(buf.getvalue())
        assert rec["message"] == "reconciled 3 pods"
        assert rec["job"] == "default/j1" and rec["rtype"] == "trainer"
        assert rec["trace_id"] == sp.trace_id
        assert rec["span_id"] == sp.span_id
        assert rec["level"] == "INFO"

    def test_no_span_means_no_trace_fields(self):
        base, buf = _capture(JsonFormatter())
        get_logger("trainingjob.test_obs", job="default/j2").info("hi")
        rec = json.loads(buf.getvalue())
        assert rec["job"] == "default/j2" and "trace_id" not in rec

    def test_bind_merges_without_mutating_parent(self):
        log = get_logger("trainingjob.test_obs", job="default/j1")
        child = log.bind(rtype="worker")
        assert child.extra == {"job": "default/j1", "rtype": "worker"}
        assert log.extra == {"job": "default/j1"}

    def test_text_formatter_appends_context_suffix(self):
        base, buf = _capture(ContextTextFormatter("%(message)s"))
        get_logger("trainingjob.test_obs", job="default/j1").info("hello")
        assert buf.getvalue().strip() == "hello [job=default/j1]"
        buf.truncate(0), buf.seek(0)
        base.info("plain")
        assert buf.getvalue().strip() == "plain"  # no fields, no suffix

    def test_configure_logging_installs_removable_handler(self):
        root = logging.getLogger()
        handler = configure_logging(json_output=True, stream=io.StringIO())
        try:
            assert handler in root.handlers
            assert isinstance(handler.formatter, JsonFormatter)
        finally:
            root.removeHandler(handler)


# -- goodput accounting ------------------------------------------------------

class TestGoodputTracker:
    def test_ledger_and_final_ratio(self):
        reg = MetricsRegistry()
        g = GoodputTracker(metrics=reg)
        k = "default/j1"
        g.on_running(k, now=100.0, start_time=90.0)     # created at 90
        g.on_interruption(k, "all", now=110.0)          # 10 s productive
        g.on_running(k, now=115.0)                      # 5 s downtime
        g.on_complete(k, now=120.0)                     # + 5 s productive
        snap = reg.snapshot()
        assert snap['trainingjob_goodput_ratio{job="default/j1"}'] == \
            pytest.approx(15.0 / 30.0)
        assert snap["trainingjob_time_to_first_step_seconds_count"] == 1
        assert snap["trainingjob_time_to_first_step_seconds_sum"] == \
            pytest.approx(10.0)
        assert snap['trainingjob_restart_downtime_seconds{scope="all"}_count'] == 1
        assert snap['trainingjob_restart_downtime_seconds{scope="all"}_sum'] == \
            pytest.approx(5.0)

    def test_complete_is_idempotent_and_forget_drops_gauge(self):
        reg = MetricsRegistry()
        g = GoodputTracker(metrics=reg)
        g.on_running("k", now=10.0)
        g.on_complete("k", now=20.0)
        g.on_complete("k", now=99.0)  # revisited terminal branch: no-op
        assert reg.snapshot()['trainingjob_goodput_ratio{job="k"}'] == 1.0
        g.on_running("k", now=30.0)   # post-completion transitions ignored
        assert g.ratio("k") == 1.0
        g.forget("k")
        assert 'trainingjob_goodput_ratio{job="k"}' not in reg.snapshot()
        assert g.ratio("k") is None

    def test_repeated_running_syncs_do_not_double_count(self):
        reg = MetricsRegistry()
        g = GoodputTracker(metrics=reg)
        g.on_running("k", now=10.0)
        g.on_running("k", now=12.0)   # resync while already Running
        g.on_complete("k", now=20.0)
        assert reg.snapshot()['trainingjob_goodput_ratio{job="k"}'] == 1.0
        assert reg.snapshot()["trainingjob_time_to_first_step_seconds_count"] == 1

    def test_live_ratio_between_transitions(self):
        g = GoodputTracker(metrics=MetricsRegistry())
        g.on_running("k")
        ratio = g.ratio("k")
        assert ratio is not None and 0.0 <= ratio <= 1.0


class TestHistogramQuantile:
    def test_empty_histogram_returns_zero(self):
        h = _Histogram((1.0, 5.0))
        assert h.quantile(0.5) == 0.0

    def test_nonpositive_q_returns_zero_not_first_bucket(self):
        h = _Histogram((1.0, 5.0))
        h.observe(4.0)
        assert h.quantile(0.0) == 0.0
        assert h.quantile(-1.0) == 0.0
        # The pre-fix bias: q=0 used to answer 1.0 (first bucket's bound)
        # even though all mass sits in the second bucket.
        assert h.quantile(0.5) == 5.0

    def test_q_above_one_clamps(self):
        h = _Histogram((1.0, 5.0))
        h.observe(0.5)
        assert h.quantile(7.0) == h.quantile(1.0) == 1.0

    def test_overflow_bucket_answers_vmax(self):
        h = _Histogram((1.0,))
        h.observe(30.0)
        assert h.quantile(0.99) == 30.0


# -- debug HTTP endpoints ----------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestDebugEndpoints:
    def test_traces_events_and_readyz(self):
        from trainingjob_operator_tpu.core.objects import Event

        reg = MetricsRegistry()
        tracer = Tracer()
        with tracer.span("sync_job", job="default/j1"):
            with tracer.span("reconcile_pods"):
                pass
        events = [
            Event(involved_namespace="default", involved_name="j1",
                  reason="TrainingJobRunning", message="m1", timestamp=2.0),
            Event(involved_namespace="default", involved_name="other",
                  reason="TrainingJobPending", message="m2", timestamp=1.0),
        ]
        ready = {"ok": False}
        server = serve_metrics(0, reg, tracer=tracer,
                               events_fn=lambda: events,
                               ready_fn=lambda: ready["ok"])
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(port, "/readyz")
            assert exc.value.code == 503
            ready["ok"] = True
            assert _get(port, "/readyz") == (200, "ok\n")

            status, body = _get(port, "/debug/traces")
            doc = json.loads(body)
            assert status == 200 and doc["count"] == 1
            assert doc["traces"][0]["root"] == "sync_job"

            _, body = _get(port, "/debug/traces?format=chrome")
            chrome = json.loads(body)
            assert {ev["name"] for ev in chrome["traceEvents"]} == {
                "sync_job", "reconcile_pods"}
            for ev in chrome["traceEvents"]:
                assert {"ph", "ts", "pid", "tid", "name"} <= set(ev)

            _, body = _get(port, "/debug/events?job=default/j1")
            doc = json.loads(body)
            assert doc["count"] == 1
            assert doc["events"][0]["reason"] == "TrainingJobRunning"
            _, body = _get(port, "/debug/events")
            doc = json.loads(body)
            # Unfiltered: all events, oldest first.
            assert [e["message"] for e in doc["events"]] == ["m2", "m1"]
        finally:
            server.shutdown()

    def test_debug_endpoints_404_without_providers(self):
        server = serve_metrics(0, MetricsRegistry())
        port = server.server_address[1]
        try:
            for path in ("/debug/traces", "/debug/events"):
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _get(port, path)
                assert exc.value.code == 404
            # No ready_fn: always ready.
            assert _get(port, "/readyz") == (200, "ok\n")
        finally:
            server.shutdown()


# -- e2e: reconcile traces (localproc) and goodput (sim) ---------------------

from trainingjob_operator_tpu.api.types import (  # noqa: E402
    ReplicaSpec,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset  # noqa: E402
from trainingjob_operator_tpu.cmd.options import OperatorOptions  # noqa: E402
from trainingjob_operator_tpu.controller.controller import (  # noqa: E402
    TrainingJobController,
)
from trainingjob_operator_tpu.core.objects import (  # noqa: E402
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)


def _phase(cs, name):
    return cs.trainingjobs.get("default", name).status.phase


class TestReconcileTraceE2E:
    @pytest.fixture
    def cluster(self, tmp_path):
        from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime

        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        rt = LocalProcRuntime(cs, nodes=2, log_dir=str(tmp_path),
                              termination_grace=0.5)
        rt.start()
        tc.run(workers=2)
        yield cs, tc, rt
        tc.stop()
        rt.stop()

    def test_reconcile_trace_root_has_children_and_env_propagates(
            self, cluster, tmp_path):
        cs, tc, rt = cluster
        TRACER.clear()
        out = tmp_path / "ctx.txt"
        code = (
            "import os\n"
            f"open({str(out)!r}, 'w').write("
            f"os.environ.get({constants.TRACE_CONTEXT_ENV!r}, ''))\n")
        job = TPUTrainingJob(
            metadata=ObjectMeta(name="traced", namespace="default"))
        job.spec.replica_specs["worker"] = ReplicaSpec(
            replicas=1,
            template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(name="aitj-w",
                          command=[sys.executable, "-u", "-c", code],
                          ports=[ContainerPort(name="aitj-7741",
                                               container_port=7741)])])))
        cs.trainingjobs.create(job)
        assert wait_for(
            lambda: _phase(cs, "traced") == TrainingJobPhase.SUCCEEDED), \
            _phase(cs, "traced")

        # The acceptance shape: some reconcile of this job produced a root
        # sync_job span with >= 3 children.
        best = None
        for tr in TRACER.traces():
            roots = [s for s in tr["spans"]
                     if s["parent_id"] is None and s["name"] == "sync_job"]
            if not roots:
                continue
            root = roots[0]
            if root["attributes"].get("job") != "default/traced":
                continue
            children = [s for s in tr["spans"]
                        if s["parent_id"] == root["span_id"]]
            if best is None or len(children) > len(best[1]):
                best = (tr, children)
        assert best is not None, "no sync_job trace recorded"
        tr, children = best
        names = {s["name"] for s in children}
        assert len(children) >= 3, names
        assert {"check_expectations", "reconcile_pods",
                "update_status"} <= names, names
        # The pod-create reconcile nests create_pod under reconcile_pods and
        # localproc.launch adopts the env context: same trace end to end.
        all_names = {s["name"] for tr2 in TRACER.traces()
                     for s in tr2["spans"]}
        assert "create_pod" in all_names
        assert "localproc.launch" in all_names

        # Cross-process propagation: the subprocess saw "trace_id:span_id".
        ctx = out.read_text()
        assert ctx and ":" in ctx
        tid, _, sid = ctx.partition(":")
        assert len(tid) == 16 and len(sid) == 16
        known_traces = {tr2["trace_id"] for tr2 in TRACER.traces()}
        assert tid in known_traces

    def test_chrome_export_of_live_reconcile_ring_validates(self, cluster):
        cs, tc, rt = cluster
        TRACER.clear()
        code = "import time; time.sleep(0.1)"
        job = TPUTrainingJob(
            metadata=ObjectMeta(name="chrome", namespace="default"))
        job.spec.replica_specs["worker"] = ReplicaSpec(
            replicas=1,
            template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(name="aitj-w",
                          command=[sys.executable, "-u", "-c", code],
                          ports=[ContainerPort(name="aitj-7742",
                                               container_port=7742)])])))
        cs.trainingjobs.create(job)
        assert wait_for(
            lambda: _phase(cs, "chrome") == TrainingJobPhase.SUCCEEDED)
        doc = json.loads(TRACER.export_chrome())
        assert doc["traceEvents"], "reconcile produced no events"
        for ev in doc["traceEvents"]:
            assert {"ph", "ts", "pid", "tid", "name"} <= set(ev)
            assert ev["ph"] == "X"


class TestGoodputE2E:
    @pytest.fixture
    def cluster(self):
        from trainingjob_operator_tpu.runtime.sim import SimRuntime

        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        sim = SimRuntime(cs)
        sim.start()
        tc.run(workers=2)
        yield cs, tc, sim
        tc.stop()
        sim.stop()

    def test_completed_sim_job_publishes_goodput_ratio(self, cluster):
        from trainingjob_operator_tpu.runtime.sim import (
            RUN_SECONDS_ANNOTATION,
        )

        cs, tc, sim = cluster
        sim.add_node("n0")
        key = "default/goodjob"
        GOODPUT.forget(key)  # other suites may have used the key
        job = TPUTrainingJob(
            metadata=ObjectMeta(name="goodjob", namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=1,
            template=PodTemplateSpec(
                metadata=ObjectMeta(
                    annotations={RUN_SECONDS_ANNOTATION: "0.5"}),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7743",
                                                   container_port=7743)])])))
        cs.trainingjobs.create(job)
        try:
            assert wait_for(
                lambda: _phase(cs, "goodjob") == TrainingJobPhase.RUNNING, 10)
            assert wait_for(
                lambda: _phase(cs, "goodjob") == TrainingJobPhase.SUCCEEDED,
                10)
            assert wait_for(
                lambda: GOODPUT.ratio(key) is not None, 5)
            # The acceptance bound: ratio in (0, 1] for a job that ran.
            ratio = GOODPUT.ratio(key)
            assert 0.0 < ratio <= 1.0, ratio
            # And it is scrapeable from the Prometheus text endpoint.
            line = next(
                (ln for ln in METRICS.render_prometheus().splitlines()
                 if ln.startswith(
                     'trainingjob_goodput_ratio{job="default/goodjob"}')),
                None)
            assert line is not None
            assert 0.0 < float(line.split()[-1]) <= 1.0
        finally:
            GOODPUT.forget(key)
