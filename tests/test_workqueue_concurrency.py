"""Workqueue semantics under concurrency: the guarantees that make
``--thread-num N`` safe (client-go parity, SURVEY.md §5.2).

- A key is never processed by two workers at once, however hard the queue
  is hammered with adds/delayed adds from outside.
- A re-add landing while the key is being processed is not lost: it is
  redelivered after done().
- Rate-limited requeues back off exponentially per key and reset on forget.
- add_after coalesces duplicate delayed keys to the earliest deadline and
  delivers exactly once; shut_down cancels pending delayed items.
"""

import collections
import random
import threading
import time

from conftest import wait_for

from trainingjob_operator_tpu.client.workqueue import RateLimitingQueue


class TestHammer:
    def test_no_key_processed_concurrently(self):
        """6 workers, 8 keys, 3000 mixed adds: the per-key concurrency
        counter must never reach 2."""
        q = RateLimitingQueue("hammer")
        keys = [f"k{i}" for i in range(8)]
        lock = threading.Lock()
        active = collections.Counter()
        processed = collections.Counter()
        violations = []
        stop = threading.Event()

        def worker():
            while True:
                item, shutdown = q.get(timeout=0.2)
                if shutdown:
                    return
                if item is None:
                    if stop.is_set():
                        return
                    continue
                with lock:
                    active[item] += 1
                    if active[item] > 1:
                        violations.append(item)
                time.sleep(0.001)
                with lock:
                    active[item] -= 1
                    processed[item] += 1
                q.done(item)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in workers:
            t.start()

        rng = random.Random(0)
        for _ in range(3000):
            key = rng.choice(keys)
            if rng.random() < 0.3:
                q.add_after(key, rng.uniform(0.0, 0.005))
            else:
                q.add(key)

        # Drain: every delayed item delivered, ready queue empty, nothing
        # mid-processing.
        assert wait_for(
            lambda: q.waiting() == 0 and len(q) == 0 and not q._processing,
            timeout=30.0)
        stop.set()
        for t in workers:
            t.join(timeout=5.0)
        q.shut_down()

        assert violations == []
        assert all(processed[k] >= 1 for k in keys), processed
        # Dedup means far fewer deliveries than adds.
        assert sum(processed.values()) <= 3000

    def test_readd_during_processing_redelivered(self):
        q = RateLimitingQueue("dirty")
        q.add("k")
        item, _ = q.get(timeout=1.0)
        assert item == "k"
        # Re-adds while processing mark dirty (and dedup among themselves).
        q.add("k")
        q.add("k")
        assert len(q) == 0   # not queued: "k" is being processed
        q.done("k")
        item, _ = q.get(timeout=1.0)
        assert item == "k"   # redelivered exactly once
        q.done("k")
        item, _ = q.get(timeout=0.05)
        assert item is None
        q.shut_down()


class TestRateLimiting:
    def test_backoff_is_per_key_and_forgettable(self):
        q = RateLimitingQueue("backoff", base_delay=0.05, max_delay=1.0)
        # Third failure for "a" -> 0.2 s; first for "b" -> 0.05 s.
        q.add_rate_limited("a")
        item, _ = q.get(timeout=2.0)
        assert item == "a"
        q.done("a")
        q.add_rate_limited("a")
        q.add_rate_limited("a")
        q.add_rate_limited("b")
        assert q.num_requeues("a") == 3
        assert q.num_requeues("b") == 1
        assert q.retries_total == 4
        first, _ = q.get(timeout=2.0)
        second, _ = q.get(timeout=2.0)
        # b's shorter backoff delivers it first despite being added last;
        # the pump pops in deadline order even when both are overdue.
        assert [first, second] == ["b", "a"]
        q.done("b")
        q.done("a")
        q.forget("a")
        assert q.num_requeues("a") == 0
        q.shut_down()


class TestDelayCoalescing:
    def test_coalesces_to_earliest_deadline(self):
        q = RateLimitingQueue("coalesce")
        q.add_after("k", 30.0)
        q.add_after("k", 0.05)       # earlier: supersedes the 30 s entry
        assert q.coalesced_total == 1
        assert q.waiting() == 1
        item, _ = q.get(timeout=5.0)
        assert item == "k"
        q.done("k")
        assert q.waiting() == 0
        # The superseded 30 s heap entry must not fire a second delivery.
        item, _ = q.get(timeout=0.1)
        assert item is None

        # Later-than-pending deadlines are absorbed outright.
        q.add_after("k", 0.05)
        q.add_after("k", 30.0)
        assert q.coalesced_total == 2
        assert q.waiting() == 1
        item, _ = q.get(timeout=5.0)
        assert item == "k"
        q.done("k")
        q.shut_down()

    def test_shutdown_cancels_pending_delays(self):
        q = RateLimitingQueue("cancel")
        q.add_after("k", 0.2)
        assert q.waiting() == 1
        q.shut_down()
        assert q.waiting() == 0
        item, shutdown = q.get(timeout=0.5)
        assert shutdown and item is None
        # Nothing fires later either.
        time.sleep(0.3)
        assert len(q) == 0

    def test_add_after_zero_is_immediate(self):
        q = RateLimitingQueue("zero")
        q.add_after("k", 0.0)
        item, _ = q.get(timeout=1.0)
        assert item == "k"
        q.done("k")
        q.shut_down()


class TestScaleCounters:
    def test_depth_high_water_and_queue_wait(self):
        q = RateLimitingQueue("counters")
        for i in range(5):
            q.add(f"i{i}")
        assert q.depth_high_water == 5
        item, _ = q.get(timeout=1.0)
        wait = q.pop_wait(item)
        assert wait is not None and wait >= 0.0
        assert q.pop_wait(item) is None    # consumed
        q.done(item)
        # done() without a re-add leaves no residue for the item.
        assert q.num_requeues(item) == 0
        q.shut_down()
