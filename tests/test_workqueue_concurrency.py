"""Workqueue semantics under concurrency: the guarantees that make
``--thread-num N`` safe (client-go parity, SURVEY.md §5.2).

- A key is never processed by two workers at once, however hard the queue
  is hammered with adds/delayed adds from outside -- including when the
  handler fails and requeues rate-limited (the chaos regime).
- A re-add landing while the key is being processed is not lost: it is
  redelivered after done().
- Rate-limited requeues back off exponentially per key and reset on forget.
- add_after coalesces duplicate delayed keys to the earliest deadline and
  delivers exactly once; shut_down cancels pending delayed items.
- Past ``quarantine_after`` consecutive failures a key parks at the flat
  quarantine delay; the transition is reported exactly once per episode
  and forget (one success) releases it (docs/CHAOS.md).
"""

import collections
import random
import threading
import time

from conftest import wait_for

from trainingjob_operator_tpu.client.workqueue import RateLimitingQueue


class TestHammer:
    def test_no_key_processed_concurrently(self):
        """6 workers, 8 keys, 3000 mixed adds: the per-key concurrency
        counter must never reach 2."""
        q = RateLimitingQueue("hammer")
        keys = [f"k{i}" for i in range(8)]
        lock = threading.Lock()
        active = collections.Counter()
        processed = collections.Counter()
        violations = []
        stop = threading.Event()

        def worker():
            while True:
                item, shutdown = q.get(timeout=0.2)
                if shutdown:
                    return
                if item is None:
                    if stop.is_set():
                        return
                    continue
                with lock:
                    active[item] += 1
                    if active[item] > 1:
                        violations.append(item)
                time.sleep(0.001)
                with lock:
                    active[item] -= 1
                    processed[item] += 1
                q.done(item)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(6)]
        for t in workers:
            t.start()

        rng = random.Random(0)
        for _ in range(3000):
            key = rng.choice(keys)
            if rng.random() < 0.3:
                q.add_after(key, rng.uniform(0.0, 0.005))
            else:
                q.add(key)

        # Drain: every delayed item delivered, ready queue empty, nothing
        # mid-processing.
        assert wait_for(
            lambda: q.waiting() == 0 and len(q) == 0 and not q._processing,
            timeout=30.0)
        stop.set()
        for t in workers:
            t.join(timeout=5.0)
        q.shut_down()

        assert violations == []
        assert all(processed[k] >= 1 for k in keys), processed
        # Dedup means far fewer deliveries than adds.
        assert sum(processed.values()) <= 3000

    def test_readd_during_processing_redelivered(self):
        q = RateLimitingQueue("dirty")
        q.add("k")
        item, _ = q.get(timeout=1.0)
        assert item == "k"
        # Re-adds while processing mark dirty (and dedup among themselves).
        q.add("k")
        q.add("k")
        assert len(q) == 0   # not queued: "k" is being processed
        q.done("k")
        item, _ = q.get(timeout=1.0)
        assert item == "k"   # redelivered exactly once
        q.done("k")
        item, _ = q.get(timeout=0.05)
        assert item is None
        q.shut_down()


class TestRateLimiting:
    def test_backoff_is_per_key_and_forgettable(self):
        q = RateLimitingQueue("backoff", base_delay=0.05, max_delay=1.0)
        # Third failure for "a" -> 0.2 s; first for "b" -> 0.05 s.
        q.add_rate_limited("a")
        item, _ = q.get(timeout=2.0)
        assert item == "a"
        q.done("a")
        q.add_rate_limited("a")
        q.add_rate_limited("a")
        q.add_rate_limited("b")
        assert q.num_requeues("a") == 3
        assert q.num_requeues("b") == 1
        assert q.retries_total == 4
        first, _ = q.get(timeout=2.0)
        second, _ = q.get(timeout=2.0)
        # b's shorter backoff delivers it first despite being added last;
        # the pump pops in deadline order even when both are overdue.
        assert [first, second] == ["b", "a"]
        q.done("b")
        q.done("a")
        q.forget("a")
        assert q.num_requeues("a") == 0
        q.shut_down()


class TestFailureStorm:
    def test_single_writer_per_key_when_handlers_fail(self):
        """4 workers, 6 keys, every sync "fails" for a while: rate-limited
        requeues must preserve the single-writer-per-key guarantee and
        every key must eventually be processed again after its failures."""
        q = RateLimitingQueue("storm", base_delay=0.001, max_delay=0.01)
        keys = [f"k{i}" for i in range(6)]
        lock = threading.Lock()
        active = collections.Counter()
        failures = collections.Counter()
        recovered = set()
        violations = []

        def worker():
            while True:
                item, shutdown = q.get(timeout=0.2)
                if shutdown:
                    return
                if item is None:
                    continue
                with lock:
                    active[item] += 1
                    if active[item] > 1:
                        violations.append(item)
                time.sleep(0.001)
                with lock:
                    active[item] -= 1
                    failures[item] += 1
                    failed = failures[item] <= 5
                if failed:
                    q.add_rate_limited(item)
                else:
                    q.forget(item)
                    with lock:
                        recovered.add(item)
                q.done(item)

        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(4)]
        for t in workers:
            t.start()
        for k in keys:
            q.add(k)
        assert wait_for(lambda: len(recovered) == len(keys), timeout=30.0)
        q.shut_down()
        for t in workers:
            t.join(timeout=5.0)
        assert violations == []
        assert all(q.num_requeues(k) == 0 for k in keys)

    def test_backoff_ordering_is_per_key(self):
        """A deep-failure key's long delay must not hold back a fresh
        key's short one: deliveries pop in per-key deadline order."""
        q = RateLimitingQueue("ordering", base_delay=0.02, max_delay=5.0)
        # Drive "deep" through failed cycles (back-to-back re-adds would
        # coalesce to the earliest deadline): 0.02 s, 0.04 s, then a
        # pending 0.08 s entry.
        q.add_rate_limited("deep")
        for _ in range(2):
            item, _ = q.get(timeout=2.0)
            assert item == "deep"
            q.done("deep")
            q.add_rate_limited("deep")
        q.add_rate_limited("fresh")      # first failure: 0.02 s
        first, _ = q.get(timeout=2.0)
        assert first == "fresh"
        q.done("fresh")
        second, _ = q.get(timeout=2.0)
        assert second == "deep"
        q.done("deep")
        q.shut_down()


class TestQuarantine:
    def test_entry_is_reported_once_and_delay_flattens(self):
        q = RateLimitingQueue("quarantine", base_delay=0.001,
                              max_delay=60.0, quarantine_after=3,
                              quarantine_delay=0.05)
        assert q.add_rate_limited("k") is False
        assert q.add_rate_limited("k") is False
        # Third consecutive failure crosses the threshold: reported once.
        assert q.add_rate_limited("k") is True
        assert q.is_quarantined("k")
        assert q.num_quarantined() == 1
        assert q.quarantined_total == 1
        # Further failures stay parked at the flat delay, silently.
        assert q.add_rate_limited("k") is False
        assert q.num_quarantined() == 1
        # Delay is the flat quarantine cadence, not the exponential ladder
        # (failures=5 on base 0.001 would be ~0.016 s; quarantine holds it
        # at 0.05 s -- and far below the 60 s max_delay ceiling).
        t0 = time.monotonic()
        for _ in range(5):
            item, _ = q.get(timeout=2.0)
            assert item == "k"
            q.done("k")
            if q.is_quarantined("k"):
                q.add_rate_limited("k")
            else:
                break
        assert time.monotonic() - t0 >= 0.05
        q.shut_down()

    def test_forget_releases_quarantine(self):
        q = RateLimitingQueue("release", base_delay=0.001,
                              quarantine_after=2, quarantine_delay=0.02)
        q.add_rate_limited("k")
        assert q.add_rate_limited("k") is True
        item, _ = q.get(timeout=2.0)
        assert item == "k"
        q.forget("k")                    # the sync succeeded
        q.done("k")
        assert not q.is_quarantined("k")
        assert q.num_quarantined() == 0
        assert q.num_requeues("k") == 0
        # A fresh failure episode starts from the exponential ladder and
        # must cross the threshold again to re-quarantine.
        assert q.add_rate_limited("k") is False
        assert q.add_rate_limited("k") is True
        assert q.quarantined_total == 2
        q.shut_down()

    def test_zero_disables(self):
        q = RateLimitingQueue("off", base_delay=0.001)
        for _ in range(10):
            assert q.add_rate_limited("k") is False
        assert q.num_quarantined() == 0
        assert not q.is_quarantined("k")
        q.shut_down()


class TestDelayCoalescing:
    def test_coalesces_to_earliest_deadline(self):
        q = RateLimitingQueue("coalesce")
        q.add_after("k", 30.0)
        q.add_after("k", 0.05)       # earlier: supersedes the 30 s entry
        assert q.coalesced_total == 1
        assert q.waiting() == 1
        item, _ = q.get(timeout=5.0)
        assert item == "k"
        q.done("k")
        assert q.waiting() == 0
        # The superseded 30 s heap entry must not fire a second delivery.
        item, _ = q.get(timeout=0.1)
        assert item is None

        # Later-than-pending deadlines are absorbed outright.
        q.add_after("k", 0.05)
        q.add_after("k", 30.0)
        assert q.coalesced_total == 2
        assert q.waiting() == 1
        item, _ = q.get(timeout=5.0)
        assert item == "k"
        q.done("k")
        q.shut_down()

    def test_shutdown_cancels_pending_delays(self):
        q = RateLimitingQueue("cancel")
        q.add_after("k", 0.2)
        assert q.waiting() == 1
        q.shut_down()
        assert q.waiting() == 0
        item, shutdown = q.get(timeout=0.5)
        assert shutdown and item is None
        # Nothing fires later either.
        time.sleep(0.3)
        assert len(q) == 0

    def test_add_after_zero_is_immediate(self):
        q = RateLimitingQueue("zero")
        q.add_after("k", 0.0)
        item, _ = q.get(timeout=1.0)
        assert item == "k"
        q.done("k")
        q.shut_down()


class TestScaleCounters:
    def test_depth_high_water_and_queue_wait(self):
        q = RateLimitingQueue("counters")
        for i in range(5):
            q.add(f"i{i}")
        assert q.depth_high_water == 5
        item, _ = q.get(timeout=1.0)
        wait = q.pop_wait(item)
        assert wait is not None and wait >= 0.0
        assert q.pop_wait(item) is None    # consumed
        q.done(item)
        # done() without a re-add leaves no residue for the item.
        assert q.num_requeues(item) == 0
        q.shut_down()
