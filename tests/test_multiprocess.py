"""True multi-process JAX: 2 CPU processes, one coordinator, shared corpus.

The only test tier that exercises ``jax.process_count() > 1`` for real:
``globalize_batch``'s ``make_array_from_process_local_data`` path, each
process materializing its own row block of the global batch
(workloads/llama_elastic.py ``batch_at``), and the jax.distributed
bootstrap from the operator-injected env (workloads/rendezvous.py).  The
virtual 8-device mesh used everywhere else is still ONE process and never
runs this code.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _jax_version_info():
    import jax

    return jax.__version_info__


@pytest.mark.skipif(
    _jax_version_info() < (0, 5, 0),
    reason="CPU cross-process collectives (gloo) need jax>=0.5; on older "
           "runtimes device_put into a multi-process sharding raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'")
def test_two_process_data_parallel_train(tmp_path):
    from trainingjob_operator_tpu.data import write_tokens

    corpus = str(tmp_path / "c.tokens")
    rng = np.random.default_rng(3)
    write_tokens(corpus, rng.integers(0, 256, size=4000), vocab_size=256)

    port = _free_port()
    env_common = {
        **os.environ,
        # One device per process: the point is process_count == 2, not the
        # virtual multi-device mesh (conftest's 8-device XLA_FLAGS would
        # otherwise leak in and give 16 global devices).
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "JAX_PLATFORMS": "cpu",
        "TRAININGJOB_JAX_PLATFORM": "cpu",
        "TRAININGJOB_NUM_PROCESSES": "2",
        "TRAININGJOB_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "TRAININGJOB_ELASTIC_REPLICAS": "2",
        "LLAMA_DATA": corpus,
        "LLAMA_BATCH": "4",
        "LLAMA_STEPS": "2",
        "LLAMA_SEQ": "16",
        "LLAMA_CKPT_EVERY": "100",
        "PYTHONPATH": REPO,
    }
    procs = []
    outs = []
    try:
        for pid in range(2):
            env = {**env_common, "TRAININGJOB_PROCESS_ID": str(pid)}
            procs.append(subprocess.Popen(
                [sys.executable, "-m",
                 "trainingjob_operator_tpu.workloads.llama_elastic"],
                env=env, cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        # A barrier deadlock times out ONE communicate; without this both
        # children (one wedged in the coordinator barrier) would outlive
        # the test holding the port.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {pid} failed:\n{out[-2000:]}"
    # Both ranks computed the SAME global loss (one global batch, two
    # process-local row blocks assembled into one sharded array).
    losses = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("step 2/2")]
        assert line, out[-2000:]
        losses.append(float(line[0].split("loss")[1].strip()))
    assert losses[0] == pytest.approx(losses[1], abs=1e-5)
    assert np.isfinite(losses[0])


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
