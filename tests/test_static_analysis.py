"""tools/analyze: one positive (fires on seeded-bad code) and one negative
(quiet on good code) fixture per check, the baseline/waiver machinery, output
formats, and the tier-1 gate -- zero non-baselined findings on the real tree.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.analyze import runner
from tools.analyze.findings import Finding, fingerprint_all
from tools.analyze.runner import (
    apply_baseline,
    format_findings,
    load_baseline,
    run_checks,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "trainingjob_operator_tpu"


def analyze(tmp_path, rel, source, only=None):
    """Write ``source`` at ``rel`` under tmp_path and run the checks."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_checks([str(path)], root=str(tmp_path), only=only)


def analyze_tree(tmp_path, files, only=None):
    """Write a {rel: source} tree under tmp_path and analyze the whole dir.

    Whole-program fixtures go through here: running over the directory (not
    one file) makes ``ProjectContext.covers_package`` hold for the fixture's
    miniature ``trainingjob_operator_tpu/`` package, so the absence-based
    passes (TJA011/TJA012/TJA014) actually assert."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    return run_checks([str(tmp_path)], root=str(tmp_path), only=only)


def ids(findings):
    return sorted({f.check_id for f in findings})


# -- TJA001 py-compat --------------------------------------------------------

class TestPyCompat:
    def test_fires_on_reintroduced_metrics_bug(self, tmp_path):
        """Re-introduce the exact seed bug: utils/metrics.py:147's escaped
        le-label inside an f-string expression."""
        src = open(os.path.join(REPO_ROOT, PKG, "utils", "metrics.py")).read()
        good = (
            '                # Escaped label hoisted out of the f-string: a backslash\n'
            '                # inside an f-string expression is a SyntaxError before 3.12.\n'
            "                le_label = f'le=\"{ub}\"'\n"
            '                lines.append(f"{base}_bucket{lbl(le_label)} {cum}")\n'
        )
        bad = (
            '                lines.append(f\'{base}_bucket{lbl(f"le=\\"{ub}\\"")} {cum}\')\n'
        )
        assert good in src, "metrics.py render loop changed; update fixture"
        broken = src.replace(good, bad)
        findings = analyze(tmp_path, "utils/metrics.py", broken,
                           only=["py-compat"])
        assert ids(findings) == ["TJA001"]
        # On a 3.10/3.11 interpreter the parse gate reports the SyntaxError;
        # the token scan must give the same verdict on 3.12+.
        assert any("3.10" in f.message or "f-string" in f.message
                   for f in findings)

    def test_fires_on_plain_syntax_error(self, tmp_path):
        findings = analyze(tmp_path, "m.py", "def broken(:\n    pass\n",
                           only=["py-compat"])
        assert ids(findings) == ["TJA001"]

    def test_quiet_on_hoisted_fix_and_current_tree_file(self, tmp_path):
        fixed = '''
        def render(lbl, ub, cum):
            le_label = f'le="{ub}"'
            return f"bucket{lbl(le_label)} {cum}"
        '''
        assert analyze(tmp_path, "m.py", fixed, only=["py-compat"]) == []
        real = open(os.path.join(REPO_ROOT, PKG, "utils", "metrics.py")).read()
        assert analyze(tmp_path, "utils/metrics.py", real,
                       only=["py-compat"]) == []

    def test_backslash_at_depth_zero_is_fine(self, tmp_path):
        src = 'X = f"a\\n{1 + 2}\\t"\n'
        assert analyze(tmp_path, "m.py", src, only=["py-compat"]) == []


# -- TJA002 lock-discipline --------------------------------------------------

BAD_LOCK = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.count = 0

        def put(self, k, v):
            with self._lock:
                self._items[k] = v
                self.count += 1

        def racy_clear(self):
            self._items.clear()
            self.count = 0
"""

GOOD_LOCK = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def put(self, k, v):
            with self._lock:
                self._items[k] = v

        def clear(self):
            with self._lock:
                self._items.clear()

        def _drop_locked(self, k):
            # caller-holds-lock helper convention: exempt
            self._items.pop(k, None)
"""


class TestLockDiscipline:
    def test_fires_on_unguarded_mutation(self, tmp_path):
        findings = analyze(tmp_path, "m.py", BAD_LOCK,
                           only=["lock-discipline"])
        assert ids(findings) == ["TJA002"]
        assert {f.line for f in findings} == {16, 17}
        assert any("racy_clear" in f.message and "_items" in f.message
                   for f in findings)

    def test_quiet_on_disciplined_class(self, tmp_path):
        assert analyze(tmp_path, "m.py", GOOD_LOCK,
                       only=["lock-discipline"]) == []

    def test_init_is_exempt_and_lockless_class_ignored(self, tmp_path):
        src = """
        class Plain:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)
        """
        assert analyze(tmp_path, "m.py", src, only=["lock-discipline"]) == []

    def test_condition_counts_as_lock(self, tmp_path):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition()
                self._queue = []

            def add(self, x):
                with self._cond:
                    self._queue.append(x)

            def racy_drain(self):
                self._queue.clear()
        """
        findings = analyze(tmp_path, "m.py", src, only=["lock-discipline"])
        assert ids(findings) == ["TJA002"]

    def test_quiet_on_real_workqueue_and_expectations(self, tmp_path):
        for rel in ("client/workqueue.py", "client/expectations.py",
                    "client/informers.py", "utils/metrics.py"):
            src = open(os.path.join(REPO_ROOT, PKG, *rel.split("/"))).read()
            assert analyze(tmp_path, rel, src,
                           only=["lock-discipline"]) == [], rel


# -- TJA003 reconcile-purity -------------------------------------------------

BAD_PURITY = """
    import time
    import requests

    def sync(key, queue, thread):
        time.sleep(1.0)
        requests.get("http://apiserver/jobs")
        queue.get()
        thread.join()
"""


class TestReconcilePurity:
    def test_fires_inside_controller_dir(self, tmp_path):
        findings = analyze(tmp_path, "controller/sync.py", BAD_PURITY,
                           only=["reconcile-purity"])
        assert ids(findings) == ["TJA003"]
        assert len(findings) == 4

    def test_out_of_scope_dir_is_quiet(self, tmp_path):
        assert analyze(tmp_path, "runtime/sync.py", BAD_PURITY,
                       only=["reconcile-purity"]) == []

    def test_bounded_waits_and_local_names_are_quiet(self, tmp_path):
        src = """
        def sync(key, queue, stop):
            item, _ = queue.get(timeout=0.5)
            stop.wait(1.0)
            # a k8s resources dict named "requests" is not the module
            requests = {}
            requests.setdefault("cpu", "1")
        """
        assert analyze(tmp_path, "controller/sync.py", src,
                       only=["reconcile-purity"]) == []

    def test_from_import_sleep_detected(self, tmp_path):
        src = """
        from time import sleep

        def sync(key):
            sleep(0.1)
        """
        findings = analyze(tmp_path, "controller/sync.py", src,
                           only=["reconcile-purity"])
        assert ids(findings) == ["TJA003"]

    def test_waiver_suppresses(self, tmp_path):
        src = """
        def run(stop):
            # analyzer: allow[reconcile-purity]: parks the caller thread
            stop.wait()
        """
        assert analyze(tmp_path, "controller/run.py", src,
                       only=["reconcile-purity"]) == []


# -- TJA004 broad-except -----------------------------------------------------

class TestBroadExcept:
    def test_fires_on_silent_swallow(self, tmp_path):
        src = """
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except:
                return None
        """
        findings = analyze(tmp_path, "m.py", src, only=["broad-except"])
        assert ids(findings) == ["TJA004"]
        assert len(findings) == 2
        assert any("bare except" in f.message for f in findings)

    def test_logging_reraise_and_narrow_are_quiet(self, tmp_path):
        src = """
        import logging

        log = logging.getLogger(__name__)

        def logged():
            try:
                g()
            except Exception:
                log.exception("g failed")

        def reraised():
            try:
                g()
            except Exception:
                cleanup()
                raise

        def narrow():
            try:
                g()
            except (KeyError, ValueError):
                pass
        """
        assert analyze(tmp_path, "m.py", src, only=["broad-except"]) == []

    def test_waiver_comment_suppresses(self, tmp_path):
        src = """
        def f():
            try:
                g()
            # analyzer: allow[broad-except]: best-effort cleanup, failure
            # here must never mask the original exception being handled.
            except Exception:
                pass
        """
        assert analyze(tmp_path, "m.py", src, only=["broad-except"]) == []

    def test_forwarding_the_bound_exception_is_accountable(self, tmp_path):
        src = """
        def forwarded(q):
            try:
                g()
            except Exception as exc:
                q.put(exc)          # surfaced to the consumer: fine

        def bound_but_dropped():
            try:
                g()
            except Exception as exc:
                return None         # bound name unused: still swallowing
        """
        findings = analyze(tmp_path, "m.py", src, only=["broad-except"])
        assert len(findings) == 1
        assert findings[0].line == 11


# -- TJA005 constant-drift ---------------------------------------------------

FAKE_CONSTANTS = """
    JOB_NAME_LABEL = "TrainingJobName"
    TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
    PRIORITY_LABEL = "priority"
"""


class TestConstantDrift:
    def _write_constants(self, tmp_path):
        p = tmp_path / PKG / "api" / "constants.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(FAKE_CONSTANTS))

    def test_fires_on_duplicated_and_undefined_contract_strings(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        def build(pod):
            pod.labels["TrainingJobName"] = pod.name      # dup of constant
            pod.env["TRAININGJOB_NEW_KNOB"] = "1"          # undefined contract
        """
        findings = analyze(tmp_path, f"{PKG}/controller/pod.py", src,
                           only=["constant-drift"])
        assert ids(findings) == ["TJA005"]
        msgs = " | ".join(f.message for f in findings)
        assert "JOB_NAME_LABEL" in msgs
        assert "TRAININGJOB_NEW_KNOB" in msgs

    def test_quiet_on_constant_usage_and_generic_words(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def build(pod):
            pod.labels[constants.JOB_NAME_LABEL] = pod.name
            pod.labels["priority"] = "high"   # generic word: not contract-shaped
        """
        assert analyze(tmp_path, f"{PKG}/controller/pod.py", src,
                       only=["constant-drift"]) == []

    def test_docstrings_and_out_of_scope_dirs_are_quiet(self, tmp_path):
        self._write_constants(tmp_path)
        src = '''
        """Mentions TPU_WORKER_ID and TrainingJobName in prose."""

        def f():
            """Also TRAININGJOB_UNDEFINED_IN_DOCSTRING."""
        '''
        assert analyze(tmp_path, f"{PKG}/controller/doc.py", src,
                       only=["constant-drift"]) == []
        bad = 'X = "TrainingJobName"\n'
        # models/ is outside the constant-drift scope
        assert analyze(tmp_path, f"{PKG}/models/m.py", bad,
                       only=["constant-drift"]) == []


# -- TJA006 tracer-safety ----------------------------------------------------

BAD_JIT = """
    import jax

    @jax.jit
    def step(x, lr):
        if lr > 0.5:
            x = x * lr
        while x > 0:
            x = x - 1
        loss = float(x)
        print("loss", loss)
        return x.item()
"""

GOOD_JIT = """
    from functools import partial
    import jax
    from jax import lax

    @partial(jax.jit, static_argnames=("n",))
    def step(x, n, mask=None):
        if n > 2:              # static: fine
            x = x + n
        if mask is None:       # concrete at trace time: fine
            mask = x * 0
        return lax.cond(x.sum() > 0, lambda v: v, lambda v: -v, x)

    def helper(x):             # not traced at all
        if x > 0:
            print(x)
        return float(x)
"""


class TestTracerSafety:
    def test_fires_on_all_three_bug_classes(self, tmp_path):
        findings = analyze(tmp_path, "models/step.py", BAD_JIT,
                           only=["tracer-safety"])
        assert ids(findings) == ["TJA006"]
        msgs = " | ".join(f.message for f in findings)
        assert "Python 'if' on traced" in msgs
        assert "Python 'while' on traced" in msgs
        assert "float()" in msgs
        assert ".item()" in msgs
        assert "jax.debug.print" in msgs

    def test_statics_none_checks_and_untraced_are_quiet(self, tmp_path):
        assert analyze(tmp_path, "models/step.py", GOOD_JIT,
                       only=["tracer-safety"]) == []

    def test_assignment_wrapped_function_detected(self, tmp_path):
        src = """
        import jax

        def body(q):
            if q > 0:
                q = -q
            return q

        wrapped = jax.jit(body)
        """
        findings = analyze(tmp_path, "ops/m.py", src, only=["tracer-safety"])
        assert ids(findings) == ["TJA006"]

    def test_out_of_scope_dir_is_quiet(self, tmp_path):
        assert analyze(tmp_path, "controller/m.py", BAD_JIT,
                       only=["tracer-safety"]) == []


# -- TJA007 event-reason-drift -----------------------------------------------

FAKE_REASON_CONSTANTS = """
    OK_REASON = "JobOk"
    UNREGISTERED_REASON = "JobUnregistered"
    EVENT_REASONS = frozenset((
        OK_REASON,
    ))
"""


class TestEventReasonDrift:
    def _write_constants(self, tmp_path):
        p = tmp_path / PKG / "api" / "constants.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(FAKE_REASON_CONSTANTS))

    def test_fires_on_adhoc_and_unregistered_reasons(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def f(recorder, job):
            recorder.event(job, "Normal", "JobOkk", "typo'd literal")
            recorder.event(job, "Normal", constants.UNREGISTERED_REASON, "m")
        """
        findings = analyze(tmp_path, f"{PKG}/controller/x.py", src,
                           only=["event-reason-drift"])
        assert ids(findings) == ["TJA007"]
        msgs = " | ".join(f.message for f in findings)
        assert "JobOkk" in msgs
        assert "UNREGISTERED_REASON" in msgs

    def test_quiet_on_registered_dynamic_and_non_recorder(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def f(recorder, bus, job, reason):
            recorder.event(job, "Normal", constants.OK_REASON, "m")
            recorder.event(job, "Normal", "JobOk", "registry value literal")
            recorder.event(job, "Normal", reason, "dynamic: skipped")
            bus.event(job, "Normal", "NotARecorder", "receiver out of scope")
        """
        assert analyze(tmp_path, f"{PKG}/controller/x.py", src,
                       only=["event-reason-drift"]) == []

    def test_real_tree_call_sites_are_clean(self, tmp_path):
        for rel in ("controller/control.py", "controller/pod.py",
                    "controller/controller.py"):
            src = open(os.path.join(REPO_ROOT, PKG, *rel.split("/"))).read()
            assert analyze(tmp_path, f"{PKG}/{rel}", src,
                           only=["event-reason-drift"]) == [], rel


# -- TJA008 orphaned-thread --------------------------------------------------

class TestOrphanedThread:
    def test_fires_on_leaked_and_unbound_threads(self, tmp_path):
        src = """
        import threading

        def leak(work):
            t = threading.Thread(target=work)
            t.start()

        def unbound(work):
            threading.Thread(target=work).start()
        """
        findings = analyze(tmp_path, "m.py", src, only=["orphaned-thread"])
        assert ids(findings) == ["TJA008"]
        assert len(findings) == 2
        msgs = " | ".join(f.message for f in findings)
        assert "'t'" in msgs
        assert "never bound" in msgs

    def test_quiet_on_daemon_join_sweep_and_late_daemon(self, tmp_path):
        src = """
        import threading

        def daemonized(work):
            threading.Thread(target=work, daemon=True).start()

        def joined(work):
            t = threading.Thread(target=work)
            t.start()
            t.join(1)

        def swept(work):
            threads = [threading.Thread(target=work) for _ in range(3)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()

        def appended(work):
            threads = []
            for _ in range(2):
                threads.append(threading.Thread(target=work))
            [t.join() for t in threads]

        class C:
            def start(self, work):
                self._th = threading.Thread(target=work)
                self._th.daemon = True
                self._th.start()
        """
        assert analyze(tmp_path, "m.py", src, only=["orphaned-thread"]) == []

    def test_explicit_daemon_false_still_needs_join(self, tmp_path):
        src = """
        import threading

        def f(work):
            t = threading.Thread(target=work, daemon=False)
            t.start()
        """
        findings = analyze(tmp_path, "m.py", src, only=["orphaned-thread"])
        assert ids(findings) == ["TJA008"]

    def test_waiver_suppresses(self, tmp_path):
        src = """
        import threading

        def f(work):
            # analyzer: allow[orphaned-thread]: joined by the caller
            t = threading.Thread(target=work)
            return t
        """
        assert analyze(tmp_path, "m.py", src, only=["orphaned-thread"]) == []


# -- TJA009 status-write-discipline ------------------------------------------

class TestStatusWriteDiscipline:
    def test_fires_on_direct_phase_and_condition_mutation(self, tmp_path):
        src = """
        def rogue(job, cond):
            job.status.phase = "Failed"
            job.status.conditions = []
            job.status.conditions.append(cond)
            fresh_job.status.phase = "Running"
        """
        findings = analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                           src, only=["status-write-discipline"])
        assert ids(findings) == ["TJA009"]
        assert len(findings) == 4
        assert all("update_job_conditions" in f.message for f in findings)

    def test_quiet_on_pod_status_and_reads(self, tmp_path):
        src = """
        def fine(job, pod, node):
            pod.status.phase = "Running"       # pod status: unguarded API
            node.status.conditions = []
            if job.status.phase == "Running":  # read, not write
                return job.status.conditions[-1]
            job.status.restart_replica_name = ""  # not a guarded field
        """
        assert analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                       src, only=["status-write-discipline"]) == []

    def test_status_machine_helpers_are_exempt(self, tmp_path):
        src = """
        def set_condition(status, new_cond):
            status.conditions.append(new_cond)

        def update_job_conditions(job, ctype):
            job.status.phase = ctype

        def rogue(job):
            job.status.phase = "X"
        """
        findings = analyze(
            tmp_path, "trainingjob_operator_tpu/controller/status.py", src,
            only=["status-write-discipline"])
        assert len(findings) == 1
        assert findings[0].line == 9

    def test_out_of_package_code_is_not_scoped(self, tmp_path):
        src = """
        def fixture(job):
            job.status.phase = "Succeeded"
        """
        assert analyze(tmp_path, "tests/m.py", src,
                       only=["status-write-discipline"]) == []

    def test_waiver_suppresses(self, tmp_path):
        src = """
        def migrate(job):
            # analyzer: allow[status-write-discipline]: one-shot migration
            job.status.phase = "Failed"
        """
        assert analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                       src, only=["status-write-discipline"]) == []


# -- TJA010 lock-order-cycle -------------------------------------------------

LOCK_CYCLE_SRC = """\
import threading


class Alpha:
    def __init__(self):
        self._la = threading.Lock()
        self.beta = Beta()

    def forward(self):
        with self._la:
            self.beta.poke()

    def grab(self):
        with self._la:
            pass


class Beta:
    def __init__(self):
        self._lb = threading.Lock()
        self.alpha = Alpha()

    def poke(self):
        with self._lb:
            pass

    def back(self):
        with self._lb:
            self.alpha.grab()
"""


class TestLockOrderCycle:
    def test_fires_on_two_lock_inversion(self, tmp_path):
        """Alpha holds la and calls into a lb-acquirer; Beta holds lb and
        calls (transitively) an la-acquirer: la -> lb -> la."""
        findings = analyze_tree(
            tmp_path, {"trainingjob_operator_tpu/plane.py": LOCK_CYCLE_SRC},
            only=["lock-order-cycle"])
        assert ids(findings) == ["TJA010"]
        assert any("cycle" in f.message or "deadlock" in f.message
                   for f in findings)

    def test_fires_on_self_deadlock_of_plain_lock(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/selfy.py": """\
                import threading


                class Selfy:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """}, only=["TJA010"])
        assert ids(findings) == ["TJA010"]

    def test_quiet_on_rlock_reentry(self, tmp_path):
        """The same shape with an RLock is legal re-entry, not a deadlock."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/selfy.py": """\
                import threading


                class Selfy:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """}, only=["TJA010"])
        assert findings == []

    def test_quiet_on_deferred_callback_under_lock(self, tmp_path):
        """A lambda *registered* under the lock runs later, at call time --
        its acquisitions must not count as nested-while-held (the telemetry
        gauge-callback pattern)."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/gauges.py": """\
                import threading


                class Gauges:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._cb = None

                    def register(self):
                        with self._lock:
                            self._cb = lambda: self.read()

                    def read(self):
                        with self._lock:
                            return 1
                """}, only=["TJA010"])
        assert findings == []

    def test_quiet_on_consistent_order(self, tmp_path):
        """la -> lb in every path: an ordering, not a cycle."""
        src = LOCK_CYCLE_SRC.replace(
            "    def back(self):\n"
            "        with self._lb:\n"
            "            self.alpha.grab()\n",
            "    def back(self):\n"
            "        self.alpha.grab()\n")
        findings = analyze_tree(
            tmp_path, {"trainingjob_operator_tpu/plane.py": src},
            only=["TJA010"])
        assert findings == []


# -- TJA011 env-contract -----------------------------------------------------

ENV_CONSTANTS = """\
FOO_ENV = "TRAININGJOB_FOO"
BAR_ENV = "TRAININGJOB_BAR"
"""


class TestEnvContract:
    def test_fires_on_read_never_injected(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": ENV_CONSTANTS,
            "trainingjob_operator_tpu/worker.py": """\
                import os

                from trainingjob_operator_tpu.api import constants


                def addr():
                    return os.environ.get(constants.FOO_ENV, "")
                """}, only=["env-contract"])
        assert ids(findings) == ["TJA011"]
        (f,) = findings
        assert f.severity == "error" and "never injected" in f.message
        assert f.path == "trainingjob_operator_tpu/worker.py"

    def test_fires_on_injected_never_read(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": ENV_CONSTANTS,
            "trainingjob_operator_tpu/pod.py": """\
                from trainingjob_operator_tpu.api import constants


                def build_env(env):
                    env[constants.BAR_ENV] = "1"
                """}, only=["TJA011"])
        assert ids(findings) == ["TJA011"]
        (f,) = findings
        assert f.severity == "warning" and "nothing" in f.message

    def test_fires_on_undeclared_contract_var(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": ENV_CONSTANTS,
            "trainingjob_operator_tpu/worker.py": """\
                import os


                def mystery():
                    return os.environ.get("TRAININGJOB_MYSTERY", "")
                """}, only=["TJA011"])
        assert any(f.severity == "error" and "not declared" in f.message
                   for f in findings)

    def test_quiet_when_declared_user_knob(self, tmp_path):
        """A knob the *user* sets (never the controller) is exempt from the
        read-never-injected direction via USER_ENV_KNOBS."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py":
                ENV_CONSTANTS + "USER_ENV_KNOBS = frozenset((FOO_ENV, BAR_ENV))\n",
            "trainingjob_operator_tpu/worker.py": """\
                import os

                from trainingjob_operator_tpu.api import constants


                def addr():
                    return os.environ.get(constants.FOO_ENV, "")
                """}, only=["TJA011"])
        assert findings == []

    def test_quiet_on_closed_triangle(self, tmp_path):
        """Declared, injected, and read: nothing to report."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": ENV_CONSTANTS,
            "trainingjob_operator_tpu/pod.py": """\
                from trainingjob_operator_tpu.api import constants


                def build_env(env):
                    env[constants.FOO_ENV] = "addr:1234"
                """,
            "trainingjob_operator_tpu/worker.py": """\
                import os

                from trainingjob_operator_tpu.api import constants


                def addr():
                    return os.environ.get(constants.FOO_ENV, "")
                """}, only=["TJA011"])
        assert [f for f in findings if "TRAININGJOB_FOO" in f.message] == []


# -- TJA012 metric-name-drift ------------------------------------------------

METRIC_DOC = """\
# Observability

| name | type | meaning |
|------|------|---------|
| `trainingjob_good_total` | counter | documented and emitted |
| `trainingjob_ghost_total` | counter | documented, never emitted |
"""


class TestMetricNameDrift:
    def test_fires_both_directions(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "docs/OBSERVABILITY.md": METRIC_DOC,
            "trainingjob_operator_tpu/metrics_user.py": """\
                def emit(registry):
                    registry.inc("trainingjob_good_total")
                    registry.inc("trainingjob_rogue_total")
                """}, only=["metric-name-drift"])
        assert ids(findings) == ["TJA012"]
        rogue = [f for f in findings if "rogue" in f.message]
        ghost = [f for f in findings if "ghost" in f.message]
        assert len(rogue) == 1 and rogue[0].severity == "error"
        assert rogue[0].path == "trainingjob_operator_tpu/metrics_user.py"
        assert len(ghost) == 1 and ghost[0].severity == "warning"
        assert ghost[0].path == "docs/OBSERVABILITY.md"

    def test_quiet_on_non_metric_callee(self, tmp_path):
        """A metric-patterned literal passed to a non-metric callee (the
        ContextVar-name pattern in obs/trace.py) is not an emission."""
        findings = analyze_tree(tmp_path, {
            "docs/OBSERVABILITY.md": METRIC_DOC,
            "trainingjob_operator_tpu/trace_like.py": """\
                import contextvars

                _span = contextvars.ContextVar(
                    "trainingjob_undocumented_span", default=None)


                def emit(registry):
                    registry.inc("trainingjob_good_total")
                    registry.observe("trainingjob_ghost_total", 1.0)
                """}, only=["TJA012"])
        assert findings == []


# -- TJA013 phase-transition-exhaustiveness ----------------------------------

PHASE_CONSTANTS = """\
PHASE_TRANSITIONS = {
    "": ("Pending",),
    "Pending": ("Running",),
    "Running": ("Succeed", "Failed"),
    "Succeed": (),
}
"""

PHASE_TYPES = """\
class TrainingJobPhase:
    NONE = ""
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeed"
    FAILED = "Failed"
    TIMEOUT = "Timeout"
"""


class TestPhaseTransitionExhaustiveness:
    def test_fires_on_witnessed_illegal_transition(self, tmp_path):
        """Succeed -> Running resurrects a completed job; the table forbids
        it and the dominating phase test witnesses the source."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": PHASE_CONSTANTS,
            "trainingjob_operator_tpu/api/types.py": PHASE_TYPES,
            "trainingjob_operator_tpu/sync.py": """\
                from trainingjob_operator_tpu.api.types import TrainingJobPhase
                from trainingjob_operator_tpu.status import update_job_conditions


                def resurrect(job):
                    if job.status.phase == TrainingJobPhase.SUCCEEDED:
                        update_job_conditions(job, TrainingJobPhase.RUNNING,
                                              "Restarted", "never do this")
                """}, only=["phase-transition-exhaustiveness"])
        assert ids(findings) == ["TJA013"]
        (f,) = findings
        assert "'Succeed' -> 'Running'" in f.message

    def test_fires_on_unreachable_target(self, tmp_path):
        """A target no table entry allows any source to reach."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": PHASE_CONSTANTS,
            "trainingjob_operator_tpu/api/types.py": PHASE_TYPES,
            "trainingjob_operator_tpu/sync.py": """\
                from trainingjob_operator_tpu.api.types import TrainingJobPhase
                from trainingjob_operator_tpu.status import update_job_conditions


                def expire(job):
                    update_job_conditions(job, TrainingJobPhase.TIMEOUT,
                                          "Expired", "nothing declares this")
                """}, only=["TJA013"])
        assert ids(findings) == ["TJA013"]
        assert "no PHASE_TRANSITIONS entry" in findings[0].message

    def test_quiet_on_legal_and_dynamic_transitions(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": PHASE_CONSTANTS,
            "trainingjob_operator_tpu/api/types.py": PHASE_TYPES,
            "trainingjob_operator_tpu/sync.py": """\
                from trainingjob_operator_tpu.api.types import TrainingJobPhase
                from trainingjob_operator_tpu.status import update_job_conditions


                def advance(job, ending_phase):
                    if job.status.phase == TrainingJobPhase.PENDING:
                        update_job_conditions(job, TrainingJobPhase.RUNNING,
                                              "Started", "legal")
                    if job.status.phase == TrainingJobPhase.RUNNING:
                        # Same-phase refresh: always legal.
                        update_job_conditions(job, TrainingJobPhase.RUNNING,
                                              "Heartbeat", "refresh")
                    # Dynamic target: skipped, the runtime guard owns it.
                    update_job_conditions(job, ending_phase, "End", "dynamic")
                """}, only=["TJA013"])
        assert findings == []


# -- TJA014 dead-event-reason ------------------------------------------------

REASON_CONSTANTS = """\
ALIVE_REASON = "AliveReason"
DEAD_REASON = "DeadReason"

EVENT_REASONS = frozenset((
    ALIVE_REASON,
    DEAD_REASON,
))
"""


class TestDeadEventReason:
    def test_fires_on_registry_entry_nothing_emits(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": REASON_CONSTANTS,
            "trainingjob_operator_tpu/emitter.py": """\
                from trainingjob_operator_tpu.api import constants


                def emit(recorder, job):
                    recorder.event(job, "Normal", constants.ALIVE_REASON, "m")
                """}, only=["dead-event-reason"])
        assert ids(findings) == ["TJA014"]
        (f,) = findings
        assert "'DeadReason'" in f.message
        assert f.path == "trainingjob_operator_tpu/api/constants.py"
        # Reported at the member's line inside the frozenset literal.
        assert f.line == 6

    def test_quiet_when_every_reason_is_used(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/api/constants.py": REASON_CONSTANTS,
            "trainingjob_operator_tpu/emitter.py": """\
                from trainingjob_operator_tpu.api import constants


                def emit(recorder, job):
                    recorder.event(job, "Normal", constants.ALIVE_REASON, "m")
                    recorder.event(job, "Warning", "DeadReason", "literal use")
                """}, only=["TJA014"])
        assert findings == []


# -- the CFG itself (tools/analyze/cfg.py) -----------------------------------

def _cfg_of(source, name=None):
    import ast

    from tools.analyze import cfg as cfglib

    tree = ast.parse(textwrap.dedent(source))
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    fn = fns[0] if name is None else next(f for f in fns if f.name == name)
    return cfglib.build_cfg(fn)


class TestCFGShapes:
    def test_try_finally_duplicates_the_finalbody(self):
        """The finally body exists twice: a normal-path copy reaching the
        after block, and an exceptional copy whose tail re-raises outward --
        the linearization TJA015/TJA019 rely on."""
        c = _cfg_of("""
        def f(acquire, use):
            s = acquire()
            try:
                use(s)
            finally:
                s.close()
        """)
        labels = [b.label for b in c.blocks]
        assert "finally" in labels and "finally-exc" in labels
        exc_copy = next(b for b in c.blocks if b.label == "finally-exc")
        # use(s) raises into the exceptional copy...
        try_block = next(b for b in c.blocks if b.label == "try")
        assert (exc_copy, "exc") in try_block.succs
        # ...which runs the close and re-raises to the function's exc exit.
        assert any(kind == "exc" and nxt is c.exc_exit
                   for nxt, kind in exc_copy.succs)

    def test_return_through_finally_runs_an_abrupt_copy(self):
        c = _cfg_of("""
        def f(cleanup):
            try:
                return 1
            finally:
                cleanup()
        """)
        abrupt = [b for b in c.blocks if b.label == "finally-abrupt"]
        assert len(abrupt) == 1
        # The abrupt copy drains into the normal exit, not exc_exit.
        assert any(kind == "finally" and nxt is c.exit
                   for nxt, kind in abrupt[0].succs)

    def test_while_else_edges(self):
        import ast

        c = _cfg_of("""
        def f(cond, step, wrapup, done):
            while cond():
                step()
            else:
                wrapup()
            done()
        """)
        fn = c.func
        while_stmt = fn.body[0]
        head = c.block_of[id(while_stmt)]
        kinds = {kind: nxt for nxt, kind in head.succs if kind != "exc"}
        assert kinds["true"].label == "loop-body"
        assert kinds["false"].label == "loop-else"
        # The body's back edge returns to the head.
        assert any(kind == "loop" and nxt is head
                   for b in c.blocks for nxt, kind in b.succs)
        assert isinstance(while_stmt, ast.While)

    def test_while_true_has_no_false_edge(self):
        c = _cfg_of("""
        def f(step):
            while True:
                step()
        """)
        head = next(b for b in c.blocks if b.label == "loop-head")
        assert not any(kind == "false" for _n, kind in head.succs)

    def test_nested_with_bodies_share_the_block(self):
        """``with`` introduces no kill point, so nested with bodies extend
        the current straight-line block."""
        c = _cfg_of("""
        def f(a, b, use, after):
            with a() as x:
                with b() as y:
                    use(x, y)
            after()
        """)
        fn = c.func
        outer = fn.body[0]
        inner = outer.body[0]
        use_stmt = inner.body[0]
        assert (c.block_of[id(outer)] is c.block_of[id(inner)]
                is c.block_of[id(use_stmt)])

    def test_break_and_continue_edges_target_after_and_head(self):
        c = _cfg_of("""
        def f(items, bad, stop):
            for it in items:
                if bad(it):
                    continue
                if stop(it):
                    break
            return 0
        """)
        kinds = {kind for b in c.blocks for _n, kind in b.succs}
        assert "continue" in kinds and "break" in kinds

    def test_cfg_built_once_across_passes(self, tmp_path):
        """TJA015 and TJA019 both need f's CFG; the FileContext memo means
        exactly one build."""
        from tools.analyze import cfg as cfglib

        src = """
        import socket

        def f(host):
            s = socket.socket()
            busy = True
            s.connect((host, 1))
            busy = False
            s.close()
        """
        before = cfglib.BUILD_COUNT
        findings = analyze(tmp_path, "m.py", src,
                           only=["resource-leak", "finally-state-restore"])
        assert cfglib.BUILD_COUNT - before == 1
        # Both passes also find their half of the seeded bug.
        assert ids(findings) == ["TJA015", "TJA019"]


# -- TJA015 resource-leak ----------------------------------------------------

class TestResourceLeak:
    def test_fires_on_exception_and_return_path_leaks(self, tmp_path):
        src = """
        import socket

        def exc_leak(host):
            s = socket.create_connection((host, 80))
            s.sendall(b"hi")
            s.close()

        def return_leak(ready):
            server = socket.socket()
            server.bind(("", 0))
            if ready():
                return 1
            server.close()
            return 0
        """
        findings = analyze(tmp_path, "m.py", src, only=["resource-leak"])
        assert ids(findings) == ["TJA015"]
        assert len(findings) == 2
        msgs = " | ".join(f.message for f in findings)
        assert "'s'" in msgs and "exception path" in msgs
        assert "'server'" in msgs and "return path" in msgs

    def test_quiet_on_with_finally_escape_and_handoff(self, tmp_path):
        src = """
        import socket
        import threading

        def managed(host):
            with socket.create_connection((host, 80)) as s:
                s.sendall(b"hi")

        def closed_in_finally(host):
            s = socket.create_connection((host, 80))
            try:
                s.sendall(b"hi")
            finally:
                s.close()

        def handed_off(host, pool):
            s = socket.create_connection((host, 80))
            pool.append(s)

        def returned(host):
            s = socket.create_connection((host, 80))
            return s

        def started(work):
            t = threading.Thread(target=work, daemon=True)
            t.start()
        """
        assert analyze(tmp_path, "m.py", src, only=["resource-leak"]) == []

    def test_factory_raising_does_not_leak_on_its_own_edge(self, tmp_path):
        """gen is not applied on the exception edge of the acquiring
        statement itself: if socket() raises, nothing was bound."""
        src = """
        import socket

        def f():
            s = socket.socket()
            s.close()
        """
        assert analyze(tmp_path, "m.py", src, only=["resource-leak"]) == []


# -- TJA016 lock-held-blocking-call ------------------------------------------

class TestLockHeldBlockingCall:
    def test_fires_on_blocking_io_under_with_lock(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/plane.py": """\
                import threading
                import time

                _lock = threading.Lock()


                def slow_flush(sock, payload):
                    with _lock:
                        sock.sendall(payload)


                class Pacer:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def pace(self):
                        with self._lock:
                            time.sleep(1.0)
                """}, only=["lock-held-blocking-call"])
        assert ids(findings) == ["TJA016"]
        assert len(findings) == 2
        msgs = " | ".join(f.message for f in findings)
        assert "sendall" in msgs and "sleep" in msgs

    def test_fires_on_manual_acquire_path(self, tmp_path):
        """Witness 3: the must-analysis over acquire()/release() pairs."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/manual.py": """\
                import threading


                def held_recv(sock):
                    lock = threading.Lock()
                    lock.acquire()
                    data = sock.recv(1)
                    lock.release()
                    return data
                """}, only=["TJA016"])
        assert ids(findings) == ["TJA016"]

    def test_quiet_when_io_moved_out_or_bounded(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/good.py": """\
                import threading

                _lock = threading.Lock()


                def snapshot_then_send(sock, params):
                    with _lock:
                        snap = dict(params)
                    sock.sendall(repr(snap).encode())


                def bounded_get(q):
                    with _lock:
                        return q.get(timeout=0.5)


                def released_before_io(sock):
                    lock = threading.Lock()
                    lock.acquire()
                    try:
                        payload = b"x"
                    finally:
                        lock.release()
                    sock.sendall(payload)
                """}, only=["TJA016"])
        assert findings == []

    def test_fires_transitively_through_a_callee(self, tmp_path):
        """Witness 1: the held call blocks two hops away."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/deep.py": """\
                import threading
                import time


                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def flush(self):
                        with self._lock:
                            self._drain()

                    def _drain(self):
                        self._settle()

                    def _settle(self):
                        time.sleep(0.5)
                """}, only=["TJA016"])
        assert ids(findings) == ["TJA016"]
        assert any("sleep" in f.message for f in findings)


# -- TJA017 exception-escape -------------------------------------------------

class TestExceptionEscape:
    def test_fires_on_thread_target_with_escaping_callee(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/srv.py": """\
                import threading


                def parse(frame):
                    if not frame:
                        raise ValueError("empty frame")
                    return frame


                def handle(conn):
                    data = parse(conn)
                    return data


                def serve(conn):
                    t = threading.Thread(target=handle, args=(conn,),
                                         daemon=True)
                    t.start()
                    t.join()
                """}, only=["exception-escape"])
        assert ids(findings) == ["TJA017"]
        (f,) = findings
        assert "ValueError" in f.message
        # Anchored at the spawn site, not inside the target.
        assert f.line == 16

    def test_quiet_when_target_catches(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/srv.py": """\
                import threading


                def parse(frame):
                    raise ValueError("empty frame")


                def handle(conn):
                    try:
                        parse(conn)
                    except (ValueError, OSError) as e:
                        print(e)


                def serve(conn):
                    t = threading.Thread(target=handle, args=(conn,),
                                         daemon=True)
                    t.start()
                    t.join()
                """}, only=["TJA017"])
        assert findings == []

    def test_quiet_without_a_spawn_site(self, tmp_path):
        """Escapes are reported only at Thread(target=...) anchors."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/lib.py": """\
                def boom():
                    raise RuntimeError("not a thread target")
                """}, only=["TJA017"])
        assert findings == []

    def test_handler_body_raises_are_not_caught_by_own_try(self, tmp_path):
        """Handlers guard only the try *body*: a raise inside the handler
        still escapes."""
        findings = analyze_tree(tmp_path, {
            "trainingjob_operator_tpu/srv.py": """\
                import threading


                def handle(conn):
                    try:
                        conn.recv(1)
                    except OSError:
                        raise RuntimeError("rethrown")


                def serve(conn):
                    t = threading.Thread(target=handle, args=(conn,),
                                         daemon=True)
                    t.start()
                    t.join()
                """}, only=["TJA017"])
        assert ids(findings) == ["TJA017"]
        assert "RuntimeError" in findings[0].message


# -- TJA018 retry-without-backoff --------------------------------------------

class TestRetryWithoutBackoff:
    def test_fires_on_hot_while_retry(self, tmp_path):
        src = """
        def hammer(client):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    continue
        """
        findings = analyze(tmp_path, "m.py", src,
                           only=["retry-without-backoff"])
        assert ids(findings) == ["TJA018"]
        (f,) = findings
        assert f.severity == "warning" and "OSError" in f.message

    def test_quiet_with_backoff_in_handler(self, tmp_path):
        src = """
        import time

        def patient(client):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    time.sleep(0.5)
        """
        assert analyze(tmp_path, "m.py", src,
                       only=["retry-without-backoff"]) == []

    def test_quiet_on_timeout_only_handler(self, tmp_path):
        """A blocking call that timed out already paced the loop."""
        src = """
        import socket

        def poll(sock):
            while True:
                try:
                    return sock.recv(1)
                except socket.timeout:
                    continue
        """
        assert analyze(tmp_path, "m.py", src,
                       only=["retry-without-backoff"]) == []

    def test_quiet_on_for_loop_and_non_swallowing_handler(self, tmp_path):
        src = """
        def sweep(client, items):
            for it in items:
                try:
                    client.send(it)
                except OSError:
                    continue

        def bounded(client):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    raise
        """
        assert analyze(tmp_path, "m.py", src,
                       only=["retry-without-backoff"]) == []

    # -- the jitter advisory (retry-backoff-no-jitter) -----------------------

    def test_advisory_fires_on_constant_sleep_in_client_path(self, tmp_path):
        """A paced retry loop whose every pacer is the same fixed sleep
        retries in fleet-wide lockstep; in the API-client/controller tree
        that is the thundering-herd shape the advisory flags."""
        src = """
        import time

        def patient(client):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    time.sleep(0.5)
        """
        findings = analyze(tmp_path, "client/m.py", src,
                           only=["retry-without-backoff"])
        assert ids(findings) == ["TJA018"]
        (f,) = findings
        assert f.check_name == "retry-backoff-no-jitter"
        assert f.severity == "warning" and "jitter" in f.message

    def test_advisory_quiet_outside_scoped_paths(self, tmp_path):
        src = """
        import time

        def patient(client):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    time.sleep(0.5)
        """
        assert analyze(tmp_path, "workloads/m.py", src,
                       only=["retry-without-backoff"]) == []

    def test_advisory_quiet_with_computed_delay(self, tmp_path):
        src = """
        import time

        def patient(client, delay):
            while True:
                try:
                    return client.fetch()
                except OSError:
                    time.sleep(delay * 2)
        """
        assert analyze(tmp_path, "controller/m.py", src,
                       only=["retry-without-backoff"]) == []

    def test_advisory_quiet_with_backoff_helper(self, tmp_path):
        """Pacing through a *backoff*-named helper (client/retry.py's
        backoff_pause) is presumed jittered."""
        src = """
        def patient(client, policy):
            attempt = 0
            while True:
                try:
                    return client.fetch()
                except OSError:
                    backoff_pause(policy, attempt)
                    attempt += 1
        """
        assert analyze(tmp_path, "client/m.py", src,
                       only=["retry-without-backoff"]) == []


# -- TJA019 finally-state-restore --------------------------------------------

class TestFinallyStateRestore:
    def test_fires_on_restore_skipping_the_exception_path(self, tmp_path):
        src = """
        class Watchdog:
            def drain(self, flush_replicas):
                self._suspended = True
                flush_replicas()
                self._suspended = False
        """
        findings = analyze(tmp_path, "m.py", src,
                           only=["finally-state-restore"])
        assert ids(findings) == ["TJA019"]
        (f,) = findings
        assert "self._suspended" in f.message and "finally" in f.message
        assert f.line == 4

    def test_quiet_when_restored_in_finally(self, tmp_path):
        src = """
        class Watchdog:
            def drain(self, flush_replicas):
                self._suspended = True
                try:
                    flush_replicas()
                finally:
                    self._suspended = False
        """
        assert analyze(tmp_path, "m.py", src,
                       only=["finally-state-restore"]) == []

    def test_quiet_on_single_assignment_and_init(self, tmp_path):
        src = """
        class C:
            def __init__(self):
                self._ready = False
                self.boot()
                self._ready = True

            def set_once(self, work):
                self._armed = True
                work()
        """
        assert analyze(tmp_path, "m.py", src,
                       only=["finally-state-restore"]) == []


# -- runner: baseline, waivers, formats, CLI ---------------------------------

class TestRunnerMachinery:
    def test_baseline_roundtrip_suppresses_old_reports_new(self, tmp_path):
        bad = tmp_path / "m.py"
        bad.write_text("def f():\n    try:\n        g()\n"
                       "    except Exception:\n        pass\n")
        first = run_checks([str(bad)], root=str(tmp_path))
        assert len(first) == 1
        baseline_path = tmp_path / "baseline.json"
        assert write_baseline(str(baseline_path), first) == 1
        fresh, suppressed = apply_baseline(
            run_checks([str(bad)], root=str(tmp_path)),
            load_baseline(str(baseline_path)))
        assert fresh == [] and suppressed == 1
        # A *new* finding elsewhere in the file still surfaces -- and the
        # old fingerprint survives the line shift above it.
        bad.write_text("def z():\n    try:\n        g()\n"
                       "    except Exception:\n        return 1\n\n"
                       + bad.read_text())
        fresh, suppressed = apply_baseline(
            run_checks([str(bad)], root=str(tmp_path)),
            load_baseline(str(baseline_path)))
        assert len(fresh) == 1 and suppressed == 1

    def test_allow_star_waives_any_check(self, tmp_path):
        src = """
        import time

        def sync(key):
            # analyzer: allow[*]: fixture
            time.sleep(1)
        """
        assert analyze(tmp_path, "controller/m.py", src) == []

    def test_unknown_check_name_raises(self, tmp_path):
        (tmp_path / "m.py").write_text("x = 1\n")
        with pytest.raises(ValueError, match="unknown check"):
            run_checks([str(tmp_path)], root=str(tmp_path), only=["nope"])

    def test_formats(self):
        f = Finding("TJA001", "py-compat", "a/b.py", 3, 4, "error", "boom")
        text = format_findings([f], "text")
        assert text == "a/b.py:3:4: TJA001[py-compat] error: boom\n"
        gh = format_findings([f], "github")
        assert gh.startswith("::error file=a/b.py,line=3,col=4,")
        js = json.loads(format_findings([f], "json"))
        assert js[0]["check_id"] == "TJA001" and js[0]["line"] == 3

    def test_fingerprints_disambiguate_identical_messages(self):
        a = Finding("TJA004", "broad-except", "m.py", 3, 0, "warning", "same")
        b = Finding("TJA004", "broad-except", "m.py", 9, 0, "warning", "same")
        assert len(fingerprint_all([a, b])) == 2

    def test_all_thirty_two_checks_registered(self):
        runner._load_checks()
        assert {cid for cid, _fn in runner.REGISTRY.values()} == {
            "TJA001", "TJA002", "TJA003", "TJA004", "TJA005", "TJA006",
            "TJA007", "TJA008", "TJA009", "TJA015", "TJA018", "TJA019"}
        assert {cid for cid, _fn in runner.PROJECT_REGISTRY.values()} == {
            "TJA010", "TJA011", "TJA012", "TJA013", "TJA014", "TJA016",
            "TJA017", "TJA020", "TJA021", "TJA022", "TJA023", "TJA024",
            "TJA025", "TJA026", "TJA027", "TJA028", "TJA029", "TJA030",
            "TJA031", "TJA032"}
        assert len(runner.all_checks()) == 32

    def test_every_check_has_rule_help(self):
        """SARIF rule metadata coverage: every registered ID ships a
        one-line fullDescription (RULE_HELP) -- code scanning shows it on
        the rule page, so a missing entry is a silent docs gap."""
        runner._load_checks()
        assert set(runner.RULE_HELP) == set(runner.all_checks())

    def test_sarif_roundtrip(self):
        err = Finding("TJA015", "resource-leak", "a/b.py", 7, 2, "error",
                      "socket 's' leaks")
        warn = Finding("TJA018", "retry-without-backoff", "m.py", 3, 0,
                       "warning", "hot retry loop")
        doc = json.loads(format_findings([err, warn], "sarif"))
        assert doc["version"] == "2.1.0"
        assert "sarif-schema-2.1.0" in doc["$schema"]
        (run,) = doc["runs"]
        # Every registered check becomes a rule, so code-scanning can show
        # titles for findings from any pass.
        rules = run["tool"]["driver"]["rules"]
        rule_ids = {r["id"] for r in rules}
        assert rule_ids == set(runner.all_checks())
        # Full rule metadata: description, help link, default level
        # (warning-severity passes downgrade; everything else is error).
        for r in rules:
            assert r["fullDescription"]["text"], r["id"]
            assert "STATIC_ANALYSIS.md" in r["helpUri"]
            expected = runner.RULE_DEFAULT_LEVELS.get(r["id"], "error")
            assert r["defaultConfiguration"]["level"] == expected
        first, second = run["results"]
        assert first["ruleId"] == "TJA015" and first["level"] == "error"
        assert first["message"]["text"] == "socket 's' leaks"
        loc = first["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "a/b.py"
        assert loc["region"] == {"startLine": 7, "startColumn": 2}
        # col 0 clamps to SARIF's 1-based startColumn.
        region2 = second["locations"][0]["physicalLocation"]["region"]
        assert second["level"] == "warning" and region2["startColumn"] == 1

    def test_cli_accepts_sarif_format(self, tmp_path):
        (tmp_path / "bad.py").write_text(
            "def f():\n    try:\n        g()\n"
            "    except Exception:\n        pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--no-baseline", "--format=sarif"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["runs"][0]["results"][0]["ruleId"] == "TJA004"

    def test_every_check_has_a_docs_row(self):
        """Self-check: each registered ID must have a catalog row in
        docs/STATIC_ANALYSIS.md -- a check nobody can look up is a check
        nobody waives correctly."""
        runner._load_checks()
        doc = open(os.path.join(REPO_ROOT, "docs",
                                "STATIC_ANALYSIS.md")).read()
        for cid, name in sorted(runner.all_checks().items()):
            assert f"| {cid} |" in doc, f"{cid} has no catalog row"
            assert f"`{name}`" in doc, f"{name} not named in the catalog"


# -- the tier-1 gate ---------------------------------------------------------

class TestRepoIsClean:
    def test_zero_non_baselined_findings_on_the_repo(self):
        """The contract ``make lint`` enforces: the analyzer exits 0 on the
        tree, with every finding either fixed, waived, or baselined."""
        findings = run_checks([os.path.join(REPO_ROOT, PKG)], root=REPO_ROOT)
        if os.path.exists(runner.DEFAULT_BASELINE):
            findings, _ = apply_baseline(
                findings, load_baseline(runner.DEFAULT_BASELINE))
        assert findings == [], format_findings(findings, "text")

    def test_cli_exits_zero_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", PKG, "--format=github"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_exits_nonzero_on_seeded_bug(self, tmp_path):
        (tmp_path / "bad.py").write_text(
            "def f():\n    try:\n        g()\n"
            "    except Exception:\n        pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--no-baseline"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert "TJA004" in proc.stdout


# -- TJA020-023: the jit-boundary layer --------------------------------------

def _boundary_of(tmp_path, files):
    """Build the traced-region closure/hot map for a fixture tree."""
    from tools.analyze import jit_boundary as jb
    from tools.analyze.project import ProjectContext

    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(source))
    contexts = {}
    for abs_path in runner.iter_py_files([str(tmp_path)], str(tmp_path)):
        ctx = runner.make_context(abs_path, str(tmp_path))
        contexts[ctx.path] = ctx
    pc = runner.ProjectContext.build(str(tmp_path), contexts)
    return jb.boundary(pc)


class TestTracedClosure:
    def test_closure_reaches_through_helper_calls(self, tmp_path):
        """The closure is interprocedural: helpers reachable from a jitted
        entry are traced too, with static argnums recorded on the site."""
        b = _boundary_of(tmp_path, {"mod.py": """
            import jax

            def norm(x):
                return x / (x.sum() + 1e-6)

            def entry(x, k):
                return norm(x) * k

            step = jax.jit(entry, static_argnums=(1,))
        """})
        assert "mod.entry" in b.closure
        assert "mod.norm" in b.closure       # reached via entry, not jitted
        (site,) = b.sites
        assert site.static_argnums == (1,) and site.has_static

    def test_hot_loop_seeded_from_loop_carried_device_value(self, tmp_path):
        """The hot map keys off loop-carried device values (a jitted call's
        output feeding its next-iteration input) -- not file names."""
        b = _boundary_of(tmp_path, {"anyname.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                for _ in range(100):
                    s = step(s)
                return s
        """})
        assert any(h.fn_qual == "anyname.run" for h in b.hot_loops)
        # Functions invoked from the hot loop are hot too.
        assert "anyname.step" in b.hot_fns

    def test_straight_line_dispatch_is_not_hot(self, tmp_path):
        b = _boundary_of(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                s = step(s)
                return step(s)
        """})
        assert b.hot_loops == []

    def test_boundary_built_once_across_all_four_passes(self, tmp_path):
        """TJA020-023 all consume the closure; the ProjectContext memo means
        exactly one build (same contract as the CFG memo)."""
        from tools.analyze import jit_boundary as jb

        files = {"m.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                for _ in range(10):
                    s = step(s)
                return s
        """}
        for rel, source in files.items():
            (tmp_path / rel).write_text(textwrap.dedent(source))
        before = jb.BUILD_COUNT
        run_checks([str(tmp_path)], root=str(tmp_path),
                   only=["recompile-hazard", "host-sync-in-hot-loop",
                         "donation-discipline", "impure-capture"])
        assert jb.BUILD_COUNT - before == 1


class TestRecompileHazard:
    def test_fires_on_wrapper_built_inside_loop(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            def run(xs):
                out = []
                for x in xs:
                    step = jax.jit(lambda v: v + 1)
                    out.append(step(x))
                return out
        """}, only=["recompile-hazard"])
        assert ids(findings) == ["TJA020"]
        assert any(f.severity == "error" and "loop" in f.message
                   for f in findings)

    def test_fires_on_unhashable_static_argument(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            def f(x, dims):
                return x.reshape(dims)

            step = jax.jit(f, static_argnums=(1,))

            def run(x):
                return step(x, [4, 4])
        """}, only=["recompile-hazard"])
        assert ids(findings) == ["TJA020"]
        assert any("static" in f.message for f in findings)

    def test_quiet_on_hoisted_wrapper_and_hashable_statics(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            def f(x, dims):
                return x.reshape(dims)

            step = jax.jit(f, static_argnums=(1,))

            def run(xs):
                return [step(x, (4, 4)) for x in xs]
        """}, only=["recompile-hazard"])
        assert findings == []


class TestHostSyncHotLoop:
    def test_fires_on_float_read_in_hot_loop(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                for _ in range(100):
                    s = step(s)
                    print(float(s))
                return s
        """}, only=["host-sync-in-hot-loop"])
        assert ids(findings) == ["TJA021"]
        assert all(f.severity == "warning" for f in findings)

    def test_quiet_when_read_happens_after_the_loop(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                for _ in range(100):
                    s = step(s)
                return float(s)
        """}, only=["host-sync-in-hot-loop"])
        assert findings == []

    def test_waiver_routes_deliberate_fence(self, tmp_path):
        """A documented completion fence stays, with the waiver naming it."""
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(s):
                return s + 1

            def run(s):
                for _ in range(100):
                    s = step(s)
                    # analyzer: allow[host-sync-in-hot-loop] deliberate
                    # per-step fence for this fixture.
                    print(float(s))
                return s
        """}, only=["host-sync-in-hot-loop"])
        assert findings == []


class TestDonationDiscipline:
    def test_fires_on_read_after_donate_in_loop(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def run(state, xs):
                for x in xs:
                    step(state, x)
                return state
        """}, only=["donation-discipline"])
        assert any(f.check_id == "TJA022" and f.severity == "error"
                   for f in findings)

    def test_advises_missing_donation_on_hot_round_trip(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(state):
                return state * 2

            def run(state):
                for _ in range(100):
                    state = step(state)
                return state
        """}, only=["donation-discipline"])
        assert any(f.check_id == "TJA022" and f.severity == "warning"
                   and "donate" in f.message for f in findings)

    def test_quiet_when_donated_state_is_rebound(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax
            from functools import partial

            @partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def run(state, xs):
                for x in xs:
                    state = step(state, x)
                return state
        """}, only=["donation-discipline"])
        assert findings == []


class TestImpureCapture:
    def test_fires_on_module_state_mutation_in_traced_code(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            TRACE_LOG = []

            def helper(x):
                TRACE_LOG.append(x)
                return x + 1

            @jax.jit
            def step(x):
                return helper(x)
        """}, only=["impure-capture"])
        assert any(f.check_id == "TJA023" and f.severity == "error"
                   for f in findings)

    def test_fires_on_print_inside_traced_region(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(x):
                print(x)
                return x + 1
        """}, only=["impure-capture"])
        assert any(f.check_id == "TJA023" and f.severity == "warning"
                   for f in findings)

    def test_quiet_on_pure_traced_code_with_local_mutation(self, tmp_path):
        findings = analyze_tree(tmp_path, {"m.py": """
            import jax

            @jax.jit
            def step(x):
                parts = []
                for i in range(4):
                    parts.append(x * i)
                return sum(parts)
        """}, only=["impure-capture"])
        assert findings == []


class TestChangedSinceMode:
    def _git(self, cwd, *args):
        subprocess.run(["git", *args], cwd=cwd, check=True,
                       capture_output=True, text=True)

    def test_reports_only_into_ast_changed_files(self, tmp_path):
        """Two files with the same seeded bug; only the one whose AST
        changed since the ref is reported.  A comment-only edit does not
        count as changed."""
        clean = "def f():\n    return 1\n"
        bad = ("def f():\n    try:\n        g()\n"
               "    except Exception:\n        pass\n")
        (tmp_path / "changed.py").write_text(clean)
        (tmp_path / "unchanged.py").write_text(bad)
        (tmp_path / "commented.py").write_text(bad)
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "add", "-A")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "seed")
        (tmp_path / "changed.py").write_text(bad)          # AST changed
        (tmp_path / "commented.py").write_text("# note\n" + bad)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--changed-since", "HEAD", "--no-baseline"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "changed.py" in proc.stdout
        assert "unchanged.py" not in proc.stdout
        assert "commented.py" not in proc.stdout

    def test_constants_change_widens_project_passes_tree_wide(
            self, tmp_path):
        """Editing api/constants.py drops incremental scoping: the
        registries it declares parameterize project passes, so the edit
        can land findings in files that did not change -- here, an
        unchanged module's singleton goes unclassified when its registry
        entry is deleted."""
        constants = tmp_path / PKG / "api" / "constants.py"
        constants.parent.mkdir(parents=True)
        constants.write_text(
            "SHARD_STATE_REGISTRY = {\n"
            '    "api.constants.SHARD_STATE_REGISTRY": "constant",\n'
            '    "obs.state.CACHE": "shard_local",\n}\n')
        state = tmp_path / PKG / "obs" / "state.py"
        state.parent.mkdir(parents=True)
        state.write_text("CACHE = {}\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "add", "-A")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "seed")
        constants.write_text(
            "SHARD_STATE_REGISTRY = {\n"
            '    "api.constants.SHARD_STATE_REGISTRY": "constant",\n}\n')
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", PKG,
             "--changed-since", "HEAD", "--no-baseline"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "re-running project passes" in proc.stderr
        # The finding lands in the *unchanged* file -- exactly what naive
        # report_only scoping would have swallowed.
        assert f"{PKG}/obs/state.py" in proc.stdout
        assert "TJA027" in proc.stdout

    def test_exits_zero_fast_when_nothing_changed(self, tmp_path):
        (tmp_path / "ok.py").write_text("def f():\n    return 1\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "add", "-A")
        self._git(tmp_path, "-c", "user.email=t@t", "-c", "user.name=t",
                  "commit", "-qm", "seed")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--changed-since", "HEAD"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "no AST-changed files" in proc.stderr


class TestResultCache:
    """Full-run memoization (tools/analyze/cache.py): an unchanged tree
    replays its findings from .analyze-cache.json; any file edit -- and
    ``--no-cache`` -- forces a fresh analysis."""

    BAD = ("def f():\n    try:\n        g()\n"
           "    except Exception:\n        pass\n")

    def _run(self, tmp_path, *extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--no-baseline", *extra],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})

    def test_warm_run_replays_findings_and_says_so(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.BAD)
        cold = self._run(tmp_path)
        warm = self._run(tmp_path)
        assert cold.returncode == 1 and warm.returncode == 1
        assert "(cached)" not in cold.stderr
        assert "(cached)" in warm.stderr
        assert warm.stdout == cold.stdout       # identical findings
        assert (tmp_path / ".analyze-cache.json").exists()

    def test_file_edit_invalidates(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.BAD)
        self._run(tmp_path)
        (tmp_path / "mod.py").write_text("def f():\n    return 1\n")
        fresh = self._run(tmp_path)
        assert fresh.returncode == 0, fresh.stdout + fresh.stderr
        assert "(cached)" not in fresh.stderr
        assert "0 finding(s)" in fresh.stderr

    def test_no_cache_flag_bypasses(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.BAD)
        first = self._run(tmp_path, "--no-cache")
        second = self._run(tmp_path, "--no-cache")
        assert "(cached)" not in first.stderr + second.stderr
        assert not (tmp_path / ".analyze-cache.json").exists()

    def test_scoped_runs_are_not_cached(self, tmp_path):
        (tmp_path / "mod.py").write_text(self.BAD)
        scoped = self._run(tmp_path, "--checks", "broad-except")
        assert scoped.returncode == 1
        assert not (tmp_path / ".analyze-cache.json").exists()


# -- TJA024-027: the determinism layer ----------------------------------------

PKG_INIT = {
    f"{PKG}/__init__.py": "",
    f"{PKG}/fleet/__init__.py": "",
}


class TestUnseededRandomness:
    def test_fires_on_every_unseeded_construct_in_scope(self, tmp_path):
        findings = analyze_tree(tmp_path, {f"{PKG}/fleet/plan.py": """
            import random
            import uuid

            def expand(n):
                rng = random.Random()
                pick = random.choice(["a", "b"])
                token = uuid.uuid4()
                bucket = hash(pick) % n
                return rng, pick, token, bucket
        """}, only=["unseeded-randomness"])
        assert ids(findings) == ["TJA024"]
        msgs = "\n".join(f.message for f in findings)
        assert len(findings) == 4
        assert "random.Random() without a seed" in msgs
        assert "random.choice" in msgs
        assert "uuid.uuid4" in msgs
        assert "hash()" in msgs and "PYTHONHASHSEED" in msgs

    def test_import_aliases_resolve_to_the_source_tables(self, tmp_path):
        """``from random import choice`` / ``import numpy as np`` still
        hit the tables -- the scope contract is about the callee, not the
        spelling."""
        findings = analyze_tree(tmp_path, {f"{PKG}/fleet/plan.py": """
            import numpy as np
            from random import choice

            def expand():
                return choice(["a"]), np.random.rand()
        """}, only=["unseeded-randomness"])
        assert len(findings) == 2
        assert any("numpy" in f.message for f in findings)

    def test_quiet_on_seeded_rng_and_out_of_scope_code(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            f"{PKG}/fleet/plan.py": """
                import random
                from numpy.random import default_rng

                def expand(seed):
                    rng = random.Random(seed)
                    gen = default_rng(seed)
                    return rng.random() + float(gen.random())
            """,
            # Same module-level draw outside DETERMINISM_SCOPE: TJA024
            # does not fire (TJA025 would, if it reached a digest).
            f"{PKG}/workloads/gen.py": """
                import random

                def jitter():
                    return random.random()
            """,
        }, only=["unseeded-randomness"])
        assert findings == []


class TestDigestStability:
    def test_wall_clock_local_reaches_hasher_update(self, tmp_path):
        """The localproc-shaped bug: a time.time() value folded into a
        hashlib digest via a local assignment chain."""
        findings = analyze_tree(tmp_path, {f"{PKG}/runtime/footer.py": """
            import hashlib
            import time

            def footer(payload):
                stamp = time.time()
                h = hashlib.sha256()
                h.update(payload)
                h.update(str(stamp).encode())
                return h.hexdigest()
        """}, only=["digest-stability"])
        assert ids(findings) == ["TJA025"]
        (f,) = findings
        assert "'stamp'" in f.message and "reaches digest sink" in f.message

    def test_taint_crosses_project_function_returns(self, tmp_path):
        """Interprocedural: a helper returning wall clock taints its
        caller's sorted-keys json.dumps in another module."""
        findings = analyze_tree(tmp_path, {
            f"{PKG}/obs/stamp.py": """
                import time

                def stamp_ms():
                    return int(time.time() * 1000)
            """,
            f"{PKG}/obs/bundle.py": f"""
                import json

                from {PKG}.obs.stamp import stamp_ms

                def render(payload):
                    return json.dumps({{"at": stamp_ms(), "p": payload}},
                                      sort_keys=True)
            """,
        }, only=["digest-stability"])
        assert ids(findings) == ["TJA025"]
        (f,) = findings
        assert f.path == f"{PKG}/obs/bundle.py"
        assert "stamp_ms()" in f.message

    def test_unsorted_set_materialization_is_a_source(self, tmp_path):
        """sort_keys launders dict order, not list order: a list built
        from a set stays hash-randomization-dependent."""
        findings = analyze_tree(tmp_path, {f"{PKG}/obs/canon.py": """
            import json

            def canonical():
                pending = {"create", "delete", "patch"}
                return json.dumps({"verbs": list(pending)}, sort_keys=True)
        """}, only=["digest-stability"])
        assert ids(findings) == ["TJA025"]
        assert "unsorted set materialization" in findings[0].message

    def test_quiet_on_sorted_sets_and_deterministic_inputs(self, tmp_path):
        findings = analyze_tree(tmp_path, {f"{PKG}/obs/canon.py": """
            import hashlib
            import json

            def canonical(doc):
                pending = {"create", "delete", "patch"}
                body = json.dumps({"verbs": sorted(pending), "doc": doc},
                                  sort_keys=True)
                return hashlib.sha256(body.encode()).hexdigest()
        """}, only=["digest-stability"])
        assert findings == []


class TestIterationOrderHazard:
    def test_fires_on_set_loop_with_append(self, tmp_path):
        findings = analyze_tree(tmp_path, {f"{PKG}/fleet/expand.py": """
            def expand(verbs, out):
                for verb in set(verbs):
                    out.append(verb)
        """}, only=["iteration-order-hazard"])
        assert ids(findings) == ["TJA026"]
        assert "sorted(...)" in findings[0].message

    def test_module_level_frozenset_and_rng_draws(self, tmp_path):
        """Materializing (list()) doesn't launder order, and an RNG draw
        in the body is an order-dependent effect: same seed, different
        element gets the draw."""
        findings = analyze_tree(tmp_path, {f"{PKG}/fleet/stream.py": """
            VERBS = frozenset({"get", "list", "watch"})

            def stream(rng):
                draws = []
                for v in list(VERBS):
                    draws.append(rng.uniform(0.0, 1.0))
                return draws
        """}, only=["iteration-order-hazard"])
        assert ids(findings) == ["TJA026"]

    def test_quiet_on_sorted_loops_and_order_free_bodies(self, tmp_path):
        findings = analyze_tree(tmp_path, {
            f"{PKG}/fleet/expand.py": """
                def expand(verbs, out, seen):
                    for verb in sorted(set(verbs)):
                        out.append(verb)
                    for verb in set(verbs):
                        seen.add(verb)      # commutative: order-free
            """,
            # Out of scope: same hazard shape, not TJA026's business.
            f"{PKG}/workloads/gen.py": """
                def expand(verbs, out):
                    for verb in set(verbs):
                        out.append(verb)
            """,
        }, only=["iteration-order-hazard"])
        assert findings == []

    def test_injected_unsorted_verb_expansion_is_caught(self, tmp_path):
        """End to end on the real plan generator: turn fleet/chaos.py's
        verb expansion into a set loop and the pass must catch exactly
        the bug the chaos-smoke digest contract exists to prevent."""
        src = open(os.path.join(REPO_ROOT, PKG, "fleet", "chaos.py")).read()
        good = "        for verb in CHAOS_VERBS:\n"
        assert good in src, "chaos.py plan expansion changed; update fixture"
        broken = src.replace(good, "        for verb in set(CHAOS_VERBS):\n")
        findings = analyze_tree(
            tmp_path, {f"{PKG}/fleet/chaos.py": broken},
            only=["iteration-order-hazard"])
        assert ids(findings) == ["TJA026"]
        # The unmodified file is quiet -- the real tree holds the contract.
        assert analyze_tree(
            tmp_path, {f"{PKG}/fleet/chaos.py": src},
            only=["iteration-order-hazard"]) == []

    def test_facts_built_once_across_determinism_passes(self, tmp_path):
        """TJA024-026 share determinism.facts(); the ProjectContext memo
        means one build per run (same contract as the CFG and jit-boundary
        memos -- the 2s lint budget rests on it)."""
        from tools.analyze import determinism as det

        (tmp_path / "m.py").write_text(textwrap.dedent("""
            import json

            def canonical():
                pending = {"a", "b"}
                return json.dumps(sorted(pending), sort_keys=True)
        """))
        before = det.BUILD_COUNT
        run_checks([str(tmp_path)], root=str(tmp_path),
                   only=["unseeded-randomness", "digest-stability",
                         "iteration-order-hazard"])
        assert det.BUILD_COUNT - before == 1


class TestShardStateDiscipline:
    CONSTANTS = f"{PKG}/api/constants.py"

    def _tree(self, registry, counters_extra=""):
        return {
            f"{PKG}/obs/counters.py": """
                import itertools
                import threading

                _seq = itertools.count()
                _lock = threading.Lock()
                CACHE = {}
                TABLE = {"a": 1}

                def bump():
                    return next(_seq)

                def put(k, v):
                    with _lock:
                        CACHE[k] = v
            """ + counters_extra,
            self.CONSTANTS: registry,
        }

    FULL = f"""
        SHARD_STATE_REGISTRY = {{
            "api.constants.SHARD_STATE_REGISTRY": "constant",
            "obs.counters._seq": "shard_hostile",
            "obs.counters.CACHE": "lock_guarded_shared",
            "obs.counters.TABLE": "constant",
        }}
    """

    def test_quiet_when_every_singleton_is_classified(self, tmp_path):
        assert analyze_tree(tmp_path, self._tree(self.FULL),
                            only=["shard-state-discipline"]) == []

    def test_unclassified_singleton_is_an_error_at_its_definition(
            self, tmp_path):
        registry = self.FULL.replace(
            '            "obs.counters.CACHE": "lock_guarded_shared",\n', "")
        findings = analyze_tree(tmp_path, self._tree(registry),
                                only=["shard-state-discipline"])
        assert ids(findings) == ["TJA027"]
        (f,) = findings
        assert f.path == f"{PKG}/obs/counters.py"
        assert "'obs.counters.CACHE'" in f.message
        assert "not classified" in f.message

    def test_mutating_a_constant_classified_singleton_fires_at_the_write(
            self, tmp_path):
        findings = analyze_tree(tmp_path, self._tree(self.FULL, """

                def poke():
                    TABLE["b"] = 2
            """), only=["shard-state-discipline"])
        assert ids(findings) == ["TJA027"]
        (f,) = findings
        assert f.path == f"{PKG}/obs/counters.py"
        assert "classified constant" in f.message and "mutated" in f.message

    def test_stale_registry_entry_is_an_error_at_the_registry(self, tmp_path):
        registry = self.FULL.replace(
            '"obs.counters.TABLE": "constant",',
            '"obs.counters.TABLE": "constant",\n'
            '            "obs.counters.GONE": "shard_local",')
        findings = analyze_tree(tmp_path, self._tree(registry),
                                only=["shard-state-discipline"])
        assert ids(findings) == ["TJA027"]
        (f,) = findings
        assert f.path == self.CONSTANTS and "stale" in f.message

    def test_invalid_classification_is_an_error(self, tmp_path):
        registry = self.FULL.replace('"shard_hostile"', '"per_thread"')
        findings = analyze_tree(tmp_path, self._tree(registry),
                                only=["shard-state-discipline"])
        assert ids(findings) == ["TJA027"]
        assert "not a valid classification" in findings[0].message

    def test_lock_guarded_claim_without_lock_evidence_warns(self, tmp_path):
        files = self._tree(self.FULL.replace(
            '"obs.counters.TABLE": "constant",',
            '"obs.counters.TABLE": "constant",\n'
            '            "obs.bare.SHARED": "lock_guarded_shared",'))
        files[f"{PKG}/obs/bare.py"] = """
            SHARED = {}

            def put(k, v):
                SHARED[k] = v
        """
        findings = analyze_tree(tmp_path, files,
                                only=["shard-state-discipline"])
        assert ids(findings) == ["TJA027"]
        (f,) = findings
        assert f.severity == "warning"
        assert "neither its class nor its module declares a lock" in f.message

    def test_quiet_on_trees_without_the_registry_module(self, tmp_path):
        """A bare fixture tree is not this package: no constants.py means
        nothing to hold the inventory against."""
        assert analyze_tree(tmp_path, {"m.py": "STATE = {}\n"},
                            only=["shard-state-discipline"]) == []


class TestShardStateReport:
    def test_report_is_clean_and_schema_stable_on_the_repo(self):
        """``make shard-state-report``'s contract: exit 0, and the JSON
        document round-trips against the schema docs/STATIC_ANALYSIS.md
        declares (the worklist ROADMAP item 3 consumes)."""
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze",
             "--report", "shard-state"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert set(doc) == {"version", "generated_by", "package",
                            "registry_declared", "singletons",
                            "unclassified", "stale", "constant_violations"}
        assert doc["version"] == 1
        assert doc["package"] == PKG
        assert doc["registry_declared"] is True
        assert doc["unclassified"] == []
        assert doc["stale"] == []
        assert doc["constant_violations"] == []
        names = set()
        for s in doc["singletons"]:
            assert set(s) == {"name", "path", "line", "kind",
                              "classification", "lock_guarded", "writes",
                              "reads", "modules"}
            assert s["classification"] in {
                "constant", "shard_local", "lock_guarded_shared",
                "shard_hostile"}
            assert isinstance(s["line"], int) and s["line"] > 0
            for site in s["writes"] + s["reads"]:
                assert set(site) == {"path", "line", "via"}
            names.add(s["name"])
        # The singletons ROADMAP item 3 must split are all inventoried.
        assert {"obs.incident.INCIDENTS", "obs.goodput.GOODPUT",
                "obs.telemetry.TELEMETRY", "utils.events.EVENT_SEQ"} <= names
        # The last shard-hostile entry -- the bare event-sequence counter
        # -- was retired for the lock-guarded EventSeq (epoch, shard,
        # seq) API; the registry declares no hostile state any more.
        hostile = [s["name"] for s in doc["singletons"]
                   if s["classification"] == "shard_hostile"]
        assert hostile == []

    def test_report_exits_nonzero_on_unclassified_state(self, tmp_path):
        """The CI gate: new module-level mutable state without a registry
        entry fails ``make shard-state-report``."""
        for rel, src in {
            f"{PKG}/api/constants.py": "SHARD_STATE_REGISTRY = {\n"
            '    "api.constants.SHARD_STATE_REGISTRY": "constant",\n}\n',
            f"{PKG}/obs/rogue.py": "ROGUE = {}\n",
        }.items():
            path = tmp_path / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(src)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", PKG,
             "--report", "shard-state"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["unclassified"] == ["obs.rogue.ROGUE"]
        assert "1 unclassified" in proc.stderr


# -- TJA028-TJA032 thread-model concurrency passes ---------------------------

#: The five passes built on the thread-model layer, by check name.
CONCURRENCY = ["unguarded-shared-state", "check-then-act",
               "wait-predicate-discipline", "shutdown-ordering",
               "shard-boundary-discipline"]

#: Minimal registry so shard-state-backed passes see a declared tree.
BASE_REGISTRY = (
    "SHARD_STATE_REGISTRY = {\n"
    '    "api.constants.SHARD_STATE_REGISTRY": "constant",\n'
)


def registry(entries=""):
    return BASE_REGISTRY + entries + "}\n"


class TestUnguardedSharedState:
    """TJA028: MHP roles touching shared state with disjoint lock-sets."""

    def _tree(self, work_body):
        return {
            f"{PKG}/api/constants.py": registry(
                '    "obs.stream.EVENTS": "lock_guarded_shared",\n'),
            f"{PKG}/obs/stream.py": (
                "import threading\n"
                "\n"
                "EVENTS = {}\n"
                "_lock = threading.Lock()\n"
                "\n"
                "class Pool:\n"
                "    def __init__(self):\n"
                "        self._workers = []\n"
                "\n"
                "    def start(self, n):\n"
                "        for _ in range(n):\n"
                "            th = threading.Thread(target=self._work,\n"
                "                                  daemon=True)\n"
                "            th.start()\n"
                "            self._workers.append(th)\n"
                "\n"
                "    def _work(self):\n" + work_body),
        }

    def test_fires_on_unlocked_write_from_pool_role(self, tmp_path):
        fs = self._tree('        EVENTS["tick"] = 1\n')
        found = analyze_tree(tmp_path, fs, only=["unguarded-shared-state"])
        assert ids(found) == ["TJA028"]
        msg = found[0].message
        assert "obs.stream.EVENTS" in msg
        assert "may-happen-in-parallel" in msg
        assert "spawned" in msg   # the witness names the spawn site

    def test_quiet_when_both_sites_locked(self, tmp_path):
        fs = self._tree(
            '        with _lock:\n            EVENTS["tick"] = 1\n')
        assert analyze_tree(tmp_path, fs,
                            only=["unguarded-shared-state"]) == []

    def test_fires_on_shared_instance_attr(self, tmp_path):
        fs = {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/agg.py": (
                "import threading\n"
                "\n"
                "class Agg:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._bins = {}\n"
                "        self._workers = []\n"
                "\n"
                "    def start(self, n):\n"
                "        for _ in range(n):\n"
                "            th = threading.Thread(target=self._work,\n"
                "                                  daemon=True)\n"
                "            th.start()\n"
                "            self._workers.append(th)\n"
                "\n"
                "    def _work(self):\n"
                '        self._bins["x"] = 1\n'),
        }
        found = analyze_tree(tmp_path, fs, only=["unguarded-shared-state"])
        assert ids(found) == ["TJA028"]
        assert "instance attribute" in found[0].message
        assert "._bins" in found[0].message

    def test_waiver_on_the_line_suppresses(self, tmp_path):
        fs = self._tree(
            "        # analyzer: allow[unguarded-shared-state] "
            "GIL-atomic tick, last-writer-wins by design\n"
            '        EVENTS["tick"] = 1\n')
        assert analyze_tree(tmp_path, fs,
                            only=["unguarded-shared-state"]) == []


class TestCheckThenAct:
    """TJA029: test-then-mutate on MHP-shared state with no spanning lock."""

    def _tree(self, ensure_body):
        return {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/pending.py": (
                "import threading\n"
                "\n"
                "PENDING = {}\n"
                "_lock = threading.Lock()\n"
                "\n"
                "class Filler:\n"
                "    def __init__(self):\n"
                "        self._workers = []\n"
                "\n"
                "    def start(self, n):\n"
                "        for _ in range(n):\n"
                "            th = threading.Thread(target=self._fill,\n"
                "                                  daemon=True)\n"
                "            th.start()\n"
                "            self._workers.append(th)\n"
                "\n"
                "    def _fill(self):\n"
                '        ensure("job")\n'
                "\n"
                "def ensure(key):\n" + ensure_body),
        }

    def test_fires_on_unspanned_conditional(self, tmp_path):
        fs = self._tree(
            "    if key not in PENDING:\n"
            "        PENDING[key] = object()\n")
        found = analyze_tree(tmp_path, fs, only=["check-then-act"])
        assert ids(found) == ["TJA029"]
        assert "check-then-act race" in found[0].message
        assert "obs.pending.PENDING" in found[0].message

    def test_quiet_when_lock_spans_the_conditional(self, tmp_path):
        fs = self._tree(
            "    with _lock:\n"
            "        if key not in PENDING:\n"
            "            PENDING[key] = object()\n")
        assert analyze_tree(tmp_path, fs, only=["check-then-act"]) == []


class TestWaitDiscipline:
    """TJA030: Condition.wait in a predicate loop; bounded Event.wait."""

    def _cond_tree(self, take_body):
        return {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/chan.py": (
                "import threading\n"
                "\n"
                "class Chan:\n"
                "    def __init__(self):\n"
                "        self._cond = threading.Condition()\n"
                "        self._items = []\n"
                "\n"
                "    def take(self):\n"
                "        with self._cond:\n" + take_body),
        }

    def test_fires_on_if_guarded_condition_wait(self, tmp_path):
        fs = self._cond_tree(
            "            if not self._items:\n"
            "                self._cond.wait()\n"
            "            return self._items.pop()\n")
        found = analyze_tree(tmp_path, fs,
                             only=["wait-predicate-discipline"])
        assert ids(found) == ["TJA030"]
        assert found[0].severity == "error"
        assert "predicate loop" in found[0].message

    def test_quiet_on_while_guarded_condition_wait(self, tmp_path):
        fs = self._cond_tree(
            "            while not self._items:\n"
            "                self._cond.wait()\n"
            "            return self._items.pop()\n")
        assert analyze_tree(tmp_path, fs,
                            only=["wait-predicate-discipline"]) == []

    def _event_tree(self, wait_call):
        return {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/runner.py": (
                "import threading\n"
                "\n"
                "class Runner:\n"
                "    def __init__(self):\n"
                "        self._go = threading.Event()\n"
                "        self._stop = threading.Event()\n"
                "        self._thread = None\n"
                "\n"
                "    def start(self):\n"
                "        self._thread = threading.Thread(target=self._loop,\n"
                "                                        daemon=True)\n"
                "        self._thread.start()\n"
                "\n"
                "    def _loop(self):\n"
                "        while not self._stop.is_set():\n"
                f"            {wait_call}\n"
                "\n"
                "    def stop(self):\n"
                "        self._stop.set()\n"
                "        self._thread.join(timeout=2.0)\n"),
        }

    def test_warns_on_unbounded_event_wait_in_stoppable_role(self, tmp_path):
        fs = self._event_tree("self._go.wait()")
        found = analyze_tree(tmp_path, fs,
                             only=["wait-predicate-discipline"])
        assert ids(found) == ["TJA030"]
        assert found[0].severity == "warning"
        assert "Event.wait() without a timeout" in found[0].message

    def test_quiet_on_bounded_event_wait(self, tmp_path):
        fs = self._event_tree("self._go.wait(0.5)")
        assert analyze_tree(tmp_path, fs,
                            only=["wait-predicate-discipline"]) == []


class TestShutdownOrdering:
    """TJA031: retained threads joined by stop, never under a shared lock."""

    def _tree(self, stop_body):
        return {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/looper.py": (
                "import threading\n"
                "\n"
                "class Looper:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self._stop = threading.Event()\n"
                "        self._thread = None\n"
                "\n"
                "    def start(self):\n"
                "        self._thread = threading.Thread(target=self._loop,\n"
                "                                        daemon=True)\n"
                "        self._thread.start()\n"
                "\n"
                "    def _loop(self):\n"
                "        while not self._stop.wait(0.5):\n"
                "            with self._lock:\n"
                "                pass\n"
                "\n"
                "    def stop(self):\n" + stop_body),
        }

    def test_warns_when_no_stop_path_joins(self, tmp_path):
        fs = self._tree("        self._stop.set()\n")
        found = analyze_tree(tmp_path, fs, only=["shutdown-ordering"])
        assert ids(found) == ["TJA031"]
        assert found[0].severity == "warning"
        assert "no stop path" in found[0].message
        assert "self._thread" in found[0].message

    def test_quiet_when_stop_joins(self, tmp_path):
        fs = self._tree(
            "        self._stop.set()\n"
            "        self._thread.join(timeout=2.0)\n")
        assert analyze_tree(tmp_path, fs, only=["shutdown-ordering"]) == []

    def test_quiet_when_stop_joins_via_local_alias(self, tmp_path):
        """The obs-plane idiom: ``th = self._thread; th.join(...)``."""
        fs = self._tree(
            "        self._stop.set()\n"
            "        th = self._thread\n"
            "        if th is not None:\n"
            "            th.join(timeout=2.0)\n")
        assert analyze_tree(tmp_path, fs, only=["shutdown-ordering"]) == []

    def test_errors_on_join_under_shared_lock(self, tmp_path):
        fs = self._tree(
            "        self._stop.set()\n"
            "        with self._lock:\n"
            "            self._thread.join(timeout=2.0)\n")
        found = analyze_tree(tmp_path, fs, only=["shutdown-ordering"])
        assert ids(found) == ["TJA031"]
        assert found[0].severity == "error"
        assert "while holding" in found[0].message


class TestShardBoundaryDiscipline:
    """TJA032: registry classifications hold against the thread model."""

    def _tree(self, put_body, classification="lock_guarded_shared"):
        return {
            f"{PKG}/api/constants.py": registry(
                f'    "obs.state.CACHE": "{classification}",\n'),
            f"{PKG}/obs/state.py": (
                "import threading\n"
                "\n"
                "CACHE = {}\n"
                "_lock = threading.Lock()\n"
                "\n"
                "def put(k, v):\n" + put_body),
        }

    def test_fires_on_unlocked_write_to_lock_guarded(self, tmp_path):
        fs = self._tree("    CACHE[k] = v\n")
        found = analyze_tree(tmp_path, fs,
                             only=["shard-boundary-discipline"])
        assert ids(found) == ["TJA032"]
        assert "declared lock_guarded_shared" in found[0].message

    def test_quiet_when_write_is_locked(self, tmp_path):
        fs = self._tree("    with _lock:\n        CACHE[k] = v\n")
        assert analyze_tree(tmp_path, fs,
                            only=["shard-boundary-discipline"]) == []

    def test_fires_on_undeclared_global_rebind_in_role(self, tmp_path):
        fs = {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/flip.py": (
                "import threading\n"
                "\n"
                "MODES = {}\n"
                "\n"
                "class Flipper:\n"
                "    def __init__(self):\n"
                "        self._workers = []\n"
                "\n"
                "    def start(self, n):\n"
                "        for _ in range(n):\n"
                "            th = threading.Thread(target=self._work,\n"
                "                                  daemon=True)\n"
                "            th.start()\n"
                "            self._workers.append(th)\n"
                "\n"
                "    def _work(self):\n"
                "        reset()\n"
                "\n"
                "def reset():\n"
                "    global MODES\n"
                "    MODES = {}\n"),
        }
        found = analyze_tree(tmp_path, fs,
                             only=["shard-boundary-discipline"])
        assert ids(found) == ["TJA032"]
        assert "`global MODES` rebind" in found[0].message
        assert "not classified" in found[0].message


class TestThreadModelLayer:
    """The model itself: built once per run, serves every pass."""

    def test_model_built_once_across_all_five_passes(self, tmp_path):
        from tools.analyze import threadmodel as tmod
        for rel, src in {
            f"{PKG}/api/constants.py": registry(),
            f"{PKG}/obs/w.py": (
                "import threading\n\n"
                "D = {}\n\n"
                "def go():\n"
                "    th = threading.Thread(target=work, daemon=True)\n"
                "    th.start()\n\n"
                "def work():\n"
                "    D['k'] = 1\n"),
        }.items():
            path = tmp_path / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(src)
        before = tmod.BUILD_COUNT
        run_checks([str(tmp_path)], root=str(tmp_path), only=CONCURRENCY)
        assert tmod.BUILD_COUNT - before == 1

    def test_lock_deletion_trips_tja028_and_tja032(self, tmp_path):
        """End-to-end proof on the *real* event-sequencer source: delete
        the lock acquisitions and the tree stops being certifiable --
        TJA032 (the lock_guarded claim breaks) plus TJA028 (the pool-role
        write races itself)."""
        events_src = open(
            os.path.join(REPO_ROOT, PKG, "utils", "events.py")).read()
        stream = """\
import threading

EVENTS = {}
_lock = threading.Lock()

class Pool:
    def __init__(self):
        self._workers = []

    def start(self, n):
        for _ in range(n):
            th = threading.Thread(target=self._work, daemon=True)
            th.start()
            self._workers.append(th)

    def _work(self):
        with _lock:
            EVENTS["tick"] = 1
"""
        reg = registry(
            '    "utils.events.EVENT_SEQ": "lock_guarded_shared",\n'
            '    "obs.stream.EVENTS": "lock_guarded_shared",\n')
        tree = {
            f"{PKG}/api/constants.py": reg,
            f"{PKG}/utils/events.py": events_src,
            f"{PKG}/obs/stream.py": stream,
        }
        for rel, src in tree.items():
            path = tmp_path / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(src)
        clean = run_checks([str(tmp_path)], root=str(tmp_path),
                           only=CONCURRENCY)
        assert clean == [], [f.message for f in clean]

        # Delete every lock acquisition while keeping the AST shape.
        broken_events = events_src.replace("with self._lock:", "if True:") \
                                  .replace("with self._created_lock:",
                                           "if True:")
        broken_stream = stream.replace("with _lock:", "if True:")
        (tmp_path / PKG / "utils" / "events.py").write_text(broken_events)
        (tmp_path / PKG / "obs" / "stream.py").write_text(broken_stream)
        found = run_checks([str(tmp_path)], root=str(tmp_path),
                           only=CONCURRENCY)
        assert {"TJA028", "TJA032"} <= set(ids(found)), \
            [f"{f.check_id} {f.message}" for f in found]


class TestThreadModelReport:
    """``--report thread-model``: the CI artifact next to shard_state.json."""

    def test_real_tree_report_schema_and_clean_exit(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze",
             "--report", "thread-model"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert set(doc) == {"version", "generated_by", "package", "roles",
                            "mhp", "singletons", "violations"}
        assert doc["version"] == 1
        assert doc["package"] == PKG
        # All five concurrency passes are clean (waivers documented in
        # docs/STATIC_ANALYSIS.md).
        assert doc["violations"] == {"TJA028": 0, "TJA029": 0, "TJA030": 0,
                                     "TJA031": 0, "TJA032": 0}
        names = [r["name"] for r in doc["roles"]]
        assert "main" in names
        assert any(n.startswith("_worker@controller.controller:")
                   for n in names)
        assert any(n.startswith("_pump_waiting@client.workqueue:")
                   for n in names)
        for r in doc["roles"]:
            assert {"name", "kind", "spawn", "target", "entries", "daemon",
                    "multi", "domain", "owner", "owner_class", "thread_attr",
                    "closure_size", "closure"} <= set(r)
            assert r["closure_size"] == len(r["closure"])
        # The worker pool is multi-instance: it must MHP with itself.
        worker = next(n for n in names
                      if n.startswith("_worker@controller.controller:"))
        assert worker in doc["mhp"][worker]
        # MHP is symmetric.
        for a, partners in doc["mhp"].items():
            for b in partners:
                assert a in doc["mhp"][b], (a, b)
        # Per-singleton access evidence carries roles + lock-sets.
        by_name = {s["name"]: s for s in doc["singletons"]}
        seq = by_name["utils.events.EVENT_SEQ"]
        assert seq["classification"] == "lock_guarded_shared"
        for site in seq["evidence"]:
            assert {"path", "line", "via", "write", "roles",
                    "locks"} == set(site)

    def test_report_exits_nonzero_on_broken_claim(self, tmp_path):
        for rel, src in {
            f"{PKG}/api/constants.py": registry(
                '    "obs.state.CACHE": "lock_guarded_shared",\n'),
            f"{PKG}/obs/state.py": (
                "CACHE = {}\n\n"
                "def put(k, v):\n"
                "    CACHE[k] = v\n"),
        }.items():
            path = tmp_path / rel
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(src)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", PKG,
             "--report", "thread-model"],
            cwd=tmp_path, capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["violations"]["TJA032"] >= 1
        assert "unwaived concurrency violation" in proc.stderr
