"""tools/analyze: one positive (fires on seeded-bad code) and one negative
(quiet on good code) fixture per check, the baseline/waiver machinery, output
formats, and the tier-1 gate -- zero non-baselined findings on the real tree.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.analyze import runner
from tools.analyze.findings import Finding, fingerprint_all
from tools.analyze.runner import (
    apply_baseline,
    format_findings,
    load_baseline,
    run_checks,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "trainingjob_operator_tpu"


def analyze(tmp_path, rel, source, only=None):
    """Write ``source`` at ``rel`` under tmp_path and run the checks."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return run_checks([str(path)], root=str(tmp_path), only=only)


def ids(findings):
    return sorted({f.check_id for f in findings})


# -- TJA001 py-compat --------------------------------------------------------

class TestPyCompat:
    def test_fires_on_reintroduced_metrics_bug(self, tmp_path):
        """Re-introduce the exact seed bug: utils/metrics.py:147's escaped
        le-label inside an f-string expression."""
        src = open(os.path.join(REPO_ROOT, PKG, "utils", "metrics.py")).read()
        good = (
            '                # Escaped label hoisted out of the f-string: a backslash\n'
            '                # inside an f-string expression is a SyntaxError before 3.12.\n'
            "                le_label = f'le=\"{ub}\"'\n"
            '                lines.append(f"{base}_bucket{lbl(le_label)} {cum}")\n'
        )
        bad = (
            '                lines.append(f\'{base}_bucket{lbl(f"le=\\"{ub}\\"")} {cum}\')\n'
        )
        assert good in src, "metrics.py render loop changed; update fixture"
        broken = src.replace(good, bad)
        findings = analyze(tmp_path, "utils/metrics.py", broken,
                           only=["py-compat"])
        assert ids(findings) == ["TJA001"]
        # On a 3.10/3.11 interpreter the parse gate reports the SyntaxError;
        # the token scan must give the same verdict on 3.12+.
        assert any("3.10" in f.message or "f-string" in f.message
                   for f in findings)

    def test_fires_on_plain_syntax_error(self, tmp_path):
        findings = analyze(tmp_path, "m.py", "def broken(:\n    pass\n",
                           only=["py-compat"])
        assert ids(findings) == ["TJA001"]

    def test_quiet_on_hoisted_fix_and_current_tree_file(self, tmp_path):
        fixed = '''
        def render(lbl, ub, cum):
            le_label = f'le="{ub}"'
            return f"bucket{lbl(le_label)} {cum}"
        '''
        assert analyze(tmp_path, "m.py", fixed, only=["py-compat"]) == []
        real = open(os.path.join(REPO_ROOT, PKG, "utils", "metrics.py")).read()
        assert analyze(tmp_path, "utils/metrics.py", real,
                       only=["py-compat"]) == []

    def test_backslash_at_depth_zero_is_fine(self, tmp_path):
        src = 'X = f"a\\n{1 + 2}\\t"\n'
        assert analyze(tmp_path, "m.py", src, only=["py-compat"]) == []


# -- TJA002 lock-discipline --------------------------------------------------

BAD_LOCK = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}
            self.count = 0

        def put(self, k, v):
            with self._lock:
                self._items[k] = v
                self.count += 1

        def racy_clear(self):
            self._items.clear()
            self.count = 0
"""

GOOD_LOCK = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def put(self, k, v):
            with self._lock:
                self._items[k] = v

        def clear(self):
            with self._lock:
                self._items.clear()

        def _drop_locked(self, k):
            # caller-holds-lock helper convention: exempt
            self._items.pop(k, None)
"""


class TestLockDiscipline:
    def test_fires_on_unguarded_mutation(self, tmp_path):
        findings = analyze(tmp_path, "m.py", BAD_LOCK,
                           only=["lock-discipline"])
        assert ids(findings) == ["TJA002"]
        assert {f.line for f in findings} == {16, 17}
        assert any("racy_clear" in f.message and "_items" in f.message
                   for f in findings)

    def test_quiet_on_disciplined_class(self, tmp_path):
        assert analyze(tmp_path, "m.py", GOOD_LOCK,
                       only=["lock-discipline"]) == []

    def test_init_is_exempt_and_lockless_class_ignored(self, tmp_path):
        src = """
        class Plain:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)
        """
        assert analyze(tmp_path, "m.py", src, only=["lock-discipline"]) == []

    def test_condition_counts_as_lock(self, tmp_path):
        src = """
        import threading

        class Q:
            def __init__(self):
                self._cond = threading.Condition()
                self._queue = []

            def add(self, x):
                with self._cond:
                    self._queue.append(x)

            def racy_drain(self):
                self._queue.clear()
        """
        findings = analyze(tmp_path, "m.py", src, only=["lock-discipline"])
        assert ids(findings) == ["TJA002"]

    def test_quiet_on_real_workqueue_and_expectations(self, tmp_path):
        for rel in ("client/workqueue.py", "client/expectations.py",
                    "client/informers.py", "utils/metrics.py"):
            src = open(os.path.join(REPO_ROOT, PKG, *rel.split("/"))).read()
            assert analyze(tmp_path, rel, src,
                           only=["lock-discipline"]) == [], rel


# -- TJA003 reconcile-purity -------------------------------------------------

BAD_PURITY = """
    import time
    import requests

    def sync(key, queue, thread):
        time.sleep(1.0)
        requests.get("http://apiserver/jobs")
        queue.get()
        thread.join()
"""


class TestReconcilePurity:
    def test_fires_inside_controller_dir(self, tmp_path):
        findings = analyze(tmp_path, "controller/sync.py", BAD_PURITY,
                           only=["reconcile-purity"])
        assert ids(findings) == ["TJA003"]
        assert len(findings) == 4

    def test_out_of_scope_dir_is_quiet(self, tmp_path):
        assert analyze(tmp_path, "runtime/sync.py", BAD_PURITY,
                       only=["reconcile-purity"]) == []

    def test_bounded_waits_and_local_names_are_quiet(self, tmp_path):
        src = """
        def sync(key, queue, stop):
            item, _ = queue.get(timeout=0.5)
            stop.wait(1.0)
            # a k8s resources dict named "requests" is not the module
            requests = {}
            requests.setdefault("cpu", "1")
        """
        assert analyze(tmp_path, "controller/sync.py", src,
                       only=["reconcile-purity"]) == []

    def test_from_import_sleep_detected(self, tmp_path):
        src = """
        from time import sleep

        def sync(key):
            sleep(0.1)
        """
        findings = analyze(tmp_path, "controller/sync.py", src,
                           only=["reconcile-purity"])
        assert ids(findings) == ["TJA003"]

    def test_waiver_suppresses(self, tmp_path):
        src = """
        def run(stop):
            # analyzer: allow[reconcile-purity]: parks the caller thread
            stop.wait()
        """
        assert analyze(tmp_path, "controller/run.py", src,
                       only=["reconcile-purity"]) == []


# -- TJA004 broad-except -----------------------------------------------------

class TestBroadExcept:
    def test_fires_on_silent_swallow(self, tmp_path):
        src = """
        def f():
            try:
                g()
            except Exception:
                pass

        def h():
            try:
                g()
            except:
                return None
        """
        findings = analyze(tmp_path, "m.py", src, only=["broad-except"])
        assert ids(findings) == ["TJA004"]
        assert len(findings) == 2
        assert any("bare except" in f.message for f in findings)

    def test_logging_reraise_and_narrow_are_quiet(self, tmp_path):
        src = """
        import logging

        log = logging.getLogger(__name__)

        def logged():
            try:
                g()
            except Exception:
                log.exception("g failed")

        def reraised():
            try:
                g()
            except Exception:
                cleanup()
                raise

        def narrow():
            try:
                g()
            except (KeyError, ValueError):
                pass
        """
        assert analyze(tmp_path, "m.py", src, only=["broad-except"]) == []

    def test_waiver_comment_suppresses(self, tmp_path):
        src = """
        def f():
            try:
                g()
            # analyzer: allow[broad-except]: best-effort cleanup, failure
            # here must never mask the original exception being handled.
            except Exception:
                pass
        """
        assert analyze(tmp_path, "m.py", src, only=["broad-except"]) == []

    def test_forwarding_the_bound_exception_is_accountable(self, tmp_path):
        src = """
        def forwarded(q):
            try:
                g()
            except Exception as exc:
                q.put(exc)          # surfaced to the consumer: fine

        def bound_but_dropped():
            try:
                g()
            except Exception as exc:
                return None         # bound name unused: still swallowing
        """
        findings = analyze(tmp_path, "m.py", src, only=["broad-except"])
        assert len(findings) == 1
        assert findings[0].line == 11


# -- TJA005 constant-drift ---------------------------------------------------

FAKE_CONSTANTS = """
    JOB_NAME_LABEL = "TrainingJobName"
    TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
    PRIORITY_LABEL = "priority"
"""


class TestConstantDrift:
    def _write_constants(self, tmp_path):
        p = tmp_path / PKG / "api" / "constants.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(FAKE_CONSTANTS))

    def test_fires_on_duplicated_and_undefined_contract_strings(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        def build(pod):
            pod.labels["TrainingJobName"] = pod.name      # dup of constant
            pod.env["TRAININGJOB_NEW_KNOB"] = "1"          # undefined contract
        """
        findings = analyze(tmp_path, f"{PKG}/controller/pod.py", src,
                           only=["constant-drift"])
        assert ids(findings) == ["TJA005"]
        msgs = " | ".join(f.message for f in findings)
        assert "JOB_NAME_LABEL" in msgs
        assert "TRAININGJOB_NEW_KNOB" in msgs

    def test_quiet_on_constant_usage_and_generic_words(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def build(pod):
            pod.labels[constants.JOB_NAME_LABEL] = pod.name
            pod.labels["priority"] = "high"   # generic word: not contract-shaped
        """
        assert analyze(tmp_path, f"{PKG}/controller/pod.py", src,
                       only=["constant-drift"]) == []

    def test_docstrings_and_out_of_scope_dirs_are_quiet(self, tmp_path):
        self._write_constants(tmp_path)
        src = '''
        """Mentions TPU_WORKER_ID and TrainingJobName in prose."""

        def f():
            """Also TRAININGJOB_UNDEFINED_IN_DOCSTRING."""
        '''
        assert analyze(tmp_path, f"{PKG}/controller/doc.py", src,
                       only=["constant-drift"]) == []
        bad = 'X = "TrainingJobName"\n'
        # models/ is outside the constant-drift scope
        assert analyze(tmp_path, f"{PKG}/models/m.py", bad,
                       only=["constant-drift"]) == []


# -- TJA006 tracer-safety ----------------------------------------------------

BAD_JIT = """
    import jax

    @jax.jit
    def step(x, lr):
        if lr > 0.5:
            x = x * lr
        while x > 0:
            x = x - 1
        loss = float(x)
        print("loss", loss)
        return x.item()
"""

GOOD_JIT = """
    from functools import partial
    import jax
    from jax import lax

    @partial(jax.jit, static_argnames=("n",))
    def step(x, n, mask=None):
        if n > 2:              # static: fine
            x = x + n
        if mask is None:       # concrete at trace time: fine
            mask = x * 0
        return lax.cond(x.sum() > 0, lambda v: v, lambda v: -v, x)

    def helper(x):             # not traced at all
        if x > 0:
            print(x)
        return float(x)
"""


class TestTracerSafety:
    def test_fires_on_all_three_bug_classes(self, tmp_path):
        findings = analyze(tmp_path, "models/step.py", BAD_JIT,
                           only=["tracer-safety"])
        assert ids(findings) == ["TJA006"]
        msgs = " | ".join(f.message for f in findings)
        assert "Python 'if' on traced" in msgs
        assert "Python 'while' on traced" in msgs
        assert "float()" in msgs
        assert ".item()" in msgs
        assert "jax.debug.print" in msgs

    def test_statics_none_checks_and_untraced_are_quiet(self, tmp_path):
        assert analyze(tmp_path, "models/step.py", GOOD_JIT,
                       only=["tracer-safety"]) == []

    def test_assignment_wrapped_function_detected(self, tmp_path):
        src = """
        import jax

        def body(q):
            if q > 0:
                q = -q
            return q

        wrapped = jax.jit(body)
        """
        findings = analyze(tmp_path, "ops/m.py", src, only=["tracer-safety"])
        assert ids(findings) == ["TJA006"]

    def test_out_of_scope_dir_is_quiet(self, tmp_path):
        assert analyze(tmp_path, "controller/m.py", BAD_JIT,
                       only=["tracer-safety"]) == []


# -- TJA007 event-reason-drift -----------------------------------------------

FAKE_REASON_CONSTANTS = """
    OK_REASON = "JobOk"
    UNREGISTERED_REASON = "JobUnregistered"
    EVENT_REASONS = frozenset((
        OK_REASON,
    ))
"""


class TestEventReasonDrift:
    def _write_constants(self, tmp_path):
        p = tmp_path / PKG / "api" / "constants.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(FAKE_REASON_CONSTANTS))

    def test_fires_on_adhoc_and_unregistered_reasons(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def f(recorder, job):
            recorder.event(job, "Normal", "JobOkk", "typo'd literal")
            recorder.event(job, "Normal", constants.UNREGISTERED_REASON, "m")
        """
        findings = analyze(tmp_path, f"{PKG}/controller/x.py", src,
                           only=["event-reason-drift"])
        assert ids(findings) == ["TJA007"]
        msgs = " | ".join(f.message for f in findings)
        assert "JobOkk" in msgs
        assert "UNREGISTERED_REASON" in msgs

    def test_quiet_on_registered_dynamic_and_non_recorder(self, tmp_path):
        self._write_constants(tmp_path)
        src = """
        from trainingjob_operator_tpu.api import constants

        def f(recorder, bus, job, reason):
            recorder.event(job, "Normal", constants.OK_REASON, "m")
            recorder.event(job, "Normal", "JobOk", "registry value literal")
            recorder.event(job, "Normal", reason, "dynamic: skipped")
            bus.event(job, "Normal", "NotARecorder", "receiver out of scope")
        """
        assert analyze(tmp_path, f"{PKG}/controller/x.py", src,
                       only=["event-reason-drift"]) == []

    def test_real_tree_call_sites_are_clean(self, tmp_path):
        for rel in ("controller/control.py", "controller/pod.py",
                    "controller/controller.py"):
            src = open(os.path.join(REPO_ROOT, PKG, *rel.split("/"))).read()
            assert analyze(tmp_path, f"{PKG}/{rel}", src,
                           only=["event-reason-drift"]) == [], rel


# -- TJA008 orphaned-thread --------------------------------------------------

class TestOrphanedThread:
    def test_fires_on_leaked_and_unbound_threads(self, tmp_path):
        src = """
        import threading

        def leak(work):
            t = threading.Thread(target=work)
            t.start()

        def unbound(work):
            threading.Thread(target=work).start()
        """
        findings = analyze(tmp_path, "m.py", src, only=["orphaned-thread"])
        assert ids(findings) == ["TJA008"]
        assert len(findings) == 2
        msgs = " | ".join(f.message for f in findings)
        assert "'t'" in msgs
        assert "never bound" in msgs

    def test_quiet_on_daemon_join_sweep_and_late_daemon(self, tmp_path):
        src = """
        import threading

        def daemonized(work):
            threading.Thread(target=work, daemon=True).start()

        def joined(work):
            t = threading.Thread(target=work)
            t.start()
            t.join(1)

        def swept(work):
            threads = [threading.Thread(target=work) for _ in range(3)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()

        def appended(work):
            threads = []
            for _ in range(2):
                threads.append(threading.Thread(target=work))
            [t.join() for t in threads]

        class C:
            def start(self, work):
                self._th = threading.Thread(target=work)
                self._th.daemon = True
                self._th.start()
        """
        assert analyze(tmp_path, "m.py", src, only=["orphaned-thread"]) == []

    def test_explicit_daemon_false_still_needs_join(self, tmp_path):
        src = """
        import threading

        def f(work):
            t = threading.Thread(target=work, daemon=False)
            t.start()
        """
        findings = analyze(tmp_path, "m.py", src, only=["orphaned-thread"])
        assert ids(findings) == ["TJA008"]

    def test_waiver_suppresses(self, tmp_path):
        src = """
        import threading

        def f(work):
            # analyzer: allow[orphaned-thread]: joined by the caller
            t = threading.Thread(target=work)
            return t
        """
        assert analyze(tmp_path, "m.py", src, only=["orphaned-thread"]) == []


# -- TJA009 status-write-discipline ------------------------------------------

class TestStatusWriteDiscipline:
    def test_fires_on_direct_phase_and_condition_mutation(self, tmp_path):
        src = """
        def rogue(job, cond):
            job.status.phase = "Failed"
            job.status.conditions = []
            job.status.conditions.append(cond)
            fresh_job.status.phase = "Running"
        """
        findings = analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                           src, only=["status-write-discipline"])
        assert ids(findings) == ["TJA009"]
        assert len(findings) == 4
        assert all("update_job_conditions" in f.message for f in findings)

    def test_quiet_on_pod_status_and_reads(self, tmp_path):
        src = """
        def fine(job, pod, node):
            pod.status.phase = "Running"       # pod status: unguarded API
            node.status.conditions = []
            if job.status.phase == "Running":  # read, not write
                return job.status.conditions[-1]
            job.status.restart_replica_name = ""  # not a guarded field
        """
        assert analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                       src, only=["status-write-discipline"]) == []

    def test_status_machine_helpers_are_exempt(self, tmp_path):
        src = """
        def set_condition(status, new_cond):
            status.conditions.append(new_cond)

        def update_job_conditions(job, ctype):
            job.status.phase = ctype

        def rogue(job):
            job.status.phase = "X"
        """
        findings = analyze(
            tmp_path, "trainingjob_operator_tpu/controller/status.py", src,
            only=["status-write-discipline"])
        assert len(findings) == 1
        assert findings[0].line == 9

    def test_out_of_package_code_is_not_scoped(self, tmp_path):
        src = """
        def fixture(job):
            job.status.phase = "Succeeded"
        """
        assert analyze(tmp_path, "tests/m.py", src,
                       only=["status-write-discipline"]) == []

    def test_waiver_suppresses(self, tmp_path):
        src = """
        def migrate(job):
            # analyzer: allow[status-write-discipline]: one-shot migration
            job.status.phase = "Failed"
        """
        assert analyze(tmp_path, "trainingjob_operator_tpu/controller/m.py",
                       src, only=["status-write-discipline"]) == []


# -- runner: baseline, waivers, formats, CLI ---------------------------------

class TestRunnerMachinery:
    def test_baseline_roundtrip_suppresses_old_reports_new(self, tmp_path):
        bad = tmp_path / "m.py"
        bad.write_text("def f():\n    try:\n        g()\n"
                       "    except Exception:\n        pass\n")
        first = run_checks([str(bad)], root=str(tmp_path))
        assert len(first) == 1
        baseline_path = tmp_path / "baseline.json"
        assert write_baseline(str(baseline_path), first) == 1
        fresh, suppressed = apply_baseline(
            run_checks([str(bad)], root=str(tmp_path)),
            load_baseline(str(baseline_path)))
        assert fresh == [] and suppressed == 1
        # A *new* finding elsewhere in the file still surfaces -- and the
        # old fingerprint survives the line shift above it.
        bad.write_text("def z():\n    try:\n        g()\n"
                       "    except Exception:\n        return 1\n\n"
                       + bad.read_text())
        fresh, suppressed = apply_baseline(
            run_checks([str(bad)], root=str(tmp_path)),
            load_baseline(str(baseline_path)))
        assert len(fresh) == 1 and suppressed == 1

    def test_allow_star_waives_any_check(self, tmp_path):
        src = """
        import time

        def sync(key):
            # analyzer: allow[*]: fixture
            time.sleep(1)
        """
        assert analyze(tmp_path, "controller/m.py", src) == []

    def test_unknown_check_name_raises(self, tmp_path):
        (tmp_path / "m.py").write_text("x = 1\n")
        with pytest.raises(ValueError, match="unknown check"):
            run_checks([str(tmp_path)], root=str(tmp_path), only=["nope"])

    def test_formats(self):
        f = Finding("TJA001", "py-compat", "a/b.py", 3, 4, "error", "boom")
        text = format_findings([f], "text")
        assert text == "a/b.py:3:4: TJA001[py-compat] error: boom\n"
        gh = format_findings([f], "github")
        assert gh.startswith("::error file=a/b.py,line=3,col=4,")
        js = json.loads(format_findings([f], "json"))
        assert js[0]["check_id"] == "TJA001" and js[0]["line"] == 3

    def test_fingerprints_disambiguate_identical_messages(self):
        a = Finding("TJA004", "broad-except", "m.py", 3, 0, "warning", "same")
        b = Finding("TJA004", "broad-except", "m.py", 9, 0, "warning", "same")
        assert len(fingerprint_all([a, b])) == 2

    def test_all_nine_checks_registered(self):
        runner._load_checks()
        assert {cid for cid, _fn in runner.REGISTRY.values()} == {
            "TJA001", "TJA002", "TJA003", "TJA004", "TJA005", "TJA006",
            "TJA007", "TJA008", "TJA009"}


# -- the tier-1 gate ---------------------------------------------------------

class TestRepoIsClean:
    def test_zero_non_baselined_findings_on_the_repo(self):
        """The contract ``make lint`` enforces: the analyzer exits 0 on the
        tree, with every finding either fixed, waived, or baselined."""
        findings = run_checks([os.path.join(REPO_ROOT, PKG)], root=REPO_ROOT)
        if os.path.exists(runner.DEFAULT_BASELINE):
            findings, _ = apply_baseline(
                findings, load_baseline(runner.DEFAULT_BASELINE))
        assert findings == [], format_findings(findings, "text")

    def test_cli_exits_zero_on_repo(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", PKG, "--format=github"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_exits_nonzero_on_seeded_bug(self, tmp_path):
        (tmp_path / "bad.py").write_text(
            "def f():\n    try:\n        g()\n"
            "    except Exception:\n        pass\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analyze", str(tmp_path),
             "--no-baseline"],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert "TJA004" in proc.stdout
