"""Client plumbing tests: tracker CRUD/watch/graceful-delete, informers,
workqueue semantics, expectations."""

import threading
import time

import pytest

from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.client import (
    AlreadyExistsError,
    Clientset,
    ConflictError,
    ControllerExpectations,
    InformerFactory,
    NotFoundError,
    ObjectTracker,
    RateLimitingQueue,
)
from trainingjob_operator_tpu.client.expectations import pods_key
from trainingjob_operator_tpu.client.tracker import (
    ADDED,
    DELETED,
    MODIFIED,
    meta_namespace_key,
    split_meta_namespace_key,
)
from trainingjob_operator_tpu.core.objects import ObjectMeta, Pod


def make_pod(name, namespace="default", labels=None) -> Pod:
    return Pod(metadata=ObjectMeta(name=name, namespace=namespace,
                                   labels=dict(labels or {})))


class TestTracker:
    def test_create_get_roundtrip_and_isolation(self):
        t = ObjectTracker()
        pod = make_pod("p1")
        created = t.create(pod)
        assert created.metadata.uid
        assert created.metadata.resource_version == 1
        # Mutating the returned object must not touch the store.
        created.metadata.labels["x"] = "y"
        assert t.get("Pod", "default", "p1").metadata.labels == {}

    def test_create_duplicate(self):
        t = ObjectTracker()
        t.create(make_pod("p1"))
        with pytest.raises(AlreadyExistsError):
            t.create(make_pod("p1"))

    def test_get_missing(self):
        t = ObjectTracker()
        with pytest.raises(NotFoundError):
            t.get("Pod", "default", "nope")

    def test_list_namespace_and_labels(self):
        t = ObjectTracker()
        t.create(make_pod("a", "ns1", {"role": "trainer"}))
        t.create(make_pod("b", "ns1", {"role": "ps"}))
        t.create(make_pod("c", "ns2", {"role": "trainer"}))
        assert len(t.list("Pod")) == 3
        assert len(t.list("Pod", "ns1")) == 2
        assert [p.name for p in t.list("Pod", "ns1", {"role": "trainer"})] == ["a"]

    def test_update_conflict_on_stale_version(self):
        t = ObjectTracker()
        t.create(make_pod("p"))
        fresh = t.get("Pod", "default", "p")
        stale = t.get("Pod", "default", "p")
        fresh.metadata.labels["a"] = "1"
        t.update(fresh)
        stale.metadata.labels["b"] = "2"
        with pytest.raises(ConflictError):
            t.update(stale)

    def test_watch_events(self):
        t = ObjectTracker()
        events = []
        t.watch("Pod", lambda e: events.append((e.type, e.obj.name)))
        t.create(make_pod("p"))
        pod = t.get("Pod", "default", "p")
        t.update(pod)
        t.delete("Pod", "default", "p")
        assert events == [(ADDED, "p"), (MODIFIED, "p"), (DELETED, "p")]

    def test_graceful_delete_with_finalizer(self):
        t = ObjectTracker()
        seen = []
        t.register_finalizer("Pod", lambda obj: seen.append(obj.name))
        t.create(make_pod("p"))
        t.delete("Pod", "default", "p", grace_period=30)
        # Still present, marked terminating.
        pod = t.get("Pod", "default", "p")
        assert pod.metadata.deletion_timestamp is not None
        assert seen == ["p"]
        t.finalize_delete("Pod", "default", "p")
        with pytest.raises(NotFoundError):
            t.get("Pod", "default", "p")

    def test_force_delete_bypasses_finalizer(self):
        # Reference: forceDeletePod grace=0 (pod.go:469-481).
        t = ObjectTracker()
        t.register_finalizer("Pod", lambda obj: None)
        t.create(make_pod("p"))
        t.delete("Pod", "default", "p", grace_period=0)
        with pytest.raises(NotFoundError):
            t.get("Pod", "default", "p")

    def test_keys(self):
        pod = make_pod("n", "ns")
        assert meta_namespace_key(pod) == "ns/n"
        assert split_meta_namespace_key("ns/n") == ("ns", "n")
        assert split_meta_namespace_key("n") == ("", "n")

    def test_generate_name(self):
        t = ObjectTracker()
        pod = Pod(metadata=ObjectMeta(name="", generate_name="job-worker-",
                                      namespace="default"))
        created = t.create(pod)
        assert created.name.startswith("job-worker-")


class TestClientset:
    def test_typed_clients_share_tracker(self):
        cs = Clientset()
        cs.pods.create(make_pod("p"))
        assert cs.tracker.count("Pod") == 1
        job = TPUTrainingJob(metadata=ObjectMeta(name="j"))
        cs.trainingjobs.create(job)
        got = cs.trainingjobs.get("default", "j")
        got.status.phase = "Running"
        cs.trainingjobs.update_status(got)
        assert cs.trainingjobs.get("default", "j").status.phase == "Running"


class TestInformers:
    def test_handlers_fire(self):
        cs = Clientset()
        factory = InformerFactory(cs.tracker)
        log = []
        factory.informer("Pod").add_event_handler(
            on_add=lambda o: log.append(("add", o.name)),
            on_update=lambda old, new: log.append(("upd", new.name)),
            on_delete=lambda o: log.append(("del", o.name)),
        )
        cs.pods.create(make_pod("p"))
        pod = cs.pods.get("default", "p")
        cs.pods.update(pod)
        cs.pods.delete("default", "p")
        assert log == [("add", "p"), ("upd", "p"), ("del", "p")]

    def test_update_handler_sees_old_object(self):
        cs = Clientset()
        factory = InformerFactory(cs.tracker)
        pairs = []
        factory.informer("Pod").add_event_handler(
            on_update=lambda old, new: pairs.append(
                (old.metadata.labels.get("v"), new.metadata.labels.get("v"))))
        cs.pods.create(make_pod("p", labels={"v": "1"}))
        pod = cs.pods.get("default", "p")
        pod.metadata.labels["v"] = "2"
        cs.pods.update(pod)
        assert pairs == [("1", "2")]

    def test_resync_redelivers(self):
        cs = Clientset()
        factory = InformerFactory(cs.tracker)
        cs.pods.create(make_pod("p"))
        log = []
        factory.informer("Pod").add_event_handler(
            on_update=lambda old, new: log.append(new.name))
        factory.resync_all()
        assert log == ["p"]

    def test_lister_reads_through(self):
        cs = Clientset()
        factory = InformerFactory(cs.tracker)
        lister = factory.lister("Pod")
        cs.pods.create(make_pod("p"))
        assert lister.get("default", "p").name == "p"
        assert lister.try_get("default", "gone") is None


class TestWorkqueue:
    def test_dedup_while_queued(self):
        q = RateLimitingQueue()
        q.add("a")
        q.add("a")
        assert len(q) == 1

    def test_dirty_requeue_while_processing(self):
        # Single-writer-per-key guarantee (SURVEY.md §5.2).
        q = RateLimitingQueue()
        q.add("a")
        item, _ = q.get()
        assert item == "a"
        q.add("a")          # re-added while processing -> dirty
        assert len(q) == 0  # not queued yet
        q.done("a")
        assert len(q) == 1  # requeued on done
        item2, _ = q.get()
        assert item2 == "a"

    def test_add_after(self):
        q = RateLimitingQueue()
        q.add_after("a", 0.08)
        assert len(q) == 0
        item, _ = q.get(timeout=2.0)
        assert item == "a"

    def test_rate_limited_backoff_growth(self):
        q = RateLimitingQueue(base_delay=0.01, max_delay=1.0)
        q.add_rate_limited("a")
        assert q.num_requeues("a") == 1
        item, _ = q.get(timeout=2.0)
        assert item == "a"
        q.done("a")
        q.forget("a")
        assert q.num_requeues("a") == 0

    def test_shutdown_unblocks_get(self):
        q = RateLimitingQueue()
        result = {}

        def consumer():
            item, shutdown = q.get()
            result["shutdown"] = shutdown

        th = threading.Thread(target=consumer)
        th.start()
        time.sleep(0.05)
        q.shut_down()
        th.join(timeout=2)
        assert result["shutdown"] is True

    def test_get_timeout(self):
        q = RateLimitingQueue()
        item, shutdown = q.get(timeout=0.05)
        assert item is None and shutdown is False


class TestExpectations:
    def test_satisfied_lifecycle(self):
        e = ControllerExpectations()
        key = pods_key("default/job", "trainer")
        assert e.satisfied(key)  # never set
        e.expect_creations(key, 2)
        assert not e.satisfied(key)
        e.creation_observed(key)
        assert not e.satisfied(key)
        e.creation_observed(key)
        assert e.satisfied(key)

    def test_deletions(self):
        e = ControllerExpectations()
        key = pods_key("default/job", "trainer")
        e.expect_deletions(key, 1)
        assert not e.satisfied(key)
        e.deletion_observed(key)
        assert e.satisfied(key)

    def test_expiry(self, monkeypatch):
        import trainingjob_operator_tpu.client.expectations as exp

        e = ControllerExpectations()
        key = "k"
        e.expect_creations(key, 1)
        assert not e.satisfied(key)
        monkeypatch.setattr(exp, "EXPECTATION_TIMEOUT", 0.0)
        time.sleep(0.01)
        assert e.satisfied(key)


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_create_does_not_mutate_caller_generate_name(self):
        t = ObjectTracker()
        from trainingjob_operator_tpu.core.objects import ObjectMeta as OM, Pod as P
        pod = P(metadata=OM(name="", generate_name="w-", namespace="default"))
        a = t.create(pod)
        b = t.create(pod)  # same caller object reused -> second generated name
        assert pod.metadata.name == ""
        assert a.name != b.name and a.name.startswith("w-") and b.name.startswith("w-")

    def test_nodes_cluster_scoped(self):
        from trainingjob_operator_tpu.core.objects import make_ready_node, Node, ObjectMeta as OM
        cs = Clientset()
        cs.nodes.create(Node(metadata=OM(name="n1")))  # default ns normalized
        assert cs.nodes.get_node("n1").name == "n1"
        assert len(cs.nodes.list()) == 1

    def test_event_order_under_concurrent_writers(self):
        import threading as th
        t = ObjectTracker()
        t.create(make_pod("p"))
        versions = []
        t.watch("Pod", lambda e: versions.append(e.obj.metadata.resource_version))

        def writer():
            for _ in range(50):
                while True:
                    pod = t.get("Pod", "default", "p")
                    pod.metadata.labels["x"] = str(time.time())
                    try:
                        t.update(pod)
                        break
                    except ConflictError:
                        continue

        threads = [th.Thread(target=writer) for _ in range(4)]
        [x.start() for x in threads]
        [x.join() for x in threads]
        assert versions == sorted(versions), "watch events delivered out of commit order"
        assert len(versions) == 200


class TestDefaultsElasticRange:
    def test_range_only_spec_defaults_to_min(self):
        from trainingjob_operator_tpu.api.types import TPUTrainingJob, ReplicaSpec
        from trainingjob_operator_tpu.api.defaults import set_defaults
        from trainingjob_operator_tpu.api.validation import validate_job
        from trainingjob_operator_tpu.core.objects import (
            Container, ObjectMeta as OM, PodSpec, PodTemplateSpec)
        job = TPUTrainingJob(metadata=OM(name="j"))
        job.spec.replica_specs["w"] = ReplicaSpec(
            min_replicas=2, max_replicas=8,
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(name="c")])))
        set_defaults(job)
        assert job.spec.replica_specs["w"].replicas == 2
        assert validate_job(job) == []

    def test_tpu_without_topology_rejected(self):
        from trainingjob_operator_tpu.api.types import TPUSpec, TPUTrainingJob, ReplicaSpec
        from trainingjob_operator_tpu.api.validation import validate_job
        from trainingjob_operator_tpu.core.objects import (
            Container, ObjectMeta as OM, PodSpec, PodTemplateSpec)
        job = TPUTrainingJob(metadata=OM(name="j"))
        job.spec.replica_specs["w"] = ReplicaSpec(
            tpu=TPUSpec(accelerator="tpu-v5e"),
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(name="c")])))
        assert any("topology: required" in e for e in validate_job(job))
