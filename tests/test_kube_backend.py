"""Kube backend tests against the in-process fake apiserver.

VERDICT round 1 item 1: the controller, unchanged, must drive an
API-compatible apiserver over the stdlib REST transport -- CRUD + status
subresource + streaming watch feeding the informers, CRD self-creation,
Lease leader election, auth loading, watch reconnect/resume, conflicts.
Reference: cmd/app/server.go:111-151, pkg/client/informers/externalversions/
factory.go:100-130, controller.go:210-234.
"""

import base64
import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    ReplicaSpec,
    RestartPolicy,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.kube import KubeClientset
from trainingjob_operator_tpu.client.rest import ApiError, ClusterConfig, RestClient
from trainingjob_operator_tpu.client.tracker import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)
from trainingjob_operator_tpu.cmd.options import LeaderElectionConfig, OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    Pod,
    PodSpec,
    PodTemplateSpec,
    make_ready_node,
)
from trainingjob_operator_tpu.runtime.kube import KubeRuntime
from trainingjob_operator_tpu.utils.leader import KubeLeaderElector

from conftest import wait_for  # noqa: E402
from fake_apiserver import FakeApiServer  # noqa: E402


@pytest.fixture
def server():
    srv = FakeApiServer().start()
    yield srv
    srv.stop()


def cs_for(srv, **kw) -> KubeClientset:
    return KubeClientset(ClusterConfig(server=srv.url), watch_timeout=2, **kw)


def make_pod(name="p0", ns="default", labels=None) -> Pod:
    return Pod(metadata=ObjectMeta(name=name, namespace=ns,
                                   labels=labels or {}),
               spec=PodSpec(containers=[Container(name="aitj-c",
                                                  image="img")]))


class TestRestCrud:
    def test_create_get_list_delete(self, server):
        cs = cs_for(server)
        cs.pods.create(make_pod("a", labels={"role": "w"}))
        cs.pods.create(make_pod("b", labels={"role": "ps"}))
        got = cs.pods.get("default", "a")
        assert got.metadata.uid and got.metadata.resource_version
        assert [p.name for p in cs.pods.list("default")] == ["a", "b"]
        assert [p.name for p in cs.pods.list(
            "default", {"role": "w"})] == ["a"]
        cs.pods.delete("default", "a")
        with pytest.raises(NotFoundError):
            cs.pods.get("default", "a")

    def test_already_exists_and_conflict(self, server):
        cs = cs_for(server)
        cs.pods.create(make_pod("a"))
        with pytest.raises(AlreadyExistsError):
            cs.pods.create(make_pod("a"))
        stale = cs.pods.get("default", "a")
        fresh = cs.pods.get("default", "a")
        fresh.metadata.labels["x"] = "1"
        cs.pods.update(fresh)
        stale.metadata.labels["x"] = "2"
        with pytest.raises(ConflictError):
            cs.pods.update(stale)

    def test_status_subresource_preserves_spec(self, server):
        cs = cs_for(server)
        job = TPUTrainingJob(metadata=ObjectMeta(name="j", namespace="default"))
        job.spec.replica_specs["worker"] = ReplicaSpec(replicas=3)
        created = cs.trainingjobs.create(job)
        created.status.phase = TrainingJobPhase.PENDING
        # Poison the spec client-side: the status write must not carry it.
        created.spec.replica_specs["worker"].replicas = 99
        out = cs.trainingjobs.update_status(created)
        assert out.status.phase == TrainingJobPhase.PENDING
        stored = cs.trainingjobs.get("default", "j")
        assert stored.spec.replica_specs["worker"].replicas == 3
        assert stored.status.phase == TrainingJobPhase.PENDING

    def test_cluster_scoped_nodes(self, server):
        cs = cs_for(server)
        cs.nodes.create(make_ready_node("n0"))
        assert cs.nodes.get_node("n0").is_ready()
        assert [n.name for n in cs.nodes.list()] == ["n0"]

    def test_bearer_token_auth(self):
        srv = FakeApiServer(required_token="sekrit").start()
        try:
            good = KubeClientset(ClusterConfig(server=srv.url, token="sekrit"))
            good.pods.create(make_pod("a"))
            bad = KubeClientset(ClusterConfig(server=srv.url, token="wrong"))
            with pytest.raises(ApiError) as err:
                bad.pods.list()
            assert err.value.status == 401
        finally:
            srv.stop()

    def test_kubeconfig_loading(self, server, tmp_path):
        cfg = tmp_path / "kubeconfig"
        cfg.write_text(f"""
apiVersion: v1
kind: Config
current-context: test
contexts:
- name: test
  context: {{cluster: c, user: u}}
clusters:
- name: c
  cluster:
    server: {server.url}
users:
- name: u
  user:
    token: tok-{base64.b64encode(b'x').decode()}
""")
        loaded = ClusterConfig.from_kubeconfig(str(cfg))
        assert loaded.server == server.url
        assert loaded.token.startswith("tok-")
        KubeClientset(loaded).pods.create(make_pod("a"))
        assert server.get_obj("pods", "default", "a") is not None

    def test_ensure_crd_idempotent(self, server):
        cs = cs_for(server)
        assert cs.ensure_crd() is True
        assert cs.ensure_crd() is False
        stored = server.list_objs("customresourcedefinitions")
        assert stored[0]["spec"]["group"] == constants.GROUP_NAME


class TestReflector:
    def test_watch_feeds_informers(self, server):
        cs = cs_for(server)
        cs.start()
        try:
            seen = []
            from trainingjob_operator_tpu.client.informers import InformerFactory

            factory = InformerFactory(cs.tracker)
            factory.informer(Pod.KIND).add_event_handler(
                on_add=lambda p: seen.append(("add", p.name)),
                on_delete=lambda p: seen.append(("del", p.name)))
            cs.pods.create(make_pod("w0"))
            assert wait_for(lambda: ("add", "w0") in seen, 5)
            cs.pods.delete("default", "w0")
            assert wait_for(lambda: ("del", "w0") in seen, 5)
        finally:
            cs.stop()

    def test_preexisting_objects_listed(self, server):
        server.seed("pods", make_pod("old").to_dict())
        cs = cs_for(server)
        cs.start()
        try:
            assert wait_for(
                lambda: cs.tracker.count(Pod.KIND) == 1, 5)
        finally:
            cs.stop()

    def test_410_gone_triggers_relist(self, server):
        cs = cs_for(server)
        cs.start()
        try:
            cs.pods.create(make_pod("a"))
            assert wait_for(lambda: cs.tracker.count(Pod.KIND) == 1, 5)
            reflector = next(r for r in cs.reflectors
                             if r._info.kind == Pod.KIND)
            before = reflector.relist_count
            # Advance the global rv past the pod reflector's resume point,
            # then drop the log: its next reconnect (the 2 s server-side
            # timeout) resumes from a pre-window rv -> 410 Gone -> re-list.
            from trainingjob_operator_tpu.core.objects import Service

            cs.services.create(Service(metadata=ObjectMeta(
                name="bump", namespace="default")))
            server.prune_watch_log()
            assert wait_for(lambda: reflector.relist_count > before, 10)
            cs.pods.create(make_pod("b"))
            assert wait_for(lambda: cs.tracker.count(Pod.KIND) == 2, 10)
        finally:
            cs.stop()

    def test_watch_times_out_on_half_open_connection(self):
        """ADVICE r2 medium: a half-open watch (server never closes, never
        sends) must hit the client-side socket deadline instead of blocking
        readline() forever with a silently stale reflector cache."""
        import socket
        import threading

        def half_open_server(sock):
            conn, _ = sock.accept()
            conn.recv(65536)  # swallow the request...
            conn.sendall(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Type: application/json\r\n\r\n")
            # ...then go silent forever: no events, no close (NAT drop /
            # crashed apiserver behind a dead conntrack entry).
            threading.Event().wait(30)
            conn.close()

        sock = socket.socket()
        try:
            sock.bind(("127.0.0.1", 0))
            sock.listen(1)
            threading.Thread(target=half_open_server, args=(sock,),
                             daemon=True).start()
            host, port = sock.getsockname()
            rest = RestClient(ClusterConfig(server=f"http://{host}:{port}"))
            t0 = time.time()
            with pytest.raises(OSError):  # socket timeout (TimeoutError)
                # server_timeout=1 -> socket deadline 1 + max(5, .25) = 6 s.
                for _ in rest.watch("/api/v1/pods", timeout_seconds=1):
                    pass
            assert time.time() - t0 < 15, "watch did not time out client-side"
        finally:
            sock.close()

    def test_reflector_backs_off_on_persistent_5xx(self, server):
        """ADVICE r2 low: persistent 5xx must re-list with backoff, not in a
        tight loop hammering a struggling apiserver."""
        cs = cs_for(server)
        cs.start()
        try:
            assert wait_for(lambda: all(r.wait_synced(5)
                                        for r in cs.reflectors), 10)
            server.fail_with = 500
            start = server.request_count
            time.sleep(2.0)
            failed_requests = server.request_count - start
            # 4 reflectors x a tight loop would be thousands of requests in
            # 2 s; backoff (0.5, 1.0, ...) keeps it to a handful each.
            assert failed_requests < 40, (
                f"{failed_requests} requests in 2 s: reflectors are "
                f"tight-looping on 5xx")
            server.fail_with = None
            cs.pods.create(make_pod("after-recovery"))
            assert wait_for(lambda: cs.tracker.count(Pod.KIND) == 1, 15)
        finally:
            cs.stop()

    def test_reflector_backs_off_on_watch_only_5xx(self, server):
        """Backoff must also grow when LIST succeeds but WATCH persistently
        5xxs (watch cache down): resetting after a mere successful list
        would re-list in a tight 0.5 s loop forever."""
        cs = cs_for(server)
        cs.start()
        try:
            assert wait_for(lambda: all(r.wait_synced(5)
                                        for r in cs.reflectors), 10)
            server.fail_watch_with = 500
            time.sleep(1.0)  # let each reflector hit the fault at least once
            start = server.request_count
            time.sleep(2.0)
            requests = server.request_count - start
            # 4 reflectors tight-looping would be thousands (list+watch pairs)
            # in 2 s; growing backoff keeps it to a handful each.
            assert requests < 40, (
                f"{requests} requests in 2 s: reflectors tight-loop when "
                f"only the watch fails")
        finally:
            server.fail_watch_with = None
            cs.stop()

    def test_mirror_prunes_deleted_during_downtime(self, server):
        # Objects deleted while no watch is running disappear on re-list.
        server.seed("pods", make_pod("gone").to_dict())
        server.seed("pods", make_pod("kept").to_dict())
        cs = cs_for(server)
        cs.start()
        try:
            assert wait_for(lambda: cs.tracker.count(Pod.KIND) == 2, 5)
        finally:
            cs.stop()
        server._store.pop(("pods", "default", "gone"))
        server.prune_watch_log()
        cs2 = cs_for(server)
        cs2.start()
        try:
            assert wait_for(lambda: cs2.tracker.count(Pod.KIND) == 1, 5)
            assert cs2.tracker.get(Pod.KIND, "default", "kept")
        finally:
            cs2.stop()


class TestKubeLeaderElection:
    CFG = LeaderElectionConfig(leader_elect=True, lease_duration=0.6,
                               renew_deadline=0.3, retry_period=0.05)

    def test_acquire_and_renew(self, server):
        rest = RestClient(ClusterConfig(server=server.url))
        elector = KubeLeaderElector(rest, self.CFG, identity="op-1")
        ran = []
        elector.run(lambda: ran.append(time.time()) or time.sleep(0.2))
        assert len(ran) == 1
        lease = server.get_obj("leases", "kube-system",
                               "tpu-trainingjob-operator")
        # Released on exit: holder cleared for fast successor acquisition.
        assert lease["spec"]["holderIdentity"] == ""

    def test_second_candidate_blocks_until_release(self, server):
        import threading

        rest = RestClient(ClusterConfig(server=server.url))
        first = KubeLeaderElector(rest, self.CFG, identity="op-1")
        second = KubeLeaderElector(
            RestClient(ClusterConfig(server=server.url)), self.CFG,
            identity="op-2")
        order = []
        release_first = threading.Event()

        def lead_first():
            order.append("first")
            release_first.wait(5)

        t1 = threading.Thread(
            target=lambda: first.run(lead_first), daemon=True)
        t1.start()
        assert wait_for(lambda: order == ["first"], 5)
        t2 = threading.Thread(
            target=lambda: second.run(lambda: order.append("second")),
            daemon=True)
        t2.start()
        time.sleep(0.3)
        assert order == ["first"]  # lease held; second must wait
        release_first.set()
        t1.join(5)
        t2.join(5)
        assert order == ["first", "second"]

    def test_lost_lease_fires_on_lost(self, server):
        """A deposed leader steps down (renew fails past renew_deadline ->
        on_lost), instead of reconciling split-brain beside its successor."""
        import threading

        rest = RestClient(ClusterConfig(server=server.url))
        elector = KubeLeaderElector(rest, self.CFG, identity="op-1")
        stop = threading.Event()

        def lead():
            # Usurper rewrites the lease out from under us; our renews then
            # conflict/fail until the renew deadline trips.
            lease = server.get_obj("leases", "kube-system",
                                   "tpu-trainingjob-operator")
            lease["spec"]["holderIdentity"] = "usurper"
            from trainingjob_operator_tpu.utils.leader import _micro_ts
            lease["spec"]["renewTime"] = _micro_ts(time.time() + 3600)
            server.seed("leases", lease)  # bumps rv: conflicts our renews
            assert stop.wait(5), "on_lost never fired"

        elector.run(lead, on_lost=stop.set)
        assert elector.lost.is_set()

    def test_lost_lease_on_transport_error(self, server):
        """ADVICE r2 high: a ConnectionError during renew (apiserver gone)
        must demote the leader via on_lost, not kill the renew thread and
        leave a deposed leader reconciling split-brain."""
        import threading

        rest = RestClient(ClusterConfig(server=server.url))
        elector = KubeLeaderElector(rest, self.CFG, identity="op-1")
        stop = threading.Event()

        def lead():
            server.stop()  # every subsequent renew raises ConnectionError
            assert stop.wait(5), "on_lost never fired after transport loss"

        elector.run(lead, on_lost=stop.set)
        assert elector.lost.is_set()

    def test_takeover_of_expired_lease(self, server):
        from trainingjob_operator_tpu.utils.leader import _micro_ts

        server.seed("leases", {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "tpu-trainingjob-operator",
                         "namespace": "kube-system"},
            "spec": {"holderIdentity": "dead-operator",
                     "leaseDurationSeconds": 1,
                     "renewTime": _micro_ts(time.time() - 30),
                     "leaseTransitions": 4},
        })
        rest = RestClient(ClusterConfig(server=server.url))
        elector = KubeLeaderElector(rest, self.CFG, identity="op-2")
        ran = []
        elector.run(lambda: ran.append(1))
        assert ran == [1]
        lease = server.get_obj("leases", "kube-system",
                               "tpu-trainingjob-operator")
        assert lease["spec"]["leaseTransitions"] == 5


class TestKubeE2E:
    """The round-1 acceptance bar: the existing controller, unchanged,
    drives the apiserver through --backend kube plumbing."""

    @pytest.fixture
    def cluster(self):
        srv = FakeApiServer(kubelet=True).start()
        srv.seed("nodes", make_ready_node("fake-node").to_dict())
        cs = cs_for(srv)
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05, backend="kube"))
        rt = KubeRuntime(cs)
        rt.start()
        tc.run(workers=2)
        yield srv, cs, tc
        tc.stop()
        rt.stop()

    def job(self, name="kjob", replicas=2, run_seconds="0.3") -> TPUTrainingJob:
        from trainingjob_operator_tpu.api.types import CleanPodPolicy

        job = TPUTrainingJob(metadata=ObjectMeta(name=name,
                                                 namespace="default"))
        job.spec.clean_pod_policy = CleanPodPolicy.NONE  # keep pods to assert on
        job.spec.replica_specs["worker"] = ReplicaSpec(
            replicas=replicas,
            restart_policy=RestartPolicy.NEVER,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    FakeApiServer.RUN_SECONDS: run_seconds}),
                spec=PodSpec(containers=[Container(
                    name="aitj-worker", image="img",
                    ports=[ContainerPort(name="aitj-7900",
                                         container_port=7900)])])))
        return job

    def test_job_runs_to_success(self, cluster):
        srv, cs, tc = cluster
        cs.trainingjobs.create(self.job())

        def phase():
            try:
                return cs.trainingjobs.get("default", "kjob").status.phase
            except NotFoundError:
                return None

        assert wait_for(lambda: phase() == TrainingJobPhase.SUCCEEDED, 20), \
            f"job stuck in {phase()}"
        # The reconcile created one pod + one headless service per index,
        # with owner references, on the real (fake) apiserver.
        pods = srv.list_objs("pods")
        services = srv.list_objs("services")
        assert {p["metadata"]["name"] for p in pods} == {
            "kjob-worker-0", "kjob-worker-1"}
        assert {s["metadata"]["name"] for s in services} == {
            "kjob-worker-0", "kjob-worker-1"}
        owner = pods[0]["metadata"]["ownerReferences"][0]
        assert owner["kind"] == constants.KIND and owner["controller"]
        assert services[0]["spec"]["clusterIP"] == "None"

    def test_gang_recovers_all_or_nothing(self, cluster):
        """VERDICT r3 item 4: the fake apiserver schedules 3 of 4 gang pods;
        the controller must release the partial gang (all-or-nothing) and,
        once capacity appears, run the full slice -- never count the job
        Running on a sub-slice."""
        from trainingjob_operator_tpu.api.types import (
            CleanPodPolicy,
            TPUSpec,
        )

        srv, cs, tc = cluster
        tc.options.scale_pending_time = 0.3
        srv.unschedulable_names = {"gjob-worker-3"}
        job = TPUTrainingJob(metadata=ObjectMeta(name="gjob",
                                                 namespace="default"))
        job.spec.clean_pod_policy = CleanPodPolicy.NONE
        job.spec.replica_specs["worker"] = ReplicaSpec(
            replicas=4,  # topology 4x4 = 4 TPU-VM hosts, one slice
            restart_policy=RestartPolicy.ON_NODE_FAIL,
            tpu=TPUSpec(accelerator="tpu-v5-lite-podslice", topology="4x4"),
            template=PodTemplateSpec(
                spec=PodSpec(containers=[Container(
                    name="aitj-worker", image="img",
                    ports=[ContainerPort(name="aitj-7900",
                                         container_port=7900)])])))
        cs.trainingjobs.create(job)

        def pod_uids():
            return {p["metadata"]["name"]: p["metadata"].get("uid")
                    for p in srv.list_objs("pods")}

        assert wait_for(lambda: len(pod_uids()) == 4, 10)
        first = pod_uids()
        # The partial gang (3 placed + 1 starved) must be torn down whole...
        assert wait_for(
            lambda: not (set(pod_uids().values()) & set(first.values())), 15), \
            "partial gang was never released"
        # ...and the job must never have counted Running on 3/4 hosts.
        assert (cs.trainingjobs.get("default", "gjob").status.phase
                != TrainingJobPhase.RUNNING)
        # Capacity appears: the next atomic retry schedules all 4.
        srv.unschedulable_names = set()
        assert wait_for(
            lambda: (cs.trainingjobs.get("default", "gjob").status.phase
                     == TrainingJobPhase.RUNNING), 20)
        assert len(pod_uids()) == 4

    def test_clean_pod_policy_all_deferred_ending(self, cluster):
        """CleanPodPolicy All stashes the final phase in a metadata
        annotation until pods drain (status.go:256-283).  On a real
        apiserver that stash MUST go through a full update -- the status
        subresource drops metadata (the round-1 bug this harness caught)."""
        from trainingjob_operator_tpu.api.types import CleanPodPolicy

        srv, cs, tc = cluster
        job = self.job("cjob")
        job.spec.clean_pod_policy = CleanPodPolicy.ALL
        cs.trainingjobs.create(job)
        assert wait_for(
            lambda: (cs.trainingjobs.get("default", "cjob").status.phase
                     == TrainingJobPhase.SUCCEEDED), 20)
        assert wait_for(lambda: not srv.list_objs("pods"), 10)
        # Terminal: the job must NOT cycle back to recreating pods.
        time.sleep(1.0)
        assert not srv.list_objs("pods")
        assert (cs.trainingjobs.get("default", "cjob").status.phase
                == TrainingJobPhase.SUCCEEDED)

    def test_deleted_pod_is_recreated(self, cluster):
        srv, cs, tc = cluster
        cs.trainingjobs.create(self.job("rejob", run_seconds="30"))
        assert wait_for(
            lambda: (cs.trainingjobs.get("default", "rejob").status.phase
                     == TrainingJobPhase.RUNNING), 20)
        uid0 = srv.get_obj("pods", "default", "rejob-worker-0")["metadata"]["uid"]
        cs.pods.delete("default", "rejob-worker-0")
        # Gap-filling reconcile (pod.go:186-193): a new incarnation appears.
        assert wait_for(
            lambda: (srv.get_obj("pods", "default", "rejob-worker-0") or
                     {}).get("metadata", {}).get("uid", uid0) != uid0, 20)
