"""Fleet control-plane tests: churn determinism, the O(changed-pods) status
index, the informer job index, and the churn harness itself (a fast seeded
smoke in tier-1; the 10k-job / 100k-replica run behind ``-m slow``)."""

import os

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.client.informers import Informer
from trainingjob_operator_tpu.controller.control import gen_owner_reference
from trainingjob_operator_tpu.controller.controller import job_index_key
from trainingjob_operator_tpu.controller.pod_index import PodPhaseIndex
from trainingjob_operator_tpu.core.objects import ObjectMeta, Pod, PodPhase
from trainingjob_operator_tpu.fleet.churn import (
    FATE_POD_FAIL,
    ChurnGenerator,
    ChurnProfile,
)
from trainingjob_operator_tpu.fleet.harness import FleetHarness


class TestChurnDeterminism:
    def test_same_seed_same_schedule(self):
        profile = ChurnProfile(jobs=150, duration=10.0, seed=42)
        a = ChurnGenerator(profile).plan()
        b = ChurnGenerator(profile).plan()
        assert a == b  # JobPlan is a frozen dataclass: field-exact equality

    def test_different_seed_different_schedule(self):
        a = ChurnGenerator(ChurnProfile(jobs=50, seed=1)).plan()
        b = ChurnGenerator(ChurnProfile(jobs=50, seed=2)).plan()
        assert a != b

    def test_schedule_shape(self):
        profile = ChurnProfile(jobs=100, duration=5.0, seed=0)
        plans = ChurnGenerator(profile).plan()
        assert len(plans) == 100
        assert all(0.0 <= p.create_at <= 5.0 for p in plans)
        assert plans[-1].create_at == pytest.approx(5.0)
        lo, hi = profile.replicas
        assert all(lo <= p.replicas <= hi for p in plans)
        for p in plans:
            if p.disrupt_at:
                assert p.disrupt_at > p.create_at
            if p.fate == FATE_POD_FAIL:
                assert 0 <= p.fail_index < p.replicas


def _job(name="j", uid="u1"):
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.metadata.uid = uid
    return job


def _pod(job, rtype, index, phase, node=""):
    pod = Pod(metadata=ObjectMeta(
        name=f"{job.metadata.name}-{rtype}-{index}",
        namespace=job.metadata.namespace,
        labels={
            constants.GROUP_NAME_LABEL: constants.GROUP_NAME,
            constants.JOB_NAME_LABEL: job.metadata.name,
            constants.REPLICA_NAME_LABEL: rtype,
            constants.REPLICA_INDEX_LABEL: str(index),
        },
        owner_references=[gen_owner_reference(job)]))
    pod.spec.node_name = node
    pod.status.phase = phase
    return pod


class TestPodPhaseIndex:
    def test_counts_match_pod_set(self):
        job = _job()
        idx = PodPhaseIndex()
        idx.observe(_pod(job, "trainer", 0, PodPhase.RUNNING, node="n0"))
        idx.observe(_pod(job, "trainer", 1, PodPhase.PENDING, node="n0"))
        idx.observe(_pod(job, "trainer", 2, PodPhase.PENDING))
        idx.observe(_pod(job, "trainer", 3, PodPhase.SUCCEEDED, node="n0"))
        idx.observe(_pod(job, "trainer", 4, PodPhase.FAILED, node="n0"))
        rs, population = idx.replica_status(
            "default/j", "u1", "trainer", width=5, restarted=False)
        assert population == 5
        assert (rs.active, rs.scheduled, rs.pending, rs.succeeded, rs.failed) \
            == (1, 1, 1, 1, 1)
        assert rs.restarting == 0

    def test_restarted_job_counts_pending_as_restarting(self):
        job = _job()
        idx = PodPhaseIndex()
        idx.observe(_pod(job, "trainer", 0, PodPhase.PENDING, node="n0"))
        rs, _ = idx.replica_status(
            "default/j", "u1", "trainer", width=1, restarted=True)
        assert rs.restarting == 1 and rs.scheduled == 0

    def test_update_replaces_record(self):
        """A pod observed again (phase moved) must not double-count."""
        job = _job()
        idx = PodPhaseIndex()
        idx.observe(_pod(job, "trainer", 0, PodPhase.PENDING))
        idx.observe(_pod(job, "trainer", 0, PodPhase.RUNNING, node="n0"))
        rs, population = idx.replica_status(
            "default/j", "u1", "trainer", width=1, restarted=False)
        assert population == 1
        assert rs.active == 1 and rs.pending == 0

    def test_width_and_uid_filters(self):
        """Out-of-width pods (elastic shrink leftovers) and pods owned by a
        same-name previous incarnation are excluded."""
        job = _job(uid="u1")
        old = _job(uid="u0")
        idx = PodPhaseIndex()
        idx.observe(_pod(job, "trainer", 0, PodPhase.RUNNING, node="n0"))
        idx.observe(_pod(job, "trainer", 7, PodPhase.RUNNING, node="n0"))
        stale = _pod(old, "trainer", 1, PodPhase.RUNNING, node="n0")
        stale.metadata.name = "j-trainer-1"  # same naming, old uid
        idx.observe(stale)
        rs, population = idx.replica_status(
            "default/j", "u1", "trainer", width=4, restarted=False)
        assert population == 1 and rs.active == 1

    def test_delete_and_forget(self):
        job = _job()
        idx = PodPhaseIndex()
        p = _pod(job, "trainer", 0, PodPhase.RUNNING, node="n0")
        idx.observe(p)
        assert idx.pod_count("default/j") == 1
        idx.observe_delete(p)
        assert idx.pod_count("default/j") == 0
        idx.observe(p)
        idx.forget_job("default/j")
        assert idx.total_pods() == 0

    def test_orphan_pods_ignored(self):
        idx = PodPhaseIndex()
        orphan = Pod(metadata=ObjectMeta(name="stray", namespace="default"))
        orphan.status.phase = PodPhase.RUNNING
        idx.observe(orphan)
        assert idx.total_pods() == 0


class TestInformerJobIndex:
    def test_by_index_tracks_adds_updates_deletes(self):
        cs = Clientset()
        informer = Informer(cs.tracker, Pod.KIND)
        informer.add_index(constants.JOB_INDEX, job_index_key)
        job_a, job_b = _job("a", "ua"), _job("b", "ub")
        cs.pods.create(_pod(job_a, "trainer", 0, PodPhase.PENDING))
        cs.pods.create(_pod(job_a, "trainer", 1, PodPhase.PENDING))
        cs.pods.create(_pod(job_b, "trainer", 0, PodPhase.PENDING))
        # An unlabeled pod never lands in any bucket.
        cs.pods.create(Pod(metadata=ObjectMeta(name="stray",
                                               namespace="default")))

        names = {p.metadata.name
                 for p in informer.by_index(constants.JOB_INDEX, "default/a")}
        assert names == {"a-trainer-0", "a-trainer-1"}
        assert len(informer.by_index(constants.JOB_INDEX, "default/b")) == 1
        assert informer.by_index(constants.JOB_INDEX, "default/nope") == []

        # Updates keep the bucket entry current (object identity refreshed).
        pod = cs.pods.get("default", "a-trainer-0")
        pod.status.phase = PodPhase.RUNNING
        cs.pods.update(pod)
        phases = {p.metadata.name: p.status.phase
                  for p in informer.by_index(constants.JOB_INDEX, "default/a")}
        assert phases["a-trainer-0"] == PodPhase.RUNNING

        # by_index hands out copies: mutating a result must not poison the
        # cache.
        informer.by_index(constants.JOB_INDEX,
                          "default/a")[0].metadata.labels.clear()
        assert len(informer.by_index(constants.JOB_INDEX, "default/a")) == 2

        cs.pods.delete("default", "a-trainer-0", grace_period=0)
        names = {p.metadata.name
                 for p in informer.by_index(constants.JOB_INDEX, "default/a")}
        assert names == {"a-trainer-1"}
        informer.stop()

    def test_index_seeded_from_existing_store(self):
        cs = Clientset()
        job = _job("pre", "up")
        cs.pods.create(_pod(job, "trainer", 0, PodPhase.RUNNING, node="n0"))
        informer = Informer(cs.tracker, Pod.KIND)
        informer.add_index(constants.JOB_INDEX, job_index_key)
        assert len(informer.by_index(constants.JOB_INDEX, "default/pre")) == 1
        informer.stop()


class TestFleetSmoke:
    def test_small_fleet_converges(self):
        """Seeded ~40-job churn run: every fate settles, no orphans, and the
        latency recorder actually recorded transitions."""
        profile = ChurnProfile(jobs=40, duration=1.5, seed=11,
                               replicas=(1, 4))
        harness = FleetHarness(profile, workers=2, resync_period=5.0,
                               gc_interval=5.0, converge_timeout=60.0)
        report = harness.run()
        assert report.converged, report.violations[:10]
        assert report.violations == []
        assert report.jobs == 40
        assert report.sync_count > 0 and report.reconciles_per_s > 0
        assert report.event_to_visible_ms["count"] > 0
        assert report.event_to_visible_ms["by_kind"]["create"] > 0
        assert report.workqueue_depth_high_water >= 1
        # Terminal/steady phases only -- nothing stuck mid-flight.
        assert set(report.phase_counts) <= {"Succeed", "Running", "Preempted"}

    def test_report_roundtrips_to_json_dict(self):
        profile = ChurnProfile(jobs=6, duration=0.5, seed=3, replicas=(1, 2))
        report = FleetHarness(profile, workers=2, resync_period=5.0,
                              gc_interval=5.0, converge_timeout=45.0).run()
        d = report.to_dict()
        assert d["converged"] is True
        assert isinstance(d["event_to_visible_ms"], dict)
        import json
        json.dumps(d)  # must be JSON-serializable as-is


@pytest.mark.slow
class TestFleetAtScale:
    def test_10k_jobs_100k_replicas_converge(self):
        """The tentpole acceptance run: 10k jobs / ~100k replicas of seeded
        churn must converge with zero invariant violations.  Tier-1 excludes
        it (-m 'not slow').  Calibration: 1000 jobs / ~10k replicas converges
        in ~15 min on one core under either sim kernel (controller-bound at
        ~150 reconciles/s / ~135k syncs; see docs/FLEET.md "Sim kernels"),
        so the timeout scales with the job count -- at the full 10k this is
        a multi-hour soak on a single core, proportionally faster with real
        parallelism.  TRAININGJOB_FLEET_JOBS downsizes the run."""
        jobs = int(os.environ.get(constants.FLEET_JOBS_ENV, "10000"))
        seed = int(os.environ.get(constants.FLEET_SEED_ENV, "1"))
        profile = ChurnProfile(jobs=jobs, duration=180.0, seed=seed,
                               replicas=(8, 12))
        harness = FleetHarness(profile, workers=8, resync_period=120.0,
                               resync_shards=16, gc_interval=600.0,
                               pods_per_node=256, sim_tick=0.5,
                               converge_timeout=max(2400.0, jobs * 1.5))
        report = harness.run()
        assert report.replicas_total >= jobs * 9  # ~10 avg from (8, 12)
        assert report.converged, report.violations[:20]
        assert report.event_to_visible_ms["count"] > 0
