"""In-process API-compatible fake Kubernetes apiserver.

The envtest analogue for this environment (VERDICT round 1, item 1): a real
HTTP server speaking enough of the Kubernetes REST surface to drive the kube
backend end-to-end -- CRUD with optimistic concurrency, AlreadyExists/
NotFound/Conflict status objects, label-selector LISTs, the /status
subresource, streaming watches with resourceVersion resume + 410 Gone after
log pruning, bearer-token auth, and an optional toy kubelet that walks pods
Pending -> Running -> Succeeded/Failed (honoring the sim runtime's
``sim.tpu.trainingjob.dev/*`` annotations).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit

Key = Tuple[str, str, str]  # (plural, namespace, name)


def _now_iso() -> str:
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


class FakeApiServer:
    def __init__(self, required_token: str = "", kubelet: bool = False,
                 watch_log_limit: int = 10000):
        self._lock = threading.Condition()
        self._store: Dict[Key, Dict[str, Any]] = {}
        self._rv = 0
        # (rv, plural, event_type, obj_snapshot); pruned to watch_log_limit.
        self._log: List[Tuple[int, str, str, Dict[str, Any]]] = []
        self._log_start_rv = 0
        self._watch_log_limit = watch_log_limit
        self.required_token = required_token
        self.request_count = 0
        #: Fault injection: when set (e.g. 500), every request is answered
        #: with this status -- models a persistently erroring apiserver.
        self.fail_with: Optional[int] = None
        #: Like fail_with, but only for watch requests (watch cache down,
        #: lists still served).
        self.fail_watch_with: Optional[int] = None
        #: Pod names the toy kubelet refuses to schedule: they stay Pending
        #: with an Unschedulable condition (gang-atomicity scenarios).
        self.unschedulable_names: set = set()

        handler = self._make_handler()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="fake-apiserver")
        self._kubelet_stop = threading.Event()
        self._kubelet_thread: Optional[threading.Thread] = None
        if kubelet:
            self._kubelet_thread = threading.Thread(
                target=self._kubelet_loop, daemon=True, name="fake-kubelet")

    # -- lifecycle -----------------------------------------------------------

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FakeApiServer":
        self._thread.start()
        if self._kubelet_thread is not None:
            self._kubelet_thread.start()
        return self

    def stop(self) -> None:
        if getattr(self, "_stopped", False):
            return  # idempotent: tests may stop mid-test to inject failure
        self._stopped = True
        self._kubelet_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._lock:
            self._lock.notify_all()

    # -- store ---------------------------------------------------------------

    def _commit_locked(self, key: Key, obj: Optional[Dict[str, Any]],
                etype: str) -> Dict[str, Any]:
        """Caller holds self._lock: stamp rv, append to the watch log,
        wake watchers."""
        self._rv += 1
        if obj is not None:
            obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            self._store[key] = obj
            snapshot = json.loads(json.dumps(obj))
        else:
            snapshot = json.loads(json.dumps(self._store.pop(key)))
            snapshot.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
        self._log.append((self._rv, key[0], etype, snapshot))
        if len(self._log) > self._watch_log_limit:
            drop = len(self._log) - self._watch_log_limit
            self._log_start_rv = self._log[drop - 1][0]
            del self._log[:drop]
        self._lock.notify_all()
        return snapshot

    def prune_watch_log(self) -> None:
        """Force every held resourceVersion out of the watch window (tests:
        the client must observe 410 Gone and re-list)."""
        with self._lock:
            self._log_start_rv = self._rv
            self._log.clear()

    def seed(self, plural: str, obj: Dict[str, Any]) -> None:
        """Directly insert an object (test setup)."""
        meta = obj.setdefault("metadata", {})
        ns = meta.get("namespace", "") if plural != "nodes" else ""
        meta.setdefault("uid", str(uuid.uuid4()))
        with self._lock:
            self._commit_locked((plural, ns, meta["name"]), obj, "ADDED")

    def get_obj(self, plural: str, ns: str, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            obj = self._store.get((plural, ns, name))
            return json.loads(json.dumps(obj)) if obj is not None else None

    def list_objs(self, plural: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [json.loads(json.dumps(o)) for (p, _, _), o
                    in sorted(self._store.items()) if p == plural]

    # -- toy kubelet ---------------------------------------------------------

    RUN_SECONDS = "sim.tpu.trainingjob.dev/run-seconds"
    EXIT_CODE = "sim.tpu.trainingjob.dev/exit-code"

    def _kubelet_loop(self) -> None:
        started: Dict[str, float] = {}
        while not self._kubelet_stop.wait(0.01):
            with self._lock:
                pods = [(k, json.loads(json.dumps(o)))
                        for k, o in self._store.items() if k[0] == "pods"]
                nodes = [o for (p, _, _), o in self._store.items()
                         if p == "nodes"]
                node_name = nodes[0]["metadata"]["name"] if nodes else "fake-node"
                for key, pod in pods:
                    uid = pod["metadata"].get("uid", "")
                    phase = (pod.get("status") or {}).get("phase", "Pending")
                    ann = pod["metadata"].get("annotations") or {}
                    if phase == "Pending" and (pod["metadata"].get("name")
                                               in self.unschedulable_names):
                        conds = (pod.get("status") or {}).get("conditions") or []
                        if not conds:
                            pod.setdefault("status", {})["phase"] = "Pending"
                            pod["status"]["conditions"] = [{
                                "type": "PodScheduled", "status": "False",
                                "reason": "Unschedulable",
                                "message": "0/1 nodes available: "
                                           "insufficient google.com/tpu"}]
                            self._commit_locked(key, pod, "MODIFIED")
                        continue
                    if phase == "Pending":
                        pod.setdefault("spec", {})["nodeName"] = node_name
                        pod["status"] = {
                            "phase": "Running",
                            "startTime": _now_iso(),
                            "containerStatuses": [
                                {"name": c["name"],
                                 "state": {"running": {"startedAt": _now_iso()}}}
                                for c in pod["spec"].get("containers", [])],
                        }
                        started[uid] = time.time()
                        self._commit_locked(key, pod, "MODIFIED")
                    elif phase == "Running" and self.RUN_SECONDS in ann:
                        t0 = started.setdefault(uid, time.time())
                        if time.time() - t0 >= float(ann[self.RUN_SECONDS]):
                            code = int(ann.get(self.EXIT_CODE, "0"))
                            state = ({"terminated": {"exitCode": code,
                                                     "reason": "Completed"}}
                                     if code == 0 else
                                     {"terminated": {"exitCode": code,
                                                     "reason": "Error"}})
                            pod["status"]["phase"] = ("Succeeded" if code == 0
                                                      else "Failed")
                            pod["status"]["containerStatuses"] = [
                                {"name": c["name"], "state": state}
                                for c in pod["spec"].get("containers", [])]
                            self._commit_locked(key, pod, "MODIFIED")

    # -- HTTP plumbing -------------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # quiet
                pass

            def _status(self, code: int, reason: str, message: str):
                body = json.dumps({
                    "kind": "Status", "apiVersion": "v1", "code": code,
                    "reason": reason, "message": message,
                }).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, obj: Dict[str, Any]):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _auth_ok(self) -> bool:
                if server.fail_with is not None:
                    self._status(server.fail_with, "InternalError",
                                 "injected fault")
                    return False
                if not server.required_token:
                    return True
                got = self.headers.get("Authorization", "")
                if got == f"Bearer {server.required_token}":
                    return True
                self._status(401, "Unauthorized", "bad or missing token")
                return False

            def _route(self):
                """-> (plural, namespace|None, name|None, subresource|None,
                query) or None (after replying 404)."""
                split = urlsplit(self.path)
                query = {k: v[0] for k, v in parse_qs(split.query).items()}
                parts = [p for p in split.path.split("/") if p]
                # /api/v1/... | /apis/{group}/{version}/...
                if parts[:2] == ["api", "v1"]:
                    rest = parts[2:]
                elif parts and parts[0] == "apis" and len(parts) >= 3:
                    rest = parts[3:]
                else:
                    self._status(404, "NotFound", f"no route {self.path}")
                    return None
                ns = None
                if rest and rest[0] == "namespaces" and len(rest) >= 3:
                    ns = rest[1]
                    rest = rest[2:]
                if not rest:
                    self._status(404, "NotFound", f"no route {self.path}")
                    return None
                plural = rest[0]
                name = rest[1] if len(rest) > 1 else None
                sub = rest[2] if len(rest) > 2 else None
                return plural, ns, name, sub, query

            # -- verbs -------------------------------------------------------

            def do_GET(self):
                server.request_count += 1
                if not self._auth_ok():
                    return
                routed = self._route()
                if routed is None:
                    return
                plural, ns, name, _, query = routed
                if name is not None:
                    obj = server.get_obj(plural, ns or "", name)
                    if obj is None:
                        self._status(404, "NotFound",
                                     f"{plural} {ns}/{name} not found")
                        return
                    self._json(200, obj)
                    return
                if query.get("watch") == "true":
                    if server.fail_watch_with is not None:
                        self._status(server.fail_watch_with, "InternalError",
                                     "injected watch fault")
                        return
                    self._watch(plural, ns, query)
                    return
                selector = {}
                for pair in (query.get("labelSelector") or "").split(","):
                    if "=" in pair:
                        k, v = pair.split("=", 1)
                        selector[k] = v
                with server._lock:
                    items = []
                    for (p, ons, _), obj in sorted(server._store.items()):
                        if p != plural:
                            continue
                        if ns is not None and ons != ns:
                            continue
                        labels = (obj.get("metadata") or {}).get("labels") or {}
                        if any(labels.get(k) != v for k, v in selector.items()):
                            continue
                        items.append(json.loads(json.dumps(obj)))
                    rv = str(server._rv)
                self._json(200, {"kind": "List", "apiVersion": "v1",
                                 "metadata": {"resourceVersion": rv},
                                 "items": items})

            def _watch(self, plural: str, ns: Optional[str], query):
                try:
                    since = int(query.get("resourceVersion") or 0)
                except ValueError:
                    since = 0
                timeout = float(query.get("timeoutSeconds") or 30)
                deadline = time.time() + timeout
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                # No Content-Length: stream until close.
                self.send_header("Connection", "close")
                self.end_headers()

                def emit(etype, obj):
                    line = json.dumps({"type": etype, "object": obj}) + "\n"
                    self.wfile.write(line.encode())
                    self.wfile.flush()

                with server._lock:
                    if since and since < server._log_start_rv:
                        emit("ERROR", {
                            "kind": "Status", "code": 410, "reason": "Expired",
                            "message": f"resourceVersion {since} is too old"})
                        return
                last = since
                try:
                    while time.time() < deadline:
                        with server._lock:
                            pending = [
                                (rv, et, obj) for rv, p, et, obj in server._log
                                if rv > last and p == plural
                                and (ns is None or (obj.get("metadata") or {})
                                     .get("namespace", "") == ns)]
                            if not pending:
                                server._lock.wait(
                                    min(0.2, max(deadline - time.time(), 0.0)))
                                continue
                        for rv, et, obj in pending:
                            emit(et, obj)
                            last = rv
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _read_body(self) -> Dict[str, Any]:
                length = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(length)) if length else {}

            def do_POST(self):
                server.request_count += 1
                if not self._auth_ok():
                    return
                routed = self._route()
                if routed is None:
                    return
                plural, ns, _, _, _ = routed
                obj = self._read_body()
                meta = obj.setdefault("metadata", {})
                if not meta.get("name"):
                    if meta.get("generateName"):
                        meta["name"] = meta["generateName"] + uuid.uuid4().hex[:5]
                    else:
                        self._status(422, "Invalid", "name required")
                        return
                if ns is not None:
                    meta["namespace"] = ns
                key = (plural, ns or "", meta["name"])
                with server._lock:
                    if key in server._store:
                        self._status(409, "AlreadyExists",
                                     f"{plural} {meta['name']} already exists")
                        return
                    meta.setdefault("uid", str(uuid.uuid4()))
                    meta.setdefault("creationTimestamp", _now_iso())
                    out = server._commit_locked(key, obj, "ADDED")
                self._json(201, out)

            def do_PUT(self):
                server.request_count += 1
                if not self._auth_ok():
                    return
                routed = self._route()
                if routed is None:
                    return
                plural, ns, name, sub, _ = routed
                body = self._read_body()
                key = (plural, ns or "", name)
                with server._lock:
                    cur = server._store.get(key)
                    if cur is None:
                        self._status(404, "NotFound",
                                     f"{plural} {ns}/{name} not found")
                        return
                    body_rv = (body.get("metadata") or {}).get(
                        "resourceVersion", "")
                    cur_rv = (cur.get("metadata") or {}).get(
                        "resourceVersion", "")
                    if body_rv and body_rv != cur_rv:
                        self._status(409, "Conflict",
                                     f"resourceVersion {body_rv} is stale "
                                     f"(current {cur_rv})")
                        return
                    if sub == "status":
                        nxt = json.loads(json.dumps(cur))
                        nxt["status"] = body.get("status", {})
                    else:
                        nxt = body
                        # Server-owned metadata survives the write.
                        nxt.setdefault("metadata", {})["uid"] = (
                            cur.get("metadata") or {}).get("uid", "")
                        nxt["metadata"].setdefault(
                            "creationTimestamp",
                            (cur.get("metadata") or {}).get(
                                "creationTimestamp"))
                        # Status-subresource semantics: a main-resource PUT
                        # never changes status (kube drops it; so do we --
                        # this is what catches controllers stashing state in
                        # the wrong half of the object).
                        if "status" in cur:
                            nxt["status"] = cur["status"]
                    out = server._commit_locked(key, nxt, "MODIFIED")
                self._json(200, out)

            def do_DELETE(self):
                server.request_count += 1
                if not self._auth_ok():
                    return
                routed = self._route()
                if routed is None:
                    return
                plural, ns, name, _, _ = routed
                key = (plural, ns or "", name)
                with server._lock:
                    if key not in server._store:
                        self._status(404, "NotFound",
                                     f"{plural} {ns}/{name} not found")
                        return
                    server._commit_locked(key, None, "DELETED")
                self._json(200, {"kind": "Status", "status": "Success"})

        return Handler
