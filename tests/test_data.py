"""Input pipeline: token format, stateless sampling, prefetch semantics."""

import sys

import numpy as np
import pytest

from conftest import apply_jax_platform_override

apply_jax_platform_override()

from trainingjob_operator_tpu.data import (  # noqa: E402
    Prefetcher,
    TokenDataset,
    write_tokens,
)


@pytest.fixture()
def corpus(tmp_path):
    path = str(tmp_path / "corpus.tokens")
    rng = np.random.default_rng(0)
    # Vocab 256 = the tiny model config's, so the workload integration test
    # exercises a MATCHED corpus (a larger corpus vocab is refused).
    toks = rng.integers(0, 256, size=5000, dtype=np.int64)
    write_tokens(path, toks, vocab_size=256)
    return path, toks


class TestTokenFormat:
    def test_roundtrip_uint16(self, corpus):
        path, toks = corpus
        ds = TokenDataset(path)
        assert len(ds) == len(toks)
        got = ds.batch(0, 4, 64)
        assert got.shape == (4, 65)
        assert got.dtype == np.int32

    def test_uint32_for_large_vocab(self, tmp_path):
        path = str(tmp_path / "big.tokens")
        toks = np.array([0, 70000, 123456], dtype=np.int64)
        write_tokens(path, toks)
        ds = TokenDataset(path)
        b = ds.batch(0, 2, 1)
        assert b.max() <= 123456
        assert len(ds) == 3

    def test_vocab_travels_in_header(self, corpus, tmp_path):
        path, _ = corpus
        assert TokenDataset(path).vocab_size == 256
        p2 = str(tmp_path / "auto.tokens")
        write_tokens(p2, np.array([3, 7, 11]))
        assert TokenDataset(p2).vocab_size == 12  # max id + 1

    def test_rejects_out_of_range_ids(self, tmp_path):
        p = str(tmp_path / "bad.tokens")
        with pytest.raises(ValueError, match="vocab_size"):
            write_tokens(p, np.array([0, 70000]), vocab_size=32000)
        with pytest.raises(ValueError, match="negative"):
            write_tokens(p, np.array([-1, 3]))

    def test_rejects_garbage(self, tmp_path):
        p = tmp_path / "bad.tokens"
        p.write_bytes(b"not a token file at all")
        with pytest.raises(ValueError, match="token file"):
            TokenDataset(str(p))

    def test_window_content_matches_stream(self, corpus):
        path, toks = corpus
        ds = TokenDataset(path, seed=3)
        batch = ds.batch(7, 8, 32)
        offs = ds._offsets(7, 8, 33)
        for row, off in zip(batch, offs):
            np.testing.assert_array_equal(row, toks[off:off + 33])


class TestStatelessSampling:
    def test_deterministic_across_instances(self, corpus):
        path, _ = corpus
        a = TokenDataset(path, seed=1).batch(5, 4, 16)
        b = TokenDataset(path, seed=1).batch(5, 4, 16)
        np.testing.assert_array_equal(a, b)

    def test_step_and_seed_vary(self, corpus):
        path, _ = corpus
        ds = TokenDataset(path, seed=1)
        assert not np.array_equal(ds.batch(0, 4, 16), ds.batch(1, 4, 16))
        ds2 = TokenDataset(path, seed=2)
        assert not np.array_equal(ds.batch(0, 4, 16), ds2.batch(0, 4, 16))

    def test_width_independent_global_batch(self, corpus):
        # The elastic contract: a width-w process taking its rows of the
        # global batch sees exactly the full-width content -- resume at any
        # width replays the identical token sequence.
        path, _ = corpus
        ds = TokenDataset(path, seed=9)
        full = ds.batch(11, 8, 16)
        for width in (1, 2, 4, 8):
            rows = 8 // width
            parts = [ds.batch(11, 8, 16, rows=slice(p * rows, (p + 1) * rows))
                     for p in range(width)]
            np.testing.assert_array_equal(np.concatenate(parts), full)

    def test_offsets_cover_stream(self, corpus):
        # The hash must not cluster: over many steps, window starts span
        # effectively the whole stream.
        path, toks = corpus
        ds = TokenDataset(path, seed=4)
        offs = np.concatenate([ds._offsets(s, 32, 65) for s in range(64)])
        span = len(toks) - 65
        assert offs.min() < span * 0.02
        assert offs.max() > span * 0.98
        # No pathological duplication either.
        assert len(np.unique(offs)) > len(offs) * 0.7

    def test_too_short_stream_raises(self, tmp_path):
        path = str(tmp_path / "short.tokens")
        write_tokens(path, np.arange(10))
        with pytest.raises(ValueError, match="tokens < window"):
            TokenDataset(path).batch(0, 1, 32)


class TestPrefetcher:
    def test_yields_in_order(self):
        with Prefetcher(lambda s: s * 10, 3, 8) as pf:
            got = list(pf)
        assert got == [(s, s * 10) for s in range(3, 8)]

    def test_propagates_producer_error(self):
        def fetch(s):
            if s == 2:
                raise RuntimeError("disk on fire")
            return s

        pf = Prefetcher(fetch, 0, 5)
        assert next(pf) == (0, 0)
        assert next(pf) == (1, 1)
        with pytest.raises(RuntimeError, match="disk on fire"):
            next(pf)

    def test_close_mid_stream(self):
        pf = Prefetcher(lambda s: s, 0, 1000)
        assert next(pf)[0] == 0
        pf.close()  # must not hang on the blocked producer
        assert not pf._thread.is_alive()

    def test_runs_ahead_of_consumer(self):
        import threading

        started = []
        gate = threading.Event()

        def fetch(s):
            started.append(s)
            if s >= 1:
                gate.set()  # step 1 fetched before step 0 consumed
            return s

        pf = Prefetcher(fetch, 0, 4, depth=2)
        assert gate.wait(timeout=5.0)
        assert started[0:2] == [0, 1]
        assert list(pf) == [(s, s) for s in range(4)]


class TestWorkloadIntegration:
    def test_llama_elastic_uses_corpus(self, corpus, tmp_path, monkeypatch):
        # End-to-end: file-backed batches through the shared elastic loop.
        path, _ = corpus
        monkeypatch.setenv("LLAMA_DATA", path)
        monkeypatch.setenv("LLAMA_BATCH", "16")
        monkeypatch.setenv("LLAMA_STEPS", "2")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv("LLAMA_CKPT_EVERY", "100")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR",
                           str(tmp_path / "ckpt"))
        monkeypatch.setenv("TRAININGJOB_JAX_PLATFORM", "cpu")
        from trainingjob_operator_tpu.workloads import llama_elastic

        assert llama_elastic.main() == 0

    def test_make_corpus_byte_level(self, tmp_path):
        import tools.make_corpus as mc

        txt = tmp_path / "a.txt"
        txt.write_text("hello tokens")
        out = str(tmp_path / "a.tokens")
        assert mc.main([out, str(txt)]) == 0
        ds = TokenDataset(out)
        assert ds.vocab_size == 256
        assert bytes(ds._tokens[:5].astype(np.uint8)) == b"hello"

    def test_eval_stream_is_heldout_and_printed(self, corpus, tmp_path,
                                                monkeypatch, capsys):
        path, _ = corpus
        monkeypatch.setenv("LLAMA_DATA", path)
        monkeypatch.setenv("LLAMA_BATCH", "16")
        monkeypatch.setenv("LLAMA_STEPS", "2")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv("LLAMA_CKPT_EVERY", "100")
        monkeypatch.setenv("LLAMA_EVAL_EVERY", "2")
        monkeypatch.setenv("LLAMA_EVAL_BATCHES", "1")
        monkeypatch.setenv("TRAININGJOB_JAX_PLATFORM", "cpu")
        from trainingjob_operator_tpu.workloads import llama_elastic

        assert llama_elastic.main() == 0
        out = capsys.readouterr().out
        assert "eval step 2 loss" in out
        # The split holds DISJOINT tokens: train windows stay in the first
        # 90% of the stream, eval windows in the last 10%.
        ds_train = TokenDataset(path, seed=17, region=(0.0, 0.9))
        ds_eval = TokenDataset(path, seed=17, region=(0.9, 1.0))
        n = len(ds_train)
        train_offs = ds_train._offsets(0, 64, 17)
        eval_offs = ds_eval._offsets(0, 64, 17)
        assert train_offs.max() + 17 <= int(n * 0.9)
        assert eval_offs.min() >= int(n * 0.9)

    def test_region_restricts_and_rejects(self, corpus):
        path, toks = corpus
        tail = TokenDataset(path, seed=1, region=(0.9, 1.0))
        batch = tail.batch(3, 4, 16)
        lo = int(len(toks) * 0.9)
        for row, off in zip(batch, tail._offsets(3, 4, 17)):
            assert off >= lo
            np.testing.assert_array_equal(row, toks[off:off + 17])
        with pytest.raises(ValueError, match="bad region"):
            TokenDataset(path, region=(0.5, 0.4))
        with pytest.raises(ValueError, match="region"):
            TokenDataset(path, region=(0.999, 1.0)).batch(0, 1, 64)

    def test_llama_elastic_refuses_vocab_mismatch(self, tmp_path,
                                                  monkeypatch):
        big = str(tmp_path / "big.tokens")
        write_tokens(big, np.array([0, 31999]), vocab_size=32000)
        monkeypatch.setenv("LLAMA_DATA", big)
        monkeypatch.setenv("LLAMA_BATCH", "16")
        monkeypatch.setenv("LLAMA_STEPS", "1")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv("TRAININGJOB_JAX_PLATFORM", "cpu")
        from trainingjob_operator_tpu.workloads import llama_elastic

        with pytest.raises(ValueError, match="corpus vocab"):
            llama_elastic.main()


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))


class TestPrefetcherStall:
    """ADVICE r4: a slow-but-alive producer warns and keeps waiting (one cold
    NFS page-in must not abort un-checkpointed training); the hard error is
    reserved for a dead producer."""

    def test_slow_fetch_warns_but_succeeds(self, monkeypatch, capsys):
        import time as _time

        monkeypatch.setenv("TRAININGJOB_PREFETCH_STALL_S", "0.1")

        def fetch(s):
            if s == 0:
                _time.sleep(0.5)
            return s

        with Prefetcher(fetch, 0, 2) as pf:
            got = list(pf)
        assert got == [(0, 0), (1, 1)]
        assert "prefetcher stalled" in capsys.readouterr().out

    def test_dead_producer_raises(self, monkeypatch):
        monkeypatch.setenv("TRAININGJOB_PREFETCH_STALL_S", "0.1")
        pf = Prefetcher(lambda s: s, 0, 1)
        assert next(pf) == (0, 0)
        with pytest.raises(StopIteration):
            next(pf)
        # Producer gone AND queue empty -> hard error, not an endless wait.
        pf2 = Prefetcher(lambda s: s, 0, 1)
        pf2._thread.join(timeout=5.0)
        pf2._q.get()  # steal the item; queue now empty, thread dead
        pf2._q.get()  # the _DONE sentinel too
        with pytest.raises(RuntimeError, match="died"):
            next(pf2)
