"""Serving plane: continuous-batching scheduler semantics, per-slot KV
paging, telemetry/scale plumbing, and the sim e2e traffic-aware scale path.

Unit layer first (scheduler driven tick by tick against a tiny llama --
admission order, backpressure, prefill/decode interleave), then the
decisive content checks (slot reuse must not leak KV; the serve path must
reproduce offline ``decode.generate``), then the obs plane (serve record
ingest, gauges, /debug/serve), then e2e: a sim ``serve`` replica group
under queue-depth telemetry must scale out on backlog and back in when
idle, riding the scope=Resize survivor-keepalive path (no restart-all).

Content comparisons run in float32: chunked prefill and the flash prefill
are different reduction orders, and in bf16 an exact top-2 logit tie can
argmax differently across paths.  Within one path bf16 is deterministic;
across paths only fp32 is exact.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from conftest import apply_jax_platform_override, wait_for

apply_jax_platform_override()

import jax  # noqa: E402

from trainingjob_operator_tpu.models import decode, llama  # noqa: E402
from trainingjob_operator_tpu.workloads import serve  # noqa: E402


def _f32_tiny():
    base = llama.LlamaConfig.tiny()
    return llama.LlamaConfig(**{**base.__dict__, "dtype": "float32"})


@pytest.fixture(scope="module")
def f32_setup():
    cfg = _f32_tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _service(params, cfg, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("queue_cap", 64)
    return serve.DecodeService(params, cfg, **kw)


def _run_until_done(svc, reqs, max_ticks=500):
    done = []
    for _ in range(max_ticks):
        done.extend(svc.step())
        if all(r.finished for r in reqs):
            return done
    raise AssertionError(f"requests did not finish in {max_ticks} ticks")


class TestSchedulerAdmission:
    def test_fifo_admission_and_eviction_order(self, f32_setup):
        # 4 requests through 2 slots: r0/r1 admitted first; each freed slot
        # goes to the NEXT queued request (r2 before r3), and completions
        # come back shortest-budget-first within the running pair.
        cfg, params = f32_setup
        svc = _service(params, cfg, slots=2)
        prompt = [1, 2, 3]
        reqs = [svc.submit(prompt, budget, now=0.0)
                for budget in (2, 6, 2, 2)]

        done = svc.step(now=1.0)
        assert reqs[0].slot == 0 and reqs[1].slot == 1
        assert reqs[2].slot == -1 and reqs[3].slot == -1  # still queued

        done = done + _run_until_done(svc, reqs)
        # Waiters enter in queue order as slots free.
        assert reqs[2].admitted <= reqs[3].admitted
        assert 0.0 < reqs[0].admitted <= reqs[2].admitted
        # Eviction: a request leaves the tick it finishes, so the 2-token
        # r0 evicts before the 6-token r1 that was admitted alongside it.
        order = [r.rid for r in done]
        assert order.index(0) < order.index(1)
        assert sorted(order) == [0, 1, 2, 3]
        assert all(len(r.tokens) == r.max_new_tokens for r in reqs)
        assert svc.completed_total == 4
        assert all(sl.state == serve.FREE for sl in svc.slots)

    def test_static_policy_gang_admission(self, f32_setup):
        # The A/B baseline: with one slot still busy, NOTHING admits --
        # the freed slot idles until the straggler finishes (the cost
        # continuous batching removes, and what bench.py measures).
        cfg, params = f32_setup
        svc = _service(params, cfg, slots=2, policy="static")
        short = svc.submit([1, 2], 1, now=0.0)
        long = svc.submit([1, 2], 8, now=0.0)
        waiter = svc.submit([1, 2], 1, now=0.0)

        while not short.finished:
            svc.step(now=1.0)
        # short's slot is free but long still runs: waiter must NOT admit.
        for _ in range(3):
            svc.step(now=2.0)
            if not long.finished:
                assert waiter.slot == -1
        while not long.finished:
            svc.step(now=3.0)
        svc.step(now=4.0)
        assert waiter.slot != -1  # all-free batch formed

    def test_queue_full_raises_and_counts(self, f32_setup):
        cfg, params = f32_setup
        svc = _service(params, cfg, queue_cap=3)
        for _ in range(3):
            svc.submit([1, 2], 1)
        with pytest.raises(serve.QueueFull):
            svc.submit([1, 2], 1)
        assert svc.rejected_total == 1
        # Backpressure is capacity-based, not permanent: draining readmits.
        svc.step()
        svc.submit([1, 2], 1)

    def test_submit_validates_cache_fit(self, f32_setup):
        cfg, params = f32_setup
        svc = _service(params, cfg, max_len=16)
        with pytest.raises(ValueError):
            svc.submit(list(range(1, 13)), 8)  # 12 + 8 > 16
        with pytest.raises(ValueError):
            svc.submit([], 4)
        with pytest.raises(ValueError):
            svc.submit([1], 0)

    def test_sliding_window_config_rejected(self, f32_setup):
        import dataclasses

        cfg, params = f32_setup
        windowed = dataclasses.replace(cfg, sliding_window=8)
        with pytest.raises(ValueError, match="sliding_window"):
            serve.DecodeService(params, windowed)


class TestPrefillDecodeInterleave:
    def test_long_prompt_does_not_stall_decode(self, f32_setup):
        # One request already decoding, then a LONG prompt arrives.  With
        # chunked prefill the decoder must keep emitting one token per
        # tick while the prompt pages in -- a scheduler that runs prefill
        # to completion first shows a multi-tick gap here.
        cfg, params = f32_setup
        svc = _service(params, cfg, slots=2, prefill_chunk=4)
        decoder = svc.submit([5, 6, 7], 24, now=0.0)
        while not decoder.tokens:
            svc.step(now=0.0)

        long_prompt = [1 + (i % 100) for i in range(20)]  # 5 chunks
        waiter = svc.submit(long_prompt, 2, now=0.0)
        while waiter.slot == -1:
            svc.step(now=0.0)
        emitted_before = len(decoder.tokens)
        remaining = len(long_prompt) - svc.slots[waiter.slot].prefill_pos
        ticks = 0
        while not waiter.tokens and ticks < 50:
            svc.step(now=0.0)
            ticks += 1
        assert waiter.tokens, "prefill never completed"
        # One chunk per tick, never more: the decode stall is bounded.
        assert ticks == -(-remaining // svc.prefill_chunk)
        assert ticks >= 2  # genuinely multi-tick: the interleave had teeth
        # The decoder emitted on EVERY interleaved tick.
        assert len(decoder.tokens) - emitted_before == ticks

    def test_prefill_round_robin_is_fair(self, f32_setup):
        # Two long prompts prefill concurrently: the round-robin cursor
        # must alternate chunks, so both finish within one chunk-count of
        # each other instead of one starving.
        cfg, params = f32_setup
        svc = _service(params, cfg, slots=2, prefill_chunk=4)
        a = svc.submit([1 + (i % 100) for i in range(16)], 1, now=0.0)
        b = svc.submit([2 + (i % 100) for i in range(16)], 1, now=0.0)
        ticks_to_first = {}
        for tick in range(40):
            svc.step(now=0.0)
            for name, req in (("a", a), ("b", b)):
                if req.tokens and name not in ticks_to_first:
                    ticks_to_first[name] = tick
            if len(ticks_to_first) == 2:
                break
        assert len(ticks_to_first) == 2
        # 4 chunks each, alternating: first tokens land 1 tick apart.
        assert abs(ticks_to_first["a"] - ticks_to_first["b"]) <= 1


class TestSlotPagingNoStaleKV:
    def test_slot_reuse_two_sequence_content_check(self, f32_setup):
        # THE paging invariant: decode request A in a fresh slot, then
        # decode it again in a slot that just held an unrelated longer
        # request B.  Greedy decode must produce byte-identical tokens --
        # any divergence means reset_slot left B's K/V visible to A.
        cfg, params = f32_setup
        prompt_a = [3, 1, 4, 1, 5, 9, 2, 6]
        prompt_b = [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5]

        fresh = _service(params, cfg, slots=1)
        ref = fresh.submit(prompt_a, 12, now=0.0)
        _run_until_done(fresh, [ref])

        reused = _service(params, cfg, slots=1)
        filler = reused.submit(prompt_b, 16, now=0.0)
        again = reused.submit(prompt_a, 12, now=0.0)  # queued behind B
        _run_until_done(reused, [filler, again])

        assert again.slot == filler.slot == 0
        assert again.tokens == ref.tokens, \
            "slot reuse leaked stale KV into the next occupant"
        assert filler.tokens != ref.tokens  # different request, not frozen

    def test_serve_matches_offline_generate(self, f32_setup):
        # Cross-path check: the chunked-prefill + slot-paged serve path
        # must reproduce the offline scan-based generate exactly (fp32;
        # both are greedy).  Catches position-offset and masking bugs the
        # self-consistency check above cannot.
        import jax.numpy as jnp

        cfg, params = f32_setup
        prompt = [7, 3, 11, 2, 9, 4]
        steps = 10
        offline = decode.generate(
            params, jnp.asarray([prompt], jnp.int32), cfg, steps=steps)
        svc = _service(params, cfg, slots=2, prefill_chunk=4)
        req = svc.submit(prompt, steps, now=0.0)
        _run_until_done(svc, [req])
        assert req.tokens == np.asarray(offline[0]).tolist()

    def test_traffic_run_has_zero_violations(self, f32_setup):
        # The smoke-level detector over real churned traffic: repeated
        # template prompts land in different, previously-used slots and
        # must still decode identically.
        cfg, params = f32_setup
        svc = _service(params, cfg, slots=3, prefill_chunk=4)
        traffic = serve.synthetic_traffic(
            24, seed=3, rate=1.5, vocab=cfg.vocab_size,
            prompt_lens=(3, 10), out_tokens=(2, 12))
        result = serve.run_traffic(svc, traffic)
        s = result["stats"]
        assert s["completed_total"] == s["submitted"] > 0
        assert s["stale_kv_violations"] == 0
        # Distinct requests exercised distinct slots (the check had teeth).
        assert len({r.slot for r in result["completed"]}) > 1


class TestServeTelemetry:
    def _agg(self):
        from trainingjob_operator_tpu.obs.goodput import GoodputTracker
        from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
        from trainingjob_operator_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        return TelemetryAggregator(
            metrics=m, goodput=GoodputTracker(metrics=m)), m

    def _serve_rec(self, job="default/sj", depth=5.0, **extra):
        rec = {"v": 1, "job": job, "rtype": "serve", "rank": 0,
               "serve_queue_depth": depth, "serve_active_slots": 3,
               "serve_slots": 4, "serve_p50_ms": 12.0, "serve_p99_ms": 80.0,
               "serve_tokens_per_sec": 250.0, "serve_completed": 17}
        rec.update(extra)
        return rec

    def test_ingest_snapshot_and_gauges(self):
        agg, m = self._agg()
        assert agg.ingest(self._serve_rec(), now=100.0)
        snap = agg.serve_stats("default/sj")
        assert snap["queue_depth"] == 5.0 and snap["at"] == 100.0
        text = m.render_prometheus()
        assert 'trainingjob_serve_queue_depth{job="default/sj"} 5.0' in text
        assert 'trainingjob_serve_token_latency_ms{job="default/sj"} 80.0' \
            in text
        assert 'trainingjob_serve_tokens_per_sec{job="default/sj"} 250.0' \
            in text
        assert 'trainingjob_serve_batch_occupancy{job="default/sj"} 0.75' \
            in text
        # Later snapshots replace, never duplicate, the gauges.
        assert agg.ingest(self._serve_rec(depth=0.0), now=101.0)
        assert agg.serve_stats("default/sj")["queue_depth"] == 0.0
        assert m.render_prometheus().count(
            "trainingjob_serve_queue_depth{") == 1

    def test_malformed_serve_records_counted(self):
        agg, m = self._agg()
        assert not agg.ingest(self._serve_rec(depth="nan-ish"), now=1.0)
        assert not agg.ingest(self._serve_rec(depth=-2.0), now=1.0)
        assert not agg.ingest(self._serve_rec(job="nonamespace"), now=1.0)
        assert agg.serve_stats("default/sj") is None
        assert "trainingjob_telemetry_malformed_total 3" in \
            m.render_prometheus()

    def test_forget_drops_serve_gauges(self):
        agg, m = self._agg()
        agg.ingest(self._serve_rec(), now=1.0)
        agg.forget("default/sj")
        assert agg.serve_stats("default/sj") is None
        assert "trainingjob_serve" not in m.render_prometheus()

    def test_emitter_serve_record_over_the_wire(self, monkeypatch):
        from trainingjob_operator_tpu.api import constants
        from trainingjob_operator_tpu.obs.telemetry import (
            TelemetryEmitter,
            TelemetrySink,
        )

        agg, _ = self._agg()
        sink = TelemetrySink(aggregator=agg, publish=False).start()
        try:
            monkeypatch.setenv(constants.TELEMETRY_ADDR_ENV, sink.address)
            monkeypatch.setenv(constants.JOB_NAMESPACE_ENV, "default")
            monkeypatch.setenv(constants.JOB_NAME_ENV, "sj")
            monkeypatch.setenv(constants.REPLICA_NAME_ENV, "serve")
            em = TelemetryEmitter()
            assert em.enabled
            em.emit_serve(queue_depth=9, active_slots=4, slots=4,
                          p50_ms=10.0, p99_ms=44.0, tokens_per_sec=123.0,
                          completed=2)
            em.close()
            assert wait_for(
                lambda: agg.serve_stats("default/sj") is not None, 5)
            snap = agg.serve_stats("default/sj")
            assert snap["queue_depth"] == 9.0 and snap["p99_ms"] == 44.0
        finally:
            sink.stop()


class TestDebugServeEndpoint:
    @pytest.fixture
    def server(self):
        from trainingjob_operator_tpu.obs.goodput import GoodputTracker
        from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
        from trainingjob_operator_tpu.utils.metrics import (
            MetricsRegistry,
            serve_metrics,
        )

        m = MetricsRegistry()
        agg = TelemetryAggregator(metrics=m,
                                  goodput=GoodputTracker(metrics=m))
        agg.ingest({"v": 1, "job": "default/sj", "serve_queue_depth": 7,
                    "serve_active_slots": 2, "serve_slots": 4,
                    "serve_p99_ms": 33.0, "serve_tokens_per_sec": 99.0},
                   now=50.0)
        srv = serve_metrics(0, MetricsRegistry(), telemetry=agg)
        yield srv.server_address[1]
        srv.shutdown()

    @staticmethod
    def _get(port, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as resp:
            return resp.status, resp.read().decode()

    def test_job_snapshot_json(self, server):
        status, body = self._get(server, "/debug/serve?job=default/sj")
        doc = json.loads(body)
        assert status == 200 and doc["job"] == "default/sj"
        assert doc["serve"]["queue_depth"] == 7.0
        assert doc["serve"]["occupancy"] == 0.5

    def test_job_list_without_param(self, server):
        status, body = self._get(server, "/debug/serve")
        doc = json.loads(body)
        assert status == 200 and doc == {"count": 1,
                                         "jobs": ["default/sj"]}

    def test_unknown_job_404(self, server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            self._get(server, "/debug/serve?job=no/such")
        assert exc.value.code == 404


class TestServeScaleE2E:
    """Queue-depth telemetry -> controller scale decision, end to end on
    the sim cluster.  The serve group rides scope=Resize: scale-out only
    raises the elastic width (survivors keep serving), scale-in deletes
    the highest index -- never a restart-all."""

    @pytest.fixture
    def cluster(self):
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.cmd.options import OperatorOptions
        from trainingjob_operator_tpu.controller.controller import (
            TrainingJobController,
        )
        from trainingjob_operator_tpu.obs.telemetry import TELEMETRY
        from trainingjob_operator_tpu.runtime.sim import SimRuntime

        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        sim = SimRuntime(cs)
        sim.add_node("n0")
        sim.start()
        tc.run(workers=2)
        jobs = []
        yield cs, tc, sim, jobs
        tc.stop()
        sim.stop()
        for name in jobs:
            TELEMETRY.forget(f"default/{name}")

    @staticmethod
    def _serve_job(name, replicas, queue_depth, *, max_replicas=None,
                   active=None):
        from trainingjob_operator_tpu.api.types import (
            EdlPolicy,
            ReplicaSpec,
            RestartScope,
            TPUTrainingJob,
        )
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ContainerPort,
            ObjectMeta,
            PodSpec,
            PodTemplateSpec,
        )
        from trainingjob_operator_tpu.runtime.sim import (
            RUN_SECONDS_ANNOTATION,
            SERVE_ACTIVE_ANNOTATION,
            SERVE_QUEUE_ANNOTATION,
            SERVE_SLOTS_ANNOTATION,
        )

        ann = {RUN_SECONDS_ANNOTATION: "60",
               SERVE_QUEUE_ANNOTATION: str(queue_depth),
               SERVE_SLOTS_ANNOTATION: "4"}
        if active is not None:
            ann[SERVE_ACTIVE_ANNOTATION] = str(active)
        job = TPUTrainingJob(
            metadata=ObjectMeta(name=name, namespace="default"))
        template = PodTemplateSpec(
            metadata=ObjectMeta(annotations=ann),
            spec=PodSpec(containers=[
                Container(name="aitj-main",
                          ports=[ContainerPort(name="aitj-7777",
                                               container_port=7777)])]))
        job.spec.replica_specs["serve"] = ReplicaSpec(
            replicas=replicas, min_replicas=1, max_replicas=max_replicas,
            template=template, edl_policy=EdlPolicy.AUTO,
            restart_scope=RestartScope.RESIZE)
        return job

    def test_scale_out_on_backlog(self, cluster):
        cs, tc, sim, jobs = cluster
        jobs.append("serve-out")
        # 32 backlogged requests >> the scale-up threshold (8): the
        # controller must raise the elastic width toward maxReplicas and
        # the creation loop must materialize the new index.
        cs.trainingjobs.create(
            self._serve_job("serve-out", 1, 32, max_replicas=3))

        def scaled():
            got = cs.trainingjobs.get("default", "serve-out")
            return got.status.elastic_replicas.get("serve", 0) >= 2
        assert wait_for(scaled, 15)
        assert wait_for(lambda: len(cs.pods.list("default")) >= 2, 10)
        got = cs.trainingjobs.get("default", "serve-out")
        assert got.status.last_scale_times.get("serve", 0.0) > 0.0

    def test_scale_in_when_idle_keeps_survivor(self, cluster):
        from trainingjob_operator_tpu.api.types import TrainingJobPhase
        from trainingjob_operator_tpu.controller.naming import pod_index

        cs, tc, sim, jobs = cluster
        jobs.append("serve-in")
        # Empty queue + idle slots at width 2: shrink to the minReplicas
        # floor by deleting the HIGHEST index; index 0 must keep its uid
        # (survivor-keepalive -- a serving replica never restarts to
        # shrink its group).
        cs.trainingjobs.create(self._serve_job("serve-in", 2, 0, active=0))
        assert wait_for(
            lambda: cs.trainingjobs.get("default", "serve-in")
            .status.phase == TrainingJobPhase.RUNNING, 15)
        uid0 = {pod_index(p): p.metadata.uid
                for p in cs.pods.list("default")
                if "serve-in" in p.name}.get(0)
        assert uid0 is not None

        def shrunk():
            got = cs.trainingjobs.get("default", "serve-in")
            return got.status.elastic_replicas.get("serve") == 1
        assert wait_for(shrunk, 15)
        assert wait_for(lambda: len(
            [p for p in cs.pods.list("default")
             if "serve-in" in p.name]) == 1, 10)
        survivor = [p for p in cs.pods.list("default")
                    if "serve-in" in p.name][0]
        assert pod_index(survivor) == 0
        assert survivor.metadata.uid == uid0
