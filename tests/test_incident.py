"""Incident flight recorder: attribution math, bundle lifecycle/retention,
the /debug/incidents endpoint, the widened /debug/steps columns, and the
sim e2e preemption acceptance.

Unit layer first (a private IncidentRecorder + registry driven with explicit
timestamps -- assembly is a pure function of the ring, so the tests pin the
phase arithmetic exactly), then retention/eviction and the metric surface,
then HTTP, then e2e: a sim job killed with exit 137 (restart scope ALL) must
leave an amended bundle whose phases sum to its downtime with no meaningful
``unknown`` residue, whose control window matches the goodput ledger, and
whose serialization is byte-stable across re-assembly.
"""

import json
import urllib.error
import urllib.request

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.goodput import GoodputTracker
from trainingjob_operator_tpu.obs.incident import (
    INCIDENTS,
    PHASES,
    IncidentRecorder,
    bundle_to_chrome,
)
from trainingjob_operator_tpu.obs.telemetry import (
    TELEMETRY,
    TelemetryAggregator,
)
from trainingjob_operator_tpu.utils.metrics import (
    METRICS,
    MetricsRegistry,
    serve_metrics,
)

from conftest import wait_for  # noqa: E402

JOB = "default/incjob"


def _rec(ring=64, keep=4):
    return IncidentRecorder(metrics=MetricsRegistry(), ring=ring, keep=keep)


def _phases_sum(bundle):
    return sum(bundle["phases"].values())


def _restart_window(rec, t0=100.0, job=JOB, scope="ALL"):
    """Drive one canonical control window: interruption at ``t0``, corrective
    event at +0.2, delete at +0.5, create at +1.0, Running at +2.0."""
    rec.on_interruption(job, scope, constants.RESTARTING_REASON, now=t0)
    rec.record_event(job, constants.RESTARTING_REASON, "restarting",
                     ts=t0 + 0.2)
    rec.record_event(job, constants.SUCCESSFUL_DELETE_POD_REASON, "del p0",
                     ts=t0 + 0.5)
    rec.record_event(job, constants.SUCCESSFUL_CREATE_POD_REASON, "create p0",
                     ts=t0 + 1.0)
    rec.on_running(job, now=t0 + 2.0)


# -- attribution unit layer ---------------------------------------------------

class TestAttribution:
    def test_provisional_bundle_partitions_control_window(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["kind"] == "restart"
        assert bundle["reason"] == constants.RESTARTING_REASON
        assert bundle["scope"] == "ALL"
        assert bundle["running_at"] == 102.0
        assert bundle["downtime_ms"] == 2000.0
        assert bundle["control_downtime_ms"] == 2000.0
        assert bundle["phases"]["detect"] == pytest.approx(200.0)
        assert bundle["phases"]["teardown"] == pytest.approx(300.0)
        assert bundle["phases"]["reschedule"] == pytest.approx(500.0)
        # No workload evidence yet: the tail up to Running is rendezvous.
        assert bundle["phases"]["rendezvous"] == pytest.approx(1000.0)
        assert bundle["phases"]["unknown"] == 0.0
        assert _phases_sum(bundle) == pytest.approx(bundle["downtime_ms"])

    def test_first_step_amends_with_overlapped_resume_tail(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        # Overlapped restore+compile: only the non-hidden compile tail
        # (500 - 300 = 200 ms) is charged to ``compile``.
        rec.record_resume(JOB, restore_ms=300.0, compile_ms=500.0,
                          overlapped=True, now=102.9)
        rec.record_step(JOB, step=5, ms=100.0, now=103.0)
        bundles = rec.bundles(JOB)
        assert len(bundles) == 1  # amended in place, same incident
        bundle = bundles[0]
        assert bundle["id"] == 1
        assert bundle["downtime_ms"] == 3000.0
        assert bundle["control_downtime_ms"] == 2000.0
        assert bundle["phases"]["rendezvous"] == pytest.approx(1400.0)
        assert bundle["phases"]["restore"] == pytest.approx(300.0)
        assert bundle["phases"]["compile"] == pytest.approx(200.0)
        assert bundle["phases"]["first_step"] == pytest.approx(100.0)
        assert _phases_sum(bundle) == pytest.approx(bundle["downtime_ms"])
        assert rec.open_incident(JOB) is None  # amend closed the incident

    def test_serial_resume_charges_full_compile(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        rec.record_resume(JOB, restore_ms=300.0, compile_ms=500.0,
                          overlapped=False, now=102.9)
        rec.record_step(JOB, step=5, ms=100.0, now=103.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["phases"]["restore"] == pytest.approx(300.0)
        assert bundle["phases"]["compile"] == pytest.approx(500.0)
        assert _phases_sum(bundle) == pytest.approx(bundle["downtime_ms"])

    def test_first_step_without_resume_evidence(self):
        rec = _rec()
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=200.0)
        rec.record_event(JOB, constants.RESTARTING_REASON, "restarting",
                         ts=200.1)
        rec.record_event(JOB, constants.SUCCESSFUL_CREATE_POD_REASON,
                         "create", ts=200.5)
        rec.on_running(JOB, now=201.0)
        rec.record_step(JOB, step=7, ms=500.0, now=201.8)
        (bundle,) = rec.bundles(JOB)
        assert bundle["phases"]["detect"] == pytest.approx(100.0)
        assert bundle["phases"]["teardown"] == 0.0
        assert bundle["phases"]["reschedule"] == pytest.approx(400.0)
        # The step's own duration is first_step; the rest is rendezvous.
        assert bundle["phases"]["first_step"] == pytest.approx(500.0)
        assert bundle["phases"]["rendezvous"] == pytest.approx(800.0)
        assert _phases_sum(bundle) == pytest.approx(1800.0)

    def test_empty_window_is_unknown_not_invented(self):
        rec = _rec()
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=300.0)
        rec.on_running(JOB, now=301.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["phases"]["unknown"] == pytest.approx(1000.0)
        assert _phases_sum(bundle) == pytest.approx(1000.0)

    def test_stall_incident_is_all_detect(self):
        rec = _rec()
        rec.record_event(JOB, constants.STEP_STALLED_REASON, "rank 2 stuck",
                         ts=400.0)
        assert rec.open_incident(JOB)["kind"] == "stall"
        rec.record_event(JOB, constants.STEP_RESUMED_REASON, "resumed",
                         ts=405.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["kind"] == "stall"
        assert bundle["downtime_ms"] == 5000.0
        assert bundle["phases"]["detect"] == pytest.approx(5000.0)

    def test_restart_adopts_open_stall(self):
        rec = _rec()
        rec.record_event(JOB, constants.STEP_STALLED_REASON, "stuck", ts=500.0)
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=502.0)
        inc = rec.open_incident(JOB)
        assert inc["kind"] == "restart"
        assert inc["scope"] == "ALL"
        assert inc["started"] == 500.0  # the stall detected it first
        rec.on_running(JOB, now=503.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["downtime_ms"] == 3000.0
        assert _phases_sum(bundle) == pytest.approx(3000.0)

    def test_reentry_mid_window_is_idempotent(self):
        rec = _rec()
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=600.0)
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=600.5)
        inc = rec.open_incident(JOB)
        assert inc["id"] == 1 and inc["started"] == 600.0

    def test_abnormal_completion_synthesizes_terminal_incident(self):
        rec = _rec()
        rec.record_event(JOB, constants.EXITED_WITH_CODE_REASON, "exit 137",
                         ts=600.0)
        rec.record_event(JOB, constants.TERMINATING_REASON, "tearing down",
                         ts=600.4)
        rec.on_complete(JOB, "Preempted", now=601.0)
        (bundle,) = rec.bundles(JOB)
        assert bundle["kind"] == "terminal"
        assert bundle["reason"] == "TrainingJobPreempted"
        assert bundle["started"] == 600.0  # anchored at earliest evidence
        assert bundle["running_at"] is None
        assert bundle["control_downtime_ms"] is None
        assert bundle["phases"]["detect"] == pytest.approx(400.0)
        assert bundle["phases"]["teardown"] == pytest.approx(600.0)
        # Completed jobs accept no further incidents.
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=700.0)
        assert rec.open_incident(JOB) is None
        rec.record_event(JOB, constants.STEP_STALLED_REASON, "x", ts=701.0)
        assert rec.open_incident(JOB) is None

    def test_normal_completion_without_incident_is_silent(self):
        rec = _rec()
        rec.on_complete(JOB, "Succeeded", now=100.0)
        assert rec.bundles(JOB) is None  # no state was ever created


# -- determinism + retention + metric surface ---------------------------------

class TestBundleLifecycle:
    def test_serialization_is_byte_stable(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        rec.record_resume(JOB, 300.0, 500.0, True, now=102.9)
        rec.record_step(JOB, 5, 100.0, ckpt_ms=2.5, hbm_bytes=1e9, now=103.0)
        first = rec.bundle_json(JOB)
        assert first is not None
        # reassemble re-runs _assemble from the frozen ring snapshot; the
        # determinism contract is byte equality, twice over.
        assert rec.reassemble(JOB) == first
        assert rec.reassemble(JOB) == first
        assert rec.bundle_json(JOB) == first
        assert json.loads(first)["timeline"]  # and it still parses

    def test_chrome_export_is_perfetto_shaped(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        doc = json.loads(rec.export_chrome(JOB))
        assert doc["displayTimeUnit"] == "ms"
        names = {ev["name"] for ev in doc["traceEvents"] if ev["ph"] == "X"}
        assert {"detect", "teardown", "reschedule"} <= names
        instants = [ev for ev in doc["traceEvents"] if ev["ph"] == "i"]
        assert any(ev["name"] == constants.SUCCESSFUL_CREATE_POD_REASON
                   for ev in instants)
        # Pure function of the bundle: same bundle, same bytes.
        (bundle,) = rec.bundles(JOB)
        assert bundle_to_chrome(bundle) == rec.export_chrome(JOB)

    def test_retention_ring_evicts_oldest_bundles(self):
        rec = _rec(keep=2)
        for i in range(5):
            _restart_window(rec, t0=1000.0 + 10.0 * i)
            rec.record_step(JOB, i, 50.0, now=1000.0 + 10.0 * i + 3.0)
        bundles = rec.bundles(JOB)
        assert [b["id"] for b in bundles] == [4, 5]
        assert rec.retained_bytes(JOB) == sum(
            len(rec.bundle_json(JOB, b["id"])) for b in bundles)
        assert rec.retained_bytes(JOB) > 0

    def test_metrics_counter_gauges_and_forget(self):
        reg = MetricsRegistry()
        rec = IncidentRecorder(metrics=reg, ring=64, keep=4)
        _restart_window(rec, t0=100.0)
        _restart_window(rec, t0=200.0)
        snap = reg.snapshot()
        counter = next(v for k, v in snap.items()
                       if k.startswith("trainingjob_incidents_total"))
        assert counter == 2.0
        downtime = {k: v for k, v in snap.items()
                    if k.startswith("trainingjob_downtime_ms")}
        assert len(downtime) == len(PHASES)  # one gauge per phase
        assert sum(downtime.values()) == pytest.approx(4000.0)
        assert any(k.startswith("trainingjob_incident_bundle_bytes") and v > 0
                   for k, v in snap.items())
        rec.forget(JOB)
        snap = reg.snapshot()
        assert not any(k.startswith("trainingjob_downtime_ms")
                       or k.startswith("trainingjob_incident_bundle_bytes")
                       for k in snap)
        assert rec.bundles(JOB) is None

    def test_incident_recorded_event_fires_once_via_sink(self):
        rec = _rec()
        seen = []
        rec.set_event_sink(lambda job, reason, msg: seen.append(
            (job, reason, msg)))
        _restart_window(rec, t0=100.0)
        rec.record_step(JOB, 5, 100.0, now=103.0)  # amend, must NOT re-emit
        assert len(seen) == 1
        job, reason, msg = seen[0]
        assert job == JOB
        assert reason == constants.INCIDENT_RECORDED_REASON
        assert "incident #1" in msg and "/debug/incidents?job=" in msg


# -- /debug/incidents endpoint ------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as resp:
        return resp.status, resp.read().decode()


class TestDebugIncidentsEndpoint:
    @pytest.fixture
    def server(self):
        rec = _rec()
        _restart_window(rec, t0=100.0)
        rec.record_step(JOB, 5, 100.0, now=103.0)
        srv = serve_metrics(0, MetricsRegistry(), incidents=rec)
        yield srv.server_address[1], rec
        srv.shutdown()

    def test_job_summary_list(self, server):
        port, _rec_ = server
        status, body = _get(port, "/debug/incidents")
        doc = json.loads(body)
        assert status == 200 and doc["count"] == 1
        assert doc["jobs"][0]["job"] == JOB
        assert doc["jobs"][0]["incidents"] == 1
        assert doc["jobs"][0]["bytes"] > 0

    def test_fetch_job_bundles(self, server):
        port, rec = server
        status, body = _get(port, f"/debug/incidents?job={JOB}")
        doc = json.loads(body)
        assert status == 200 and doc["job"] == JOB and doc["count"] == 1
        assert doc["open"] is None
        assert doc["incidents"][0]["phases"].keys() == set(PHASES)

    def test_fetch_by_id_is_canonical_json(self, server):
        port, rec = server
        status, body = _get(port, f"/debug/incidents?job={JOB}&id=1")
        assert status == 200
        assert body == rec.bundle_json(JOB, 1)
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, f"/debug/incidents?job={JOB}&id=99")
        assert exc.value.code == 404

    def test_chrome_format(self, server):
        port, _rec_ = server
        status, body = _get(port, f"/debug/incidents?job={JOB}&format=chrome")
        assert status == 200
        assert json.loads(body)["traceEvents"]

    def test_unknown_job_404(self, server):
        port, _rec_ = server
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, "/debug/incidents?job=no/such")
        assert exc.value.code == 404

    def test_bad_format_is_400_not_default(self, server):
        port, _rec_ = server
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, f"/debug/incidents?job={JOB}&format=starlight")
        assert exc.value.code == 400

    def test_404_without_incidents_provider(self):
        srv = serve_metrics(0, MetricsRegistry())
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(srv.server_address[1], "/debug/incidents")
            assert exc.value.code == 404
        finally:
            srv.shutdown()


# -- /debug/steps gains ckpt_ms + hbm_bytes -----------------------------------

class TestStepsTableColumns:
    @pytest.fixture
    def agg(self):
        reg = MetricsRegistry()
        agg = TelemetryAggregator(metrics=reg,
                                  goodput=GoodputTracker(metrics=reg))
        for step in range(5):
            # rank 0 reports checkpoint stall + HBM samples; rank 1 never.
            assert agg.ingest({"v": 1, "job": JOB, "rtype": "worker",
                               "rank": 0, "step": step, "ms": 50.0,
                               "ckpt_ms": 12.345, "hbm_bytes": 2.5e9},
                              now=1000.0 + step * 0.1)
            assert agg.ingest({"v": 1, "job": JOB, "rtype": "worker",
                               "rank": 1, "step": step, "ms": 50.0},
                              now=1000.0 + step * 0.1)
        return agg

    def test_json_rows_carry_new_columns(self, agg):
        rows = {r["replica"]: r
                for r in agg.job_table(JOB, now=1001.0)["replicas"]}
        assert rows["worker-0"]["ckpt_ms"] == pytest.approx(12.35)
        assert rows["worker-0"]["hbm_bytes"] == pytest.approx(2.5e9)
        # Never-reporting replicas stay None, not 0 -- absence is not zero.
        assert rows["worker-1"]["ckpt_ms"] is None
        assert rows["worker-1"]["hbm_bytes"] is None

    def test_text_table_renders_dash_for_missing(self, agg):
        text = agg.render_table(JOB, now=1001.0)
        header = text.splitlines()[0]
        assert "ckpt_ms" in header and "hbm_bytes" in header
        row1 = next(ln for ln in text.splitlines() if "worker-1" in ln)
        assert "-" in row1.split()

    def test_resume_record_routes_to_incidents_not_steps(self):
        reg = MetricsRegistry()
        rec = IncidentRecorder(metrics=reg, ring=64, keep=4)
        agg = TelemetryAggregator(metrics=reg,
                                  goodput=GoodputTracker(metrics=reg),
                                  incidents=rec)
        rec.on_interruption(JOB, "ALL", constants.RESTARTING_REASON, now=99.0)
        rec.record_event(JOB, constants.SUCCESSFUL_CREATE_POD_REASON,
                         "create", ts=99.5)
        assert agg.ingest({"v": 1, "job": JOB, "rtype": "worker", "rank": 0,
                           "resume_restore_ms": 120.0,
                           "resume_compile_ms": 200.0,
                           "resume_overlapped": True, "ts": 100.0}, now=100.0)
        # Not a step: the job table has no replica rows from it.
        assert agg.job_table(JOB, now=100.5) is None
        rec.on_running(JOB, now=100.6)
        agg.ingest({"v": 1, "job": JOB, "rtype": "worker", "rank": 0,
                    "step": 3, "ms": 20.0}, now=100.7)
        (bundle,) = rec.bundles(JOB)
        assert bundle["phases"]["restore"] > 0.0

    def test_malformed_resume_record_counted_not_raised(self):
        reg = MetricsRegistry()
        agg = TelemetryAggregator(metrics=reg,
                                  goodput=GoodputTracker(metrics=reg))
        assert not agg.ingest({"v": 1, "job": "noslash",
                               "resume_restore_ms": 5.0}, now=100.0)
        assert not agg.ingest({"v": 1, "job": JOB,
                               "resume_restore_ms": -1.0}, now=100.0)


# -- e2e: sim preemption -> amended incident bundle ---------------------------

class TestPreemptionE2E:
    @pytest.fixture
    def cluster(self):
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.cmd.options import OperatorOptions
        from trainingjob_operator_tpu.controller.controller import (
            TrainingJobController,
        )
        from trainingjob_operator_tpu.runtime.sim import SimRuntime

        cs = Clientset()
        tc = TrainingJobController(
            cs, options=OperatorOptions(resync_period=0.05))
        sim = SimRuntime(cs)
        sim.add_node("n0")
        sim.add_node("n1")
        sim.start()
        tc.run(workers=2)
        yield cs, tc, sim
        tc.stop()
        sim.stop()

    def test_preempted_pod_yields_attributed_bundle(self, cluster):
        from trainingjob_operator_tpu.api.types import (
            ReplicaSpec,
            RestartPolicy,
            RestartScope,
            TPUTrainingJob,
            TrainingJobPhase,
        )
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ContainerPort,
            ObjectMeta,
            PodPhase,
            PodSpec,
            PodTemplateSpec,
        )
        from trainingjob_operator_tpu.obs.goodput import GOODPUT
        from trainingjob_operator_tpu.runtime.sim import (
            CKPT_MS_ANNOTATION,
            COMPILE_MS_ANNOTATION,
            HBM_BYTES_ANNOTATION,
            RESTORE_MS_ANNOTATION,
            RUN_SECONDS_ANNOTATION,
            STEP_MS_ANNOTATION,
            TOKENS_PER_STEP_ANNOTATION,
        )

        cs, tc, sim = cluster
        key = "default/preemptjob"
        TELEMETRY.forget(key)
        INCIDENTS.forget(key)
        job = TPUTrainingJob(
            metadata=ObjectMeta(name="preemptjob", namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=2,
            restart_policy=RestartPolicy.EXIT_CODE,
            restart_scope=RestartScope.ALL,
            template=PodTemplateSpec(
                metadata=ObjectMeta(annotations={
                    RUN_SECONDS_ANNOTATION: "60",
                    STEP_MS_ANNOTATION: "20",
                    TOKENS_PER_STEP_ANNOTATION: "512",
                    CKPT_MS_ANNOTATION: "1.5",
                    HBM_BYTES_ANNOTATION: "2.5e9",
                    RESTORE_MS_ANNOTATION: "120",
                    COMPILE_MS_ANNOTATION: "200",
                }),
                spec=PodSpec(containers=[
                    Container(name="aitj-main",
                              ports=[ContainerPort(name="aitj-7745",
                                                   container_port=7745)])])))
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)
        victim = "preemptjob-trainer-0"

        def stepping():
            try:
                pod = cs.pods.get("default", victim)
            except KeyError:
                return False
            if pod.status.phase != PodPhase.RUNNING:
                return False
            table = TELEMETRY.job_table(key)
            return bool(table and any(r["step"] > 0
                                      for r in table["replicas"]))

        try:
            assert wait_for(
                lambda: cs.trainingjobs.get("default", "preemptjob")
                .status.phase == TrainingJobPhase.RUNNING, 10)
            assert wait_for(stepping, 15)
            sim.preempt_pod("default", victim, exit_code=137)

            def amended():
                for b in reversed(INCIDENTS.bundles(key) or []):
                    if (b["running_at"] is not None
                            and b["ended"] > b["running_at"]):
                        return b
                return None

            assert wait_for(lambda: amended() is not None, 20)
            bundle = amended()

            # Acceptance 1: every ms is attributed; phases partition the
            # downtime exactly (assembly sums segment lengths), and the
            # evicted-ring residue stays under the 5% budget.
            assert _phases_sum(bundle) == pytest.approx(
                bundle["downtime_ms"], abs=0.01)
            assert bundle["phases"]["unknown"] <= 0.05 * bundle["downtime_ms"]
            assert bundle["downtime_ms"] > 0

            # Acceptance 2: the control window IS the goodput ledger's
            # downtime window -- both hooks received the same clock reads.
            assert bundle["control_downtime_ms"] == pytest.approx(
                GOODPUT.downtime_seconds(key) * 1000.0, abs=1.0)

            # Acceptance 3: byte-stable across two assemblies of the ring.
            assert INCIDENTS.reassemble(key, bundle["id"]) == \
                INCIDENTS.bundle_json(key, bundle["id"])

            # Acceptance 4: the bundle announced itself as a job event, and
            # the metric surface carries the incident.
            assert wait_for(
                lambda: any(
                    ev.reason == constants.INCIDENT_RECORDED_REASON
                    for ev in cs.events.list("default")), 10)
            prom = METRICS.render_prometheus()
            assert any(ln.startswith("trainingjob_incidents_total")
                       for ln in prom.splitlines())
            assert any(ln.startswith('trainingjob_downtime_ms{'
                                     f'job="{key}"')
                       or ln.startswith("trainingjob_downtime_ms{")
                       and f'job="{key}"' in ln
                       for ln in prom.splitlines())
        finally:
            cs.trainingjobs.delete("default", "preemptjob")
            TELEMETRY.forget(key)
            INCIDENTS.forget(key)
