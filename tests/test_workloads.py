"""Workload-layer tests: the five BASELINE configs' entrypoints.

PS/worker runs its real TCP protocol in-process; the JAX workloads
(resnet_dp, bert_pretrain, llama_elastic) smoke-run their real main() on the
virtual 8-device CPU mesh with tiny shapes, including checkpoint/resume.
"""

import socket
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override

apply_jax_platform_override()

from trainingjob_operator_tpu.workloads import ps_worker
from trainingjob_operator_tpu.workloads.rendezvous import Rendezvous


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestPSWorker:
    def test_grads_match_jax(self):
        params = ps_worker.init_params(hidden=16, seed=3)
        rng = np.random.RandomState(0)
        x, y = ps_worker.synthetic_batch(rng, 8)
        loss, grads = ps_worker.loss_and_grads(params, x, y)

        import jax.numpy as jnp
        import optax

        def jax_loss(p):
            h = jnp.maximum(jnp.asarray(x) @ p["w1"] + p["b1"], 0.0)
            logits = h @ p["w2"] + p["b2"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.asarray(y)).mean()

        jl, jg = jax.value_and_grad(jax_loss)(
            {k: jnp.asarray(v) for k, v in params.items()})
        assert abs(loss - float(jl)) < 1e-4
        for k in grads:
            np.testing.assert_allclose(grads[k], np.asarray(jg[k]),
                                       atol=1e-4)

    def test_shard_keys_partition(self):
        shards = ps_worker.shard_keys(["w1", "b1", "w2", "b2"], 2)
        assert sorted(sum(shards, [])) == ["b1", "b2", "w1", "w2"]
        assert all(shards)  # both pservers own something

    def test_ps_protocol_end_to_end(self, monkeypatch):
        """1 pserver + 2 workers over real sockets; training converges."""
        monkeypatch.setenv("MNIST_STEPS", "12")
        monkeypatch.setenv("MNIST_BATCH", "32")
        monkeypatch.setenv("MNIST_HIDDEN", "32")
        monkeypatch.setenv("PS_TIMEOUT", "30")
        port = free_port()
        ps_hosts = {"PSERVER": [f"127.0.0.1:{port}"]}
        workers = {"WORKER": ["w-0", "w-1"]}

        ps_rdv = Rendezvous(replica_name="pserver", replica_index=0,
                            group_hosts=ps_hosts, group_instances=workers)
        ps_rc = []
        th = threading.Thread(
            target=lambda: ps_rc.append(ps_worker.run_pserver(ps_rdv)),
            daemon=True)
        th.start()

        rcs = []
        for i in range(2):
            w_rdv = Rendezvous(replica_name="worker", replica_index=i,
                               group_hosts=ps_hosts, group_instances=workers)
            rcs.append(ps_worker.run_worker(w_rdv))
        th.join(timeout=10)
        assert rcs == [0, 0]
        assert ps_rc == [0]

    def test_reservation_short_circuits(self, monkeypatch):
        # A canary pod must idle, not dial the pservers; pass an immediate
        # interrupt via a 0-iteration hold by checking the flag directly.
        rdv = Rendezvous(replica_name="worker", is_reservation=True)
        assert rdv.is_reservation


class TestJaxWorkloads:
    def test_resnet_dp_smoke(self, monkeypatch, tmp_path, capsys):
        from trainingjob_operator_tpu.workloads import resnet_dp

        monkeypatch.setenv("RESNET_STEPS", "3")
        monkeypatch.setenv("RESNET_BATCH", "8")
        monkeypatch.setenv("RESNET_IMAGE", "32")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert resnet_dp.main() == 0
        out = capsys.readouterr().out
        assert "imgs/s" in out and "devices=8" in out

    def test_bert_pretrain_smoke_tp2(self, monkeypatch, tmp_path, capsys):
        from trainingjob_operator_tpu.workloads import bert_pretrain

        monkeypatch.setenv("BERT_STEPS", "3")
        monkeypatch.setenv("BERT_BATCH", "8")
        monkeypatch.setenv("BERT_SEQ", "32")
        monkeypatch.setenv("BERT_TP", "2")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert bert_pretrain.main() == 0
        out = capsys.readouterr().out
        assert "tokens/s" in out and "'tp': 2" in out

    def test_llama_elastic_resume(self, monkeypatch, tmp_path, capsys):
        """Run, checkpoint, 'preempt', rerun at a smaller width: resumes from
        the shared checkpoint -- the workload half of elastic recovery."""
        from trainingjob_operator_tpu.workloads import llama_elastic

        monkeypatch.setenv("LLAMA_STEPS", "4")
        monkeypatch.setenv("LLAMA_CKPT_EVERY", "2")
        monkeypatch.setenv("LLAMA_BATCH", "8")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv("LLAMA_TP", "2")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert llama_elastic.main() == 0
        capsys.readouterr()

        # "Restart" with more steps: must resume at step 4, not step 0.
        monkeypatch.setenv("LLAMA_STEPS", "6")
        monkeypatch.setenv("TRAININGJOB_REPLICA_RESTARTCOUNT", "1")
        assert llama_elastic.main() == 0
        out = capsys.readouterr().out
        assert "resumed at step 4" in out
        assert "steps=" in out

    def test_generate_samples_from_checkpoint(self, monkeypatch, tmp_path,
                                              capsys):
        """Train -> checkpoint -> sample: the serve half of the loop, with a
        PLACEHOLDER partial restore (params read, optimizer moments not)."""
        from trainingjob_operator_tpu.workloads import generate, llama_elastic

        monkeypatch.setenv("LLAMA_STEPS", "2")
        monkeypatch.setenv("LLAMA_CKPT_EVERY", "2")
        monkeypatch.setenv("LLAMA_BATCH", "8")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert llama_elastic.main() == 0
        capsys.readouterr()

        monkeypatch.setenv("GEN_STEPS", "4")
        monkeypatch.setenv("GEN_BATCH", "2")
        monkeypatch.setenv("GEN_PROMPT", "3,1,4")
        assert generate.main() == 0
        out = capsys.readouterr().out
        assert "sampling from checkpoint at step 2" in out
        lines = [l for l in out.splitlines() if l.startswith("tokens:")]
        assert len(lines) == 2
        assert len(lines[0].split(":")[1].split(",")) == 4

    def test_bert_resume_restores_params(self, monkeypatch, tmp_path, capsys):
        from trainingjob_operator_tpu.workloads import bert_pretrain

        monkeypatch.setenv("BERT_STEPS", "2")
        monkeypatch.setenv("BERT_BATCH", "8")
        monkeypatch.setenv("BERT_SEQ", "32")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert bert_pretrain.main() == 0
        capsys.readouterr()
        monkeypatch.setenv("BERT_STEPS", "4")
        assert bert_pretrain.main() == 0
        out = capsys.readouterr().out
        assert "resumed at step 2" in out

    def test_resnet_resume_restores_full_state(self, monkeypatch, tmp_path,
                                               capsys):
        from trainingjob_operator_tpu.workloads import resnet_dp

        monkeypatch.setenv("RESNET_STEPS", "12")
        monkeypatch.setenv("RESNET_BATCH", "8")
        monkeypatch.setenv("RESNET_IMAGE", "32")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert resnet_dp.main() == 0
        first = capsys.readouterr().out
        # Second invocation starts where the first checkpointed (step 12 ==
        # steps) so zero additional optimization happens.
        assert resnet_dp.main() == 0
        out = capsys.readouterr().out
        assert "steps=1 " in out or "imgs/s" in out


class TestMoEWorkload:
    def test_moe_pretrain_smoke_ep2_and_resume(self, monkeypatch, tmp_path,
                                               capsys):
        """MoE pretrain over fsdp x ep, checkpoint, resume -- the expert-
        parallel sibling of the llama elastic flow."""
        from trainingjob_operator_tpu.workloads import moe_pretrain

        monkeypatch.setenv("MOE_STEPS", "4")
        monkeypatch.setenv("MOE_CKPT_EVERY", "2")
        monkeypatch.setenv("MOE_BATCH", "8")
        monkeypatch.setenv("MOE_SEQ", "32")
        monkeypatch.setenv("MOE_EP", "2")
        monkeypatch.setenv("MOE_TP", "2")
        monkeypatch.setenv("TRAININGJOB_CHECKPOINT_DIR", str(tmp_path))
        assert moe_pretrain.main() == 0
        out = capsys.readouterr().out
        assert "'ep': 2" in out and "active" in out

        monkeypatch.setenv("MOE_STEPS", "6")
        monkeypatch.setenv("TRAININGJOB_REPLICA_RESTARTCOUNT", "1")
        assert moe_pretrain.main() == 0
        out = capsys.readouterr().out
        assert "resumed at step 4" in out


class TestPeerLossGuard:
    def test_classifier(self):
        from trainingjob_operator_tpu.workloads import train

        assert train.looks_like_peer_loss(ValueError(
            "UNKNOWN: Gloo AllGather failed: Read error [127.0.0.1]:25483: "
            "Connection reset by peer"))
        assert train.looks_like_peer_loss(RuntimeError(
            "Coordination service agent heartbeat timeout"))
        assert not train.looks_like_peer_loss(ValueError(
            "cannot reshape array of shape (2, 32) into (3, 3)"))
        assert not train.looks_like_peer_loss(KeyError("params"))

    def test_local_bug_propagates(self):
        # A deterministic local error must NOT be converted to exit 143.
        import pytest as _pytest

        from trainingjob_operator_tpu.workloads import train

        with _pytest.raises(ValueError, match="reshape"):
            with train.peer_loss_guard():
                raise ValueError("cannot reshape array")

    def test_classifier_walks_cause_chain(self):
        from trainingjob_operator_tpu.workloads import train

        try:
            try:
                raise ConnectionError("connection reset by peer")
            except ConnectionError as inner:
                raise RuntimeError("save failed for step 40") from inner
        except RuntimeError as wrapped:
            assert train.looks_like_peer_loss(wrapped)

    def test_classifier_ignores_implicit_context(self):
        # A deterministic local bug raised while HANDLING a transport error
        # must NOT inherit the peer-loss marker via __context__ -- it has to
        # reach the exit-code policy as a failure, not restart-loop as 143.
        from trainingjob_operator_tpu.workloads import train

        try:
            try:
                raise ConnectionError("connection reset by peer")
            except ConnectionError:
                raise ValueError("shape mismatch in restore")  # no `from`
        except ValueError as bug:
            assert bug.__context__ is not None
            assert not train.looks_like_peer_loss(bug)


class TestGradAccumulation:
    def test_matches_full_batch_gradient(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from trainingjob_operator_tpu.models import llama
        from trainingjob_operator_tpu.workloads import train

        cfg = llama.LlamaConfig(**{**llama.LlamaConfig.tiny().__dict__,
                                   "dtype": "float32"})
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size)

        def loss(p, tb):
            return llama.loss_fn(p, {"tokens": tb}, cfg)

        l_full, g_full = jax.value_and_grad(loss)(params, tokens)
        l_acc, g_acc = train.accumulated_value_and_grad(
            loss, params, tokens, accum=4)
        assert np.allclose(float(l_full), float(l_acc), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_acc)):
            # atol covers f32 accumulation-order noise on near-zero
            # embedding grads; structurally the gradients are identical.
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-4)

    def test_rejects_indivisible_batch(self):
        import jax.numpy as jnp
        import pytest as _pytest

        from trainingjob_operator_tpu.workloads import train

        with _pytest.raises(ValueError, match="divisible"):
            train.accumulated_value_and_grad(
                lambda p, t: t.sum(), {}, jnp.zeros((5, 2)), accum=2)

    def test_round_global_batch_never_inflates(self):
        import pytest as _pytest

        from trainingjob_operator_tpu.workloads import train

        assert train.round_global_batch(10, 4) == (8, 1)
        assert train.round_global_batch(8, 8) == (8, 1)
        # Accumulation sheds before the batch ever inflates.
        assert train.round_global_batch(8, 2, accum=8) == (8, 4)
        assert train.round_global_batch(8, 8, accum=4) == (8, 1)
        # ...and sheds PAST the bare fit when a smaller accum preserves the
        # requested batch (elastic contract: batch is width-independent).
        # Ties prefer the larger accum (smaller microbatch HBM): accum 3
        # and 2 both keep batch 12 at 2 shards.
        assert train.round_global_batch(12, 2, accum=4) == (12, 3)
        assert train.round_global_batch(12, 4, accum=4) == (12, 3)
        # Scale-up PAST the global batch: inflate to one row per shard
        # (loudly) instead of crash-looping the job at the new width.
        assert train.round_global_batch(8, 16) == (16, 1)
        assert train.round_global_batch(3, 4, accum=2) == (4, 1)


class TestPeerLossContextHop:
    """ADVICE r4: implicit __context__ is followed one hop, but only from a
    transport-shaped wrapper (OSError/ConnectionError/TimeoutError)."""

    def test_io_shaped_wrapper_follows_context(self):
        from trainingjob_operator_tpu.workloads import train

        try:
            try:
                raise ConnectionResetError("connection reset by peer")
            except ConnectionResetError:
                raise OSError("write failed")  # bare re-raise, no `from`
        except OSError as wrapped:
            assert wrapped.__cause__ is None
            assert train.looks_like_peer_loss(wrapped)

    def test_non_io_wrapper_still_ignores_context(self):
        from trainingjob_operator_tpu.workloads import train

        try:
            try:
                raise ConnectionResetError("connection reset by peer")
            except ConnectionResetError:
                raise ValueError("shape mismatch")
        except ValueError as bug:
            assert not train.looks_like_peer_loss(bug)


class TestPSWireFormat:
    """The PS protocol is a non-executable codec (JSON + raw array bytes):
    no pickle on the wire, dtypes whitelisted."""

    def _roundtrip(self, obj):
        import socket

        a, b = socket.socketpair()
        try:
            ps_worker.send_msg(a, obj)
            return ps_worker.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_roundtrip_nested_arrays(self):
        msg = {"op": "push", "lr": 0.05,
               "grads": {"w1": np.arange(6, dtype=np.float32).reshape(2, 3),
                         "b1": np.ones(3, np.float64)}}
        out = self._roundtrip(msg)
        assert out["op"] == "push" and out["lr"] == 0.05
        np.testing.assert_array_equal(out["grads"]["w1"], msg["grads"]["w1"])
        assert out["grads"]["w1"].dtype == np.float32
        np.testing.assert_array_equal(out["grads"]["b1"], msg["grads"]["b1"])

    def test_no_pickle_on_the_wire(self):
        import io
        import pickle
        import socket

        class Evil:
            def __reduce__(self):
                return (print, ("pwned",))

        a, b = socket.socketpair()
        try:
            with pytest.raises((TypeError, ValueError)):
                ps_worker.send_msg(a, {"op": "push", "grads": Evil()})
        finally:
            a.close()
            b.close()

    def test_rejects_object_dtype(self):
        import json
        import socket
        import struct

        meta = json.dumps({"x": {"__nd__": 0, "dtype": "object",
                                 "shape": [1]}}).encode()
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">II", len(meta), 0) + meta)
            with pytest.raises(ValueError, match="dtype"):
                ps_worker.recv_msg(b)
        finally:
            a.close()
            b.close()


class TestEvalRequiresCorpus:
    def test_eval_without_data_raises(self, monkeypatch):
        from trainingjob_operator_tpu.workloads import train

        monkeypatch.delenv("LLAMA_DATA", raising=False)
        monkeypatch.setenv("LLAMA_EVAL_EVERY", "5")
        with pytest.raises(ValueError, match="synthetic"):
            train.build_batch_sources(
                prefix="LLAMA", vocab_size=256, global_batch=4,
                local_batch=4, row0=0, seq=16, batch_sharding=None,
                synthetic_key=17)


class TestPSWireFormatHardening:
    def test_rejects_negative_shape(self):
        import json
        import socket
        import struct

        meta = json.dumps({"x": {"__nd__": 0, "dtype": "float32",
                                 "shape": [-1]}}).encode()
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">II", len(meta), 0) + meta)
            with pytest.raises(ValueError, match="negative shape"):
                ps_worker.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_rejects_oversized_frame_before_buffering(self):
        """A hostile peer claiming a multi-GiB section must be refused from
        the 8-byte header alone -- _recv_exact never buffers the payload."""
        import socket
        import struct

        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">II", ps_worker.MAX_FRAME_BYTES + 1, 0))
            with pytest.raises(ValueError, match="oversized"):
                ps_worker.recv_msg(b)
            a.sendall(struct.pack(">II", 8, ps_worker.MAX_FRAME_BYTES + 1))
            with pytest.raises(ValueError, match="oversized"):
                ps_worker.recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_rejects_blob_bytes_metadata_does_not_account_for(self):
        """Every blob byte must be consumed by the metadata's arrays; a
        frame whose lengths disagree is rejected, not silently truncated."""
        import json
        import socket
        import struct

        meta = json.dumps({"x": {"__nd__": 0, "dtype": "float32",
                                 "shape": [1]}}).encode()
        blobs = b"\x00" * 8  # the one declared float32 consumes only 4
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">II", len(meta), len(blobs)) + meta + blobs)
            with pytest.raises(ValueError, match="desync"):
                ps_worker.recv_msg(b)
        finally:
            a.close()
            b.close()


class TestLlamaConfigDispatch:
    def test_unknown_config_fails_loudly(self, monkeypatch, capsys):
        from trainingjob_operator_tpu.workloads import llama_elastic

        monkeypatch.setenv("LLAMA_CONFIG", "124M")  # typo'd case
        monkeypatch.setenv("TRAININGJOB_JAX_PLATFORM", "cpu")
        assert llama_elastic.main() == 1
        out = capsys.readouterr().out
        assert "unknown" in out and "124m" in out
