"""cmd layer, signals, leader election tests."""

import subprocess
import sys
import threading
import time

from conftest import wait_for

from trainingjob_operator_tpu.cmd.options import LeaderElectionConfig
from trainingjob_operator_tpu.utils.leader import LeaderElector


class TestLeaderElection:
    def test_single_leader_and_failover(self, tmp_path):
        lock = str(tmp_path / "leader.lock")
        cfg = LeaderElectionConfig(leader_elect=True, lock_path=lock,
                                   retry_period=0.05)
        events = []
        a = LeaderElector(cfg, identity="a")
        b = LeaderElector(cfg, identity="b")
        release_a = threading.Event()

        def lead_a():
            events.append("a-leading")
            release_a.wait(5)

        def lead_b():
            events.append("b-leading")

        ta = threading.Thread(target=lambda: a.run(lead_a), daemon=True)
        ta.start()
        assert wait_for(lambda: "a-leading" in events, 2)
        tb = threading.Thread(target=lambda: b.run(lead_b), daemon=True)
        tb.start()
        time.sleep(0.3)
        assert "b-leading" not in events  # a still holds the lock
        release_a.set()
        assert wait_for(lambda: "b-leading" in events, 3)
        tb.join(timeout=2)

    def test_identity_written(self, tmp_path):
        lock = str(tmp_path / "l2.lock")
        cfg = LeaderElectionConfig(lock_path=lock)
        el = LeaderElector(cfg, identity="me")
        done = threading.Event()
        th = threading.Thread(
            target=lambda: el.run(lambda: done.wait(2)), daemon=True)
        th.start()
        assert wait_for(lambda: el.is_leader(), 2)
        assert open(lock).read().startswith("me ")
        done.set()
        th.join(timeout=2)


class TestMainCLI:
    def test_apply_and_watch_sim_backend(self, tmp_path):
        """The operator binary path: apply a manifest against the sim backend
        and watch it end (the run-this-operator flow from README)."""
        manifest = tmp_path / "job.yaml"
        manifest.write_text("""
apiVersion: tpu.trainingjob.dev/v1
kind: TPUTrainingJob
metadata: {name: cli-job}
spec:
  replicaSpecs:
    trainer:
      replicas: 2
      template:
        metadata:
          annotations: {sim.tpu.trainingjob.dev/run-seconds: "0.2"}
        spec:
          containers:
            - name: aitj-t
              ports: [{name: aitj-7000, containerPort: 7000}]
""")
        out = subprocess.run(
            [sys.executable, "-m", "trainingjob_operator_tpu.cmd.main",
             "--backend", "sim", "--resync-period", "0.05",
             "--apply", str(manifest), "--watch"],
            capture_output=True, text=True, timeout=60,
            cwd="/root/repo")
        assert out.returncode == 0, out.stderr
        assert "created default/cli-job" in out.stdout
        assert "final: default/cli-job -> Succeed" in out.stdout
