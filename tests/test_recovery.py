"""Recovery fast-path tests: snapshot-donate checkpointing, overlapped
restore+recompile, the shutdown watchdog vs in-flight writes, checkpoint-stall
telemetry, the int8 decode batch gate, and the sim's settled-pod skip.

The writer-protocol tests run CheckpointState against a FAKE orbax manager
(records write order, can block or fail on demand) so ordering, coalescing
and error surfacing are deterministic; the crash-mid-write test uses the real
orbax layout to prove recovery falls back to the last COMMITTED step.
"""

import os
import signal
import threading
import time
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override, wait_for

apply_jax_platform_override()

import jax.numpy as jnp

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.workloads import train


class FakeManager:
    """Stands in for ``orbax.CheckpointManager``: records the steps written,
    in order; ``gate`` blocks every save until set (an in-flight write);
    ``fail`` raises instead of writing (a dead filesystem)."""

    def __init__(self, gate=None, fail=None):
        self.saved = []
        self.gate = gate
        self.fail = fail

    def save(self, step, args=None):
        if self.gate is not None:
            assert self.gate.wait(10.0), "test gate never released"
        if self.fail is not None:
            raise self.fail
        self.saved.append(step)

    def wait_until_finished(self):
        pass

    def latest_step(self):
        return None


def _value(step):
    return {"step": step, "x": np.arange(4, dtype=np.int32) + step}


class TestSnapshotWriter:
    def test_background_write_commits(self):
        mngr = FakeManager()
        st = train.CheckpointState("", {}, mngr)
        assert st.snapshot_mode()  # single process, knob defaulted on
        stall_ms = st.save(_value(1))
        st.finalize()
        assert mngr.saved == [1]
        assert st.committed_step == 1
        assert stall_ms >= 0.0

    def test_latest_wins_coalescing_order_stays_monotonic(self):
        gate = threading.Event()
        mngr = FakeManager(gate=gate)
        st = train.CheckpointState("", {}, mngr)
        st.save(_value(1))
        # Wait for the writer to PICK UP step 1 (busy, queue empty) so the
        # next two saves land while a write is in flight.
        assert wait_for(lambda: st._busy and st._pending is None)
        st.save(_value(2))
        st.save(_value(3))  # replaces the unstarted 2: latest wins
        gate.set()
        st.finalize()
        assert mngr.saved == [1, 3]
        assert st.committed_step == 3

    def test_writer_failure_surfaces_then_recovers(self):
        mngr = FakeManager(fail=OSError("disk gone"))
        st = train.CheckpointState("", {}, mngr)
        st.save(_value(1))
        with pytest.raises(RuntimeError, match="last committed step"):
            st.finalize()
        # The stash is one-shot: after surfacing, the pipeline keeps working.
        mngr.fail = None
        st.save(_value(2))
        st.finalize()
        assert mngr.saved == [2]
        assert st.committed_step == 2

    def test_sync_knob_forces_direct_handoff(self, monkeypatch):
        monkeypatch.setenv(constants.CKPT_SNAPSHOT_ENV, "0")
        mngr = FakeManager()
        st = train.CheckpointState("", {}, mngr)
        assert not st.snapshot_mode()
        st.save(_value(1))
        # Written on the calling thread, before save() returned.
        assert mngr.saved == [1]
        assert st._writer is None

    def test_wait_true_commits_before_returning(self):
        mngr = FakeManager()
        st = train.CheckpointState("", {}, mngr)
        st.save(_value(5), wait=True)
        assert mngr.saved == [5]
        assert st.committed_step == 5

    def test_snapshot_to_host_materializes_device_arrays(self):
        val = {"a": jnp.arange(8), "b": 3, "c": np.ones(2)}
        host = train._snapshot_to_host(val)
        assert isinstance(host["a"], np.ndarray)
        np.testing.assert_array_equal(host["a"], np.arange(8))
        assert host["b"] == 3


class TestCrashMidWriteFallback:
    def test_uncommitted_write_falls_back_to_committed_step(self, tmp_path):
        """A crash mid-write leaves orbax's atomic-commit tmp dir behind;
        restore must resume from the last COMMITTED step, not the torn one."""
        rdv = types.SimpleNamespace(checkpoint_dir=str(tmp_path),
                                    replica_name="worker", replica_index=0)
        init = {"step": 0, "x": jnp.arange(8)}
        st = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                   subdir="t")
        st.save({"step": 2, "x": jnp.arange(8) + 2}, wait=True)
        st.finalize()
        # Fabricate the torn step-4 write with orbax's own tmp naming (the
        # commit rename to "4" never happened).
        torn = tmp_path / "t" / "4.orbax-checkpoint-tmp-99"
        torn.mkdir()
        (torn / "partial").write_bytes(b"garbage")
        st2 = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                    subdir="t")
        assert int(st2.value["step"]) == 2
        np.testing.assert_array_equal(np.asarray(st2.value["x"]),
                                      np.arange(8) + 2)


class TestResumeImage:
    """The flat resume image: the writer mirrors each committed checkpoint
    as one pickle, restore prefers it (single sequential read + device_put)
    and falls back to the orbax restore on ANY image problem."""

    def _setup(self, tmp_path):
        rdv = types.SimpleNamespace(checkpoint_dir=str(tmp_path),
                                    replica_name="worker", replica_index=0)
        init = {"step": 0, "x": jnp.arange(8)}
        return rdv, init, tmp_path / "t" / train._RESUME_IMAGE

    def test_background_writer_mirrors_commit_into_image(self, tmp_path):
        st = train.CheckpointState(str(tmp_path), {}, FakeManager())
        st.save(_value(1))
        st.finalize()
        import pickle

        with open(tmp_path / train._RESUME_IMAGE, "rb") as f:
            step, host = pickle.load(f)
        assert step == 1
        np.testing.assert_array_equal(host["x"], np.arange(4) + 1)

    def test_restore_prefers_image_over_orbax(self, tmp_path):
        rdv, init, image = self._setup(tmp_path)
        st = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                   subdir="t")
        st.save({"step": 2, "x": jnp.arange(8) + 2}, wait=True)
        st.finalize()
        assert image.exists()
        # Plant distinguishable values at the SAME step: a restore that
        # reads the image sees them; one that read orbax would not.
        train._write_resume_image(str(tmp_path / "t"), 2,
                                  {"step": 2, "x": np.arange(8) + 100})
        st2 = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                    subdir="t")
        np.testing.assert_array_equal(np.asarray(st2.value["x"]),
                                      np.arange(8) + 100)

    def test_stale_image_falls_back_to_orbax(self, tmp_path):
        rdv, init, _ = self._setup(tmp_path)
        st = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                   subdir="t")
        st.save({"step": 2, "x": jnp.arange(8) + 2}, wait=True)
        st.finalize()
        # Image claims step 1 while orbax's latest is 2 (a newer sync-mode
        # save superseded it): must be ignored.
        train._write_resume_image(str(tmp_path / "t"), 1,
                                  {"step": 1, "x": np.arange(8) + 100})
        st2 = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                    subdir="t")
        np.testing.assert_array_equal(np.asarray(st2.value["x"]),
                                      np.arange(8) + 2)

    def test_corrupt_image_falls_back_to_orbax(self, tmp_path, capsys):
        rdv, init, image = self._setup(tmp_path)
        st = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                   subdir="t")
        st.save({"step": 2, "x": jnp.arange(8) + 2}, wait=True)
        st.finalize()
        image.write_bytes(b"definitely not a pickle")
        st2 = train.CheckpointState.restore_or_init(rdv, dict(init),
                                                    subdir="t")
        np.testing.assert_array_equal(np.asarray(st2.value["x"]),
                                      np.arange(8) + 2)
        assert "image fallback reason=corrupt" in capsys.readouterr().out

    def test_knob_disables_image_restore(self, tmp_path, monkeypatch):
        template = {"step": 0, "x": jnp.arange(8)}
        train._write_resume_image(str(tmp_path), 2,
                                  {"step": 2, "x": np.arange(8)})
        monkeypatch.setenv(constants.RESUME_OVERLAP_ENV, "0")
        assert train._load_resume_image(str(tmp_path), 2, template) is None
        monkeypatch.delenv(constants.RESUME_OVERLAP_ENV)
        assert train._load_resume_image(str(tmp_path), 2, template) is not None

    def test_sync_mode_writes_no_image(self, tmp_path, monkeypatch):
        monkeypatch.setenv(constants.CKPT_SNAPSHOT_ENV, "0")
        st = train.CheckpointState(str(tmp_path), {}, FakeManager())
        st.save(_value(1), wait=True)
        assert not (tmp_path / train._RESUME_IMAGE).exists()


class TestOverlappedRestore:
    def test_phases_actually_overlap(self):
        order = []

        def restore_fn():
            order.append("restore-start")
            time.sleep(0.25)
            order.append("restore-end")
            return "state"

        def compile_fn():
            order.append("compile-start")
            time.sleep(0.25)
            return "exe"

        restored, compiled, t = train.overlapped_restore(
            restore_fn, compile_fn, overlap=True)
        assert restored == "state" and compiled == "exe"
        assert t["overlap"]
        # The overlap PROOF: compile began before restore finished, and the
        # wall is max-like, not sum-like.
        assert order.index("compile-start") < order.index("restore-end")
        assert t["wall_s"] < 0.9 * (t["restore_s"] + t["compile_s"])

    def test_serial_mode_runs_compile_after_restore(self):
        order = []
        restored, compiled, t = train.overlapped_restore(
            lambda: order.append("restore") or "s",
            lambda: order.append("compile") or "c",
            overlap=False)
        assert (restored, compiled) == ("s", "c")
        assert not t["overlap"]
        assert order == ["restore", "compile"]

    def test_env_knob_disables_overlap(self, monkeypatch):
        monkeypatch.setenv(constants.RESUME_OVERLAP_ENV, "0")
        _, _, t = train.overlapped_restore(lambda: 1, lambda: 2)
        assert not t["overlap"]

    def test_compile_failure_never_fails_the_resume(self, capsys):
        def bad_compile():
            raise ValueError("no cache for you")

        restored, compiled, t = train.overlapped_restore(
            lambda: "s", bad_compile, overlap=True)
        assert restored == "s"
        assert compiled is None
        assert "warm compile failed" in capsys.readouterr().out

    def test_no_compile_fn_restore_only(self):
        restored, compiled, t = train.overlapped_restore(lambda: "s")
        assert (restored, compiled) == ("s", None)
        assert t["compile_s"] == 0.0


class TestAotOrJit:
    def test_none_compiled_is_identity(self):
        def step(p, o, t):
            return "jit"

        assert train.aot_or_jit(None, step) is step

    def test_aot_used_when_it_works(self):
        run = train.aot_or_jit(lambda p, o, t: "aot", lambda p, o, t: "jit")
        assert run(1, 2, 3) == "aot"

    def test_fallback_is_permanent(self, capsys):
        calls = {"aot": 0, "jit": 0}

        def aot(p, o, t):
            calls["aot"] += 1
            raise RuntimeError("donated buffer shape mismatch")

        def jit(p, o, t):
            calls["jit"] += 1
            return "ok"

        run = train.aot_or_jit(aot, jit)
        assert run(1, 2, 3) == "ok"
        assert run(1, 2, 3) == "ok"
        # One failed AOT attempt, then the jitted step permanently.
        assert calls == {"aot": 1, "jit": 2}
        assert "aot step fallback" in capsys.readouterr().out


class TestExecutableSnapshot:
    """The executable-snapshot level of compile persistence: a warm resume
    loads the serialized compiled step (no trace, no lower, no compile);
    every failure mode degrades to the trace+compile path."""

    def _compiled(self):
        return jax.jit(lambda x: x * 2 + 1).lower(
            jax.ShapeDtypeStruct((4,), jnp.float32)).compile()

    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "exec.jexec")
        compiled = self._compiled()
        train.store_executable_snapshot(path, compiled)
        assert os.path.exists(path)
        loaded = train.load_executable_snapshot(path)
        assert loaded is not None
        x = jnp.arange(4, dtype=jnp.float32)
        assert jnp.allclose(loaded(x), compiled(x))

    def test_missing_or_disabled_path_is_none(self, tmp_path):
        assert train.load_executable_snapshot("") is None
        assert train.load_executable_snapshot(str(tmp_path / "nope")) is None

    def test_corrupt_snapshot_falls_back(self, tmp_path, capsys):
        p = tmp_path / "bad.jexec"
        p.write_bytes(b"definitely not a pickle")
        assert train.load_executable_snapshot(str(p)) is None
        assert "snapshot unusable" in capsys.readouterr().out

    def test_store_is_best_effort(self, tmp_path, capsys):
        # Not a Compiled: serialize() raises, store prints and returns --
        # and leaves no tmp debris behind.
        train.store_executable_snapshot(str(tmp_path / "x.jexec"), object())
        assert "snapshot store failed" in capsys.readouterr().out
        assert list(tmp_path.iterdir()) == []

    def test_store_noop_without_path(self):
        train.store_executable_snapshot("", object())  # must not raise

    def test_fastpath_env_knob(self, monkeypatch):
        monkeypatch.setenv(constants.RESUME_OVERLAP_ENV, "0")
        assert not train.resume_fastpath_enabled()
        monkeypatch.setenv(constants.RESUME_OVERLAP_ENV, "1")
        assert train.resume_fastpath_enabled()
        monkeypatch.delenv(constants.RESUME_OVERLAP_ENV)
        assert train.resume_fastpath_enabled()


class TestShutdownWatchdogVsBackgroundWrite:
    @pytest.fixture
    def fake_exit(self, monkeypatch):
        exits = []
        monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
        return exits

    @pytest.fixture
    def sigterm_restored(self):
        prev = signal.getsignal(signal.SIGTERM)
        yield
        signal.signal(signal.SIGTERM, prev)

    def test_inflight_write_gets_its_bounded_window(self, fake_exit,
                                                    sigterm_restored):
        """SIGTERM lands while a background write is in flight: the
        preemption checkpoint drains it and commits; the watchdog must NOT
        force-exit inside the bounded post-surface window."""
        gate = threading.Event()
        mngr = FakeManager(gate=gate)
        st = train.CheckpointState("", {}, mngr)
        st.save(_value(1))  # background write now in flight, blocked
        sd = train.GracefulShutdown(stuck_grace=0.1).install()
        signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
        assert sd.requested
        threading.Timer(0.2, gate.set).start()  # write "finishes" at 0.2s
        sd.checkpoint_and_exit(lambda: st.save(_value(2), wait=True))
        assert fake_exit == [train.GracefulShutdown.EXIT_CODE]
        assert st.committed_step == 2
        assert mngr.saved == [1, 2]
        # Past the watchdog's whole window: it saw _save_done and stood down.
        time.sleep(0.5)
        assert fake_exit == [train.GracefulShutdown.EXIT_CODE]

    def test_wedged_write_is_force_exited(self, fake_exit, sigterm_restored):
        """The write never finishes (dead filesystem): the watchdog
        force-exits 143 instead of burning the kubelet grace period."""
        gate = threading.Event()  # never set while the watchdog decides
        mngr = FakeManager(gate=gate)
        st = train.CheckpointState("", {}, mngr)
        st.save(_value(1))
        sd = train.GracefulShutdown(stuck_grace=0.05).install()
        signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
        sd._surfaced = True  # loop surfaced, save about to wedge in _drain
        assert wait_for(lambda: fake_exit ==
                        [train.GracefulShutdown.EXIT_CODE], timeout=5)
        gate.set()  # unblock the writer thread for teardown

    def test_stuck_loop_is_force_exited(self, fake_exit, sigterm_restored,
                                        capsys):
        """No step boundary ever observes the flag (blocked collective):
        force-exit after stuck_grace."""
        sd = train.GracefulShutdown(stuck_grace=0.05).install()
        signal.getsignal(signal.SIGTERM)(signal.SIGTERM, None)
        assert sd.requested
        assert wait_for(lambda: fake_exit ==
                        [train.GracefulShutdown.EXIT_CODE], timeout=5)


class TestCheckpointStallTelemetry:
    def test_ckpt_ms_reaches_metric_and_goodput(self):
        from trainingjob_operator_tpu.obs.goodput import GoodputTracker
        from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
        from trainingjob_operator_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        g = GoodputTracker(metrics=m)
        agg = TelemetryAggregator(metrics=m, goodput=g)
        job = "default/tjob"
        for step in range(3):
            assert agg.ingest(
                {"v": 1, "job": job, "rtype": "worker", "rank": 0,
                 "step": step, "ms": 100.0, "ckpt_ms": 50.0},
                now=1000.0 + step * 0.1)
        # 3 pacer steps x 50 ms -> 0.15 s of step-visible checkpoint stall.
        assert g.checkpoint_stall_seconds(job) == pytest.approx(0.15)
        text = m.render_prometheus()
        assert "trainingjob_checkpoint_stall_ms" in text

    def test_records_without_ckpt_ms_unaffected(self):
        from trainingjob_operator_tpu.obs.goodput import GoodputTracker
        from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
        from trainingjob_operator_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        g = GoodputTracker(metrics=m)
        agg = TelemetryAggregator(metrics=m, goodput=g)
        assert agg.ingest({"v": 1, "job": "default/j", "rtype": "worker",
                           "rank": 0, "step": 0, "ms": 100.0}, now=1000.0)
        assert g.checkpoint_stall_seconds("default/j") == 0.0
        assert "trainingjob_checkpoint_stall_ms" not in m.render_prometheus()


class TestInt8DecodeGate:
    def test_effective_at_every_batch(self):
        from trainingjob_operator_tpu.models import quant

        # qmatmul scales AFTER the accumulate, so the dequant epilogue is
        # O(batch x out) and int8 pays at every batch -- including 8,
        # BENCH_r05's old 0.88x regression that the deleted
        # INT8_DECODE_MAX_BATCH gate papered over.
        for batch in (1, 2, 4, 8, 64):
            assert quant.int8_effective(batch)

    def test_generate_quantizes_at_every_batch(self, monkeypatch):
        from trainingjob_operator_tpu.models import decode, llama, quant

        calls = []
        real = quant.quantize_weights
        monkeypatch.setattr(
            quant, "quantize_weights",
            lambda p: (calls.append(1), real(p))[1])
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        for batch in (2, 8):
            calls.clear()
            toks = jnp.ones((batch, 4), jnp.int32)
            out = decode.generate(params, toks, cfg, steps=2, quantize=True)
            assert out.shape == (batch, 2)
            assert calls, f"batch {batch}: int8 no longer gated, must engage"


class TestSimSettledSkip:
    def test_settled_pods_leave_the_tick_walk(self):
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ObjectMeta,
            Pod,
            PodPhase,
            PodSpec,
        )
        from trainingjob_operator_tpu.runtime.sim import (
            RUN_SECONDS_ANNOTATION,
            SimRuntime,
        )

        cs = Clientset()
        sim = SimRuntime(cs)
        sim.add_node("n0")
        cs.pods.create(Pod(
            metadata=ObjectMeta(name="p0", namespace="default",
                                annotations={RUN_SECONDS_ANNOTATION: "0.05"}),
            spec=PodSpec(containers=[Container(name="c")])))
        sim.start()
        try:
            assert wait_for(lambda: cs.pods.get("default", "p0")
                            .status.phase == PodPhase.SUCCEEDED)
            # Settled: dropped from the active walk, kept in the full cache
            # (capacity accounting still sees its placement).
            assert wait_for(lambda: "default/p0" not in sim._active_cache)
            assert "default/p0" in sim._pods_cache
            # Deletion re-activates it (the finalize walk owes it a
            # finalize_delete) and it is eventually reaped for real.
            cs.pods.delete("default", "p0")
            assert wait_for(lambda: "default/p0" not in sim._pods_cache)
        finally:
            sim.stop()
