"""End-to-end tests: controller + local-process runtime (real subprocesses)."""

import sys
import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset
from trainingjob_operator_tpu.cmd.options import OperatorOptions
from trainingjob_operator_tpu.controller.controller import TrainingJobController
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)
from trainingjob_operator_tpu.runtime.localproc import LocalProcRuntime


from conftest import wait_for  # noqa: E402


@pytest.fixture
def cluster(tmp_path):
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    rt = LocalProcRuntime(cs, nodes=2, log_dir=str(tmp_path),
                          termination_grace=0.5)
    rt.start()
    tc.run(workers=2)
    yield cs, tc, rt
    tc.stop()
    rt.stop()


def proc_job(name, code, replicas=1, port=7701, **replica_kw) -> TPUTrainingJob:
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.spec.replica_specs["worker"] = ReplicaSpec(
        replicas=replicas,
        template=PodTemplateSpec(spec=PodSpec(containers=[
            Container(name="aitj-w",
                      command=[sys.executable, "-u", "-c", code],
                      ports=[ContainerPort(name=f"aitj-{port}", container_port=port)])])),
        **replica_kw)
    return job


def phase(cs, name):
    return cs.trainingjobs.get("default", name).status.phase


class TestLocalProc:
    def test_subprocess_job_completes(self, cluster):
        cs, tc, rt = cluster
        cs.trainingjobs.create(proc_job("ok", "import time; time.sleep(0.2)"))
        assert wait_for(lambda: phase(cs, "ok") == TrainingJobPhase.SUCCEEDED), \
            phase(cs, "ok")

    def test_subprocess_failure_fails_job(self, cluster):
        cs, tc, rt = cluster
        cs.trainingjobs.create(proc_job("bad", "raise SystemExit(3)"))
        assert wait_for(lambda: phase(cs, "bad") == TrainingJobPhase.FAILED), \
            phase(cs, "bad")

    def test_env_identity_reaches_process(self, cluster, tmp_path):
        cs, tc, rt = cluster
        out = tmp_path / "env.txt"
        code = (
            "import os\n"
            f"open({str(out)!r}, 'w').write('|'.join([\n"
            "  os.environ['TRAININGJOB_REPLICA_NAME'],\n"
            "  os.environ['TRAININGJOB_REPLICA_INDEX'],\n"
            "  os.environ['WORKER_INSTANCES_NUM'],\n"
            "  os.environ['TRAININGJOB_COORDINATOR_ADDRESS'],\n"
            "]))\n")
        cs.trainingjobs.create(proc_job("envjob", code))
        assert wait_for(lambda: phase(cs, "envjob") == TrainingJobPhase.SUCCEEDED)
        rname, rindex, num, coord = out.read_text().split("|")
        assert (rname, rindex, num) == ("worker", "0", "1")
        # Cluster DNS rewritten to a concrete local address.
        assert coord.startswith("127.0.0.1:")

    def test_rendezvous_over_mapped_ports(self, cluster):
        """Rank 0 binds its mapped port; rank 1 connects through the same
        mapping -- the local analogue of headless-service DNS."""
        cs, tc, rt = cluster
        code = (
            "import os, socket, time\n"
            "addr = os.environ['TRAININGJOB_COORDINATOR_ADDRESS']\n"
            "host, port = addr.split(':'); port = int(port)\n"
            "rank = int(os.environ['TRAININGJOB_REPLICA_INDEX'])\n"
            "if rank == 0:\n"
            "    s = socket.socket(); s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)\n"
            "    s.bind(('127.0.0.1', port)); s.listen(1)\n"
            "    conn, _ = s.accept()\n"
            "    assert conn.recv(5) == b'hello'\n"
            "else:\n"
            "    for _ in range(100):\n"
            "        try:\n"
            "            c = socket.create_connection((host, port), timeout=0.2); break\n"
            "        except OSError: time.sleep(0.1)\n"
            "    else: raise SystemExit(9)\n"
            "    c.sendall(b'hello')\n")
        cs.trainingjobs.create(proc_job("rdv", code, replicas=2))
        assert wait_for(lambda: phase(cs, "rdv") == TrainingJobPhase.SUCCEEDED, 20), \
            phase(cs, "rdv")

    def test_preemption_restart_recovers(self, cluster):
        cs, tc, rt = cluster
        job = proc_job("longrun", "import time; time.sleep(60)", replicas=2,
                       restart_policy=RestartPolicy.EXIT_CODE,
                       restart_scope=RestartScope.ALL)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)
        assert wait_for(lambda: phase(cs, "longrun") == TrainingJobPhase.RUNNING)
        rt.preempt_pod("default", "longrun-worker-1")  # SIGKILL -> 137
        assert wait_for(
            lambda: cs.trainingjobs.get("default", "longrun").status.restart_counts.get("worker", 0) == 1,
            10)
        assert wait_for(lambda: phase(cs, "longrun") == TrainingJobPhase.RUNNING, 15), \
            phase(cs, "longrun")
        assert all(p.metadata.labels[constants.RESTART_COUNT_LABEL] == "1"
                   for p in cs.pods.list("default"))

    def test_node_fail_after_preempt_relaunches_same_name_pods(self, cluster):
        """Regression: a force-deleted pod recreated with the same name must
        get a fresh process (runtime state is per-UID, not per-name)."""
        cs, tc, rt = cluster
        job = proc_job("nf", "import time; time.sleep(60)", replicas=2,
                       restart_policy=RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE,
                       restart_scope=RestartScope.ALL)
        job.spec.restarting_exit_code = "137,143"
        cs.trainingjobs.create(job)
        assert wait_for(lambda: phase(cs, "nf") == TrainingJobPhase.RUNNING)
        rt.preempt_pod("default", "nf-worker-0")
        assert wait_for(
            lambda: phase(cs, "nf") == TrainingJobPhase.RUNNING
            and all(p.metadata.labels[constants.RESTART_COUNT_LABEL] == "1"
                    for p in cs.pods.list("default")), 15)
        victim = sorted({p.spec.node_name for p in cs.pods.list("default")})[0]
        rt.fail_node(victim)
        assert wait_for(
            lambda: phase(cs, "nf") == TrainingJobPhase.RUNNING
            and len(cs.pods.list("default")) == 2
            and all(p.metadata.labels[constants.RESTART_COUNT_LABEL] == "2"
                    and p.spec.node_name != victim
                    for p in cs.pods.list("default")), 20), phase(cs, "nf")


class TestPSWorkerE2E:
    def test_ps_worker_job_completes(self, cluster):
        """BASELINE config 2: PS + worker ReplicaSpecs as real subprocesses,
        rendezvousing through the injected multi-group env."""
        cs, tc, rt = cluster
        job = TPUTrainingJob(metadata=ObjectMeta(name="psjob",
                                                 namespace="default"))
        from trainingjob_operator_tpu.core.objects import EnvVar

        def group(port, n):
            return ReplicaSpec(
                replicas=n,
                template=PodTemplateSpec(spec=PodSpec(containers=[
                    Container(
                        name="aitj-main",
                        command=[sys.executable, "-u", "-m",
                                 "trainingjob_operator_tpu.workloads.ps_worker"],
                        env=[EnvVar("MNIST_STEPS", "8"),
                             EnvVar("MNIST_BATCH", "16"),
                             EnvVar("MNIST_HIDDEN", "16"),
                             EnvVar("PS_TIMEOUT", "60")],
                        ports=[ContainerPort(name=f"aitj-{port}",
                                             container_port=port)])])))

        job.spec.replica_specs["pserver"] = group(7821, 1)
        job.spec.replica_specs["worker"] = group(7831, 2)
        cs.trainingjobs.create(job)
        assert wait_for(
            lambda: phase(cs, "psjob") == TrainingJobPhase.SUCCEEDED, 60), \
            phase(cs, "psjob")
