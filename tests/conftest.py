"""Test configuration.

Controller/client tests are pure-Python.  Workload tests need JAX on a virtual
8-device CPU mesh; set the platform before anything imports jax.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import time as _time


def wait_for(pred, timeout=15.0, interval=0.02):
    """Poll until pred() is truthy; shared by the e2e suites."""
    deadline = _time.time() + timeout
    while _time.time() < deadline:
        if pred():
            return True
        _time.sleep(interval)
    return False
