"""Test configuration.

Controller/client tests are pure-Python.  Workload tests need JAX on a virtual
8-device CPU mesh; set the platform before anything imports jax.
"""

import os
import sys
import time as _time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force CPU with 8 virtual devices: the environment pins JAX to the real TPU
# (axon sitecustomize overrides JAX_PLATFORMS at interpreter start), but tests
# validate multi-chip sharding on a virtual mesh (SURVEY.md §7) and must not
# grab the chip.  The config update after import wins over the plugin pin.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def apply_jax_platform_override():
    """Pin jax to the virtual CPU mesh, beating the axon site hook.  Called
    from the jax-dependent test modules so the pure-Python controller suites
    never pay the jax import at collection time."""
    from trainingjob_operator_tpu.workloads.rendezvous import (
        apply_platform_override)

    apply_platform_override(var="JAX_PLATFORMS")


def wait_for(pred, timeout=45.0, interval=0.02):
    """Poll until pred() is truthy; shared by the e2e suites.

    The default is sized for a LOADED single-core host (this box has
    nproc=1; a concurrent compile starves subprocess pods for tens of
    seconds -- a 15 s deadline produced load-induced flakes).  The happy
    path returns at the first poll after the transition, so a generous
    ceiling costs idle runs nothing."""
    deadline = _time.time() + timeout
    while _time.time() < deadline:
        if pred():
            return True
        _time.sleep(interval)
    return False
