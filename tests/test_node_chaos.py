"""Data-plane failure domains: flap-damped NODE_FAIL, crash-loop
quarantine, node-chaos plan determinism, and the verified-checkpoint
fallback ladder's structured reasons.

Unit layer for the PR's robustness state machines (docs/CHAOS.md data-plane
section, docs/RECOVERY.md integrity ladder):

* TestFlapDamping -- the ``TRAININGJOB_NODE_FLAP_GRACE_S`` debounce against
  the controller + fake clients, with explicit Ready-condition transition
  timestamps so each test pins exactly which side of the grace deadline it
  sits on.
* TestCrashLoopQuarantine -- the ``_crashloop_gate``/``_crashloop_note``
  state machine driven directly with explicit ``now_ts`` values: park after
  N fast failures, one ``CrashLoopQuarantined`` event per episode, flat
  retry cadence, clean-window release.
* TestPlanDeterminism -- same seed => identical node-fault plan digest
  across two FRESH interpreter subprocesses (no shared hash/rng state), and
  the append-only property: adding node streams never perturbs the
  control-plane draws of the same seed.
* TestNodeChaosFleetDeterminism -- two fresh subprocess fleet runs under
  one seed with node chaos armed converge to identical plan digests AND
  identical final phase counts (the seed-is-the-repro contract, end to
  end).
* TestCorruptResumeFallback -- every rung of the resume-image ladder
  returns a CLASSIFIED reason (missing/corrupt/stale/structure_mismatch),
  counts it per reason, and the structured reason lands on the incident
  bundle's resume timeline entry.
"""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.types import (
    RestartPolicy,
    RestartScope,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.core.objects import (
    ConditionStatus,
    make_ready_node,
)
from trainingjob_operator_tpu.fleet.chaos import (
    FAULT_DOMAIN_DOWN,
    FAULT_NODE_DOWN,
    FAULT_NODE_FLAP,
    ChaosGenerator,
    ChaosProfile,
)
from trainingjob_operator_tpu.obs.incident import IncidentRecorder
from trainingjob_operator_tpu.utils.metrics import METRICS, MetricsRegistry
from trainingjob_operator_tpu.workloads import train

from test_controller import (  # noqa: E402
    get_job,
    make_env,
    make_job,
    pods_of,
    set_pod_running,
    sync,
)

REPO_ROOT = Path(__file__).resolve().parents[1]


def _events(cs, reason):
    return [e for e in cs.events.list() if e.reason == reason]


def _counter(name, **labels):
    snap = METRICS.snapshot()
    if labels:
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return snap.get(f"{name}{{{inner}}}", 0.0)
    return snap.get(name, 0.0)


# -- flap damping -------------------------------------------------------------

class TestFlapDamping:
    def _running_job(self, cs, tc, nodes=2):
        for i in range(nodes):
            cs.nodes.create(make_ready_node(f"node-{i}"))
        job = make_job(replicas=2,
                       restart_policy=RestartPolicy.ON_NODE_FAIL,
                       restart_scope=RestartScope.POD)
        cs.trainingjobs.create(job)
        sync(tc, job)
        set_pod_running(cs, "job-trainer-0", node="node-0")
        set_pod_running(cs, "job-trainer-1", node="node-1")
        sync(tc, job)
        assert get_job(cs).status.phase == TrainingJobPhase.RUNNING
        return job

    def _flip_not_ready(self, cs, name, since):
        node = cs.nodes.get_node(name)
        node.status.conditions[0].status = ConditionStatus.FALSE
        node.status.conditions[0].last_transition_time = since
        cs.nodes.update(node)

    def test_flap_within_grace_is_suppressed(self, monkeypatch):
        monkeypatch.setenv(constants.NODE_FLAP_GRACE_ENV, "30.0")
        cs, tc = make_env()
        job = self._running_job(cs, tc)
        # NotReady RIGHT NOW: well inside the 30 s grace.
        self._flip_not_ready(cs, "node-1", since=time.time())
        sync(tc, job, n=3)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.RUNNING
        assert got.status.restart_counts.get("trainer", 0) == 0
        assert len(pods_of(cs)) == 2  # nothing torn down
        # One NodeFlapSuppressed event per (node, episode), not per sync.
        assert len(_events(cs, constants.NODE_FLAP_SUPPRESSED_REASON)) == 1

    def test_flap_recovery_within_grace_costs_nothing(self, monkeypatch):
        monkeypatch.setenv(constants.NODE_FLAP_GRACE_ENV, "30.0")
        cs, tc = make_env()
        job = self._running_job(cs, tc)
        self._flip_not_ready(cs, "node-1", since=time.time())
        sync(tc, job)
        # The node comes back before the grace expires: the flap is fully
        # absorbed -- no restart, no NODE_FAIL, pods untouched.
        node = cs.nodes.get_node("node-1")
        node.status.conditions[0].status = ConditionStatus.TRUE
        node.status.conditions[0].last_transition_time = time.time()
        cs.nodes.update(node)
        sync(tc, job, n=2)
        got = get_job(cs)
        assert got.status.phase == TrainingJobPhase.RUNNING
        assert got.status.restart_counts.get("trainer", 0) == 0

    def test_grace_expiry_fires_node_fail(self, monkeypatch):
        grace = 5.0
        monkeypatch.setenv(constants.NODE_FLAP_GRACE_ENV, str(grace))
        cs, tc = make_env()
        job = self._running_job(cs, tc)
        # Explicit transition timestamp PAST the grace deadline: the
        # debounce must stand aside and the normal NODE_FAIL restart fire.
        self._flip_not_ready(cs, "node-1", since=time.time() - grace - 1.0)
        sync(tc, job)
        assert get_job(cs).status.restart_counts["trainer"] == 1
        assert [p.name for p in pods_of(cs)] == ["job-trainer-0"]

    def test_grace_unset_keeps_immediate_node_fail(self, monkeypatch):
        # No knob => historical behavior: NotReady restarts on the next
        # reconcile, no suppression window, no event.
        monkeypatch.delenv(constants.NODE_FLAP_GRACE_ENV, raising=False)
        cs, tc = make_env()
        job = self._running_job(cs, tc)
        self._flip_not_ready(cs, "node-1", since=time.time())
        sync(tc, job)
        assert get_job(cs).status.restart_counts["trainer"] == 1
        assert _events(cs, constants.NODE_FLAP_SUPPRESSED_REASON) == []


# -- crash-loop quarantine ----------------------------------------------------

class TestCrashLoopQuarantine:
    def _env(self, monkeypatch, after=3, window=30.0, delay=60.0):
        monkeypatch.setenv(constants.CRASHLOOP_AFTER_ENV, str(after))
        monkeypatch.setenv(constants.CRASHLOOP_WINDOW_ENV, str(window))
        monkeypatch.setenv(constants.CRASHLOOP_DELAY_ENV, str(delay))
        cs, tc = make_env()
        job = make_job()
        cs.trainingjobs.create(job)
        return cs, tc, get_job(cs)

    def test_parks_after_consecutive_fast_failures(self, monkeypatch):
        cs, tc, job = self._env(monkeypatch, after=3, window=30.0, delay=60.0)
        t0 = 1000.0
        # Three restarts 5 s apart, each inside the 30 s window.
        for i in range(3):
            now = t0 + 5.0 * i
            assert tc._crashloop_gate(job, "trainer", "trainer", now) is None
            tc._crashloop_note(job, "trainer", now)
        # Fourth attempt at +15 s: parked.
        parked = tc._crashloop_gate(job, "trainer", "trainer", t0 + 15.0)
        assert parked is not None
        phase, msg = parked
        assert phase == TrainingJobPhase.NONE
        assert "crash-loop quarantined" in msg
        quarantined = _events(cs, constants.CRASHLOOP_QUARANTINED_REASON)
        assert len(quarantined) == 1

    def test_one_quarantined_event_per_episode(self, monkeypatch):
        # window > delay so the clean-window release can't fire mid-test.
        cs, tc, job = self._env(monkeypatch, after=2, window=100.0,
                                delay=60.0)
        for i in range(2):
            tc._crashloop_gate(job, "trainer", "trainer", 1000.0 + i)
            tc._crashloop_note(job, "trainer", 1000.0 + i)
        # Repeated reconciles while held: still parked, still ONE event.
        for dt in (2.0, 10.0, 30.0, 50.0):
            assert tc._crashloop_gate(job, "trainer", "trainer",
                                      1001.0 + dt) is not None
        assert len(_events(cs, constants.CRASHLOOP_QUARANTINED_REASON)) == 1

    def test_flat_cadence_allows_retry_after_delay(self, monkeypatch):
        cs, tc, job = self._env(monkeypatch, after=2, window=30.0, delay=10.0)
        for now in (1000.0, 1005.0):
            tc._crashloop_gate(job, "trainer", "trainer", now)
            tc._crashloop_note(job, "trainer", now)
        # Held while inside the flat delay ...
        held = tc._crashloop_gate(job, "trainer", "trainer", 1010.0)
        assert held is not None and "next restart attempt" in held[1]
        # ... the attempt past last+delay proceeds (still quarantined, no
        # second event), and the NEXT fast failure holds for one flat delay
        # again -- a constant cadence, not exponential growth.
        assert tc._crashloop_gate(job, "trainer", "trainer", 1016.0) is None
        tc._crashloop_note(job, "trainer", 1016.0)
        assert tc._crashloop_gate(job, "trainer", "trainer",
                                  1020.0) is not None
        assert tc._crashloop_gate(job, "trainer", "trainer", 1027.0) is None
        assert len(_events(cs, constants.CRASHLOOP_QUARANTINED_REASON)) == 1

    def test_clean_window_releases_with_event(self, monkeypatch):
        cs, tc, job = self._env(monkeypatch, after=2, window=30.0, delay=60.0)
        before = _counter("trainingjob_crashloop_released_total")
        for now in (1000.0, 1005.0):
            tc._crashloop_gate(job, "trainer", "trainer", now)
            tc._crashloop_note(job, "trainer", now)
        assert tc._crashloop_gate(job, "trainer", "trainer",
                                  1010.0) is not None  # parked
        # The incarnation survives a full clean window (30 s past its last
        # failure): released, counter bumped, one CrashLoopReleased event,
        # and the next restart proceeds unparked.
        assert tc._crashloop_gate(job, "trainer", "trainer", 1040.0) is None
        assert len(_events(cs, constants.CRASHLOOP_RELEASED_REASON)) == 1
        assert _counter("trainingjob_crashloop_released_total") == before + 1
        tc._crashloop_note(job, "trainer", 1040.0)
        assert tc._crashloop_gate(job, "trainer", "trainer", 1041.0) is None

    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv(constants.CRASHLOOP_AFTER_ENV, raising=False)
        cs, tc = make_env()
        job = make_job()
        cs.trainingjobs.create(job)
        job = get_job(cs)
        for i in range(10):
            assert tc._crashloop_gate(job, "trainer", "trainer",
                                      1000.0 + i) is None
            tc._crashloop_note(job, "trainer", 1000.0 + i)


# -- plan determinism ---------------------------------------------------------

_DIGEST_SNIPPET = """
import json, sys
from trainingjob_operator_tpu.fleet.chaos import ChaosGenerator, ChaosProfile
plan = ChaosGenerator(ChaosProfile(seed={seed}, duration=4.0, node_flaps=3,
                                   node_kills=1, domain_kills=1)).plan()
print(json.dumps({{"digest": plan.digest(),
                  "faults": [[f.at, f.kind, f.target, f.down]
                             for f in plan.node_faults]}}))
"""


def _plan_in_subprocess(seed):
    proc = subprocess.run(
        [sys.executable, "-c", _DIGEST_SNIPPET.format(seed=seed)],
        capture_output=True, text=True, timeout=120, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout)


class TestPlanDeterminism:
    def test_same_seed_same_digest_across_fresh_interpreters(self):
        a = _plan_in_subprocess(5)
        b = _plan_in_subprocess(5)
        assert a["digest"] == b["digest"]
        assert a["faults"] == b["faults"]
        assert len(a["faults"]) == 5  # 3 flaps + 1 kill + 1 domain kill

    def test_different_seed_different_plan(self):
        assert _plan_in_subprocess(5)["digest"] != \
            _plan_in_subprocess(6)["digest"]

    def test_node_streams_never_perturb_control_plane_draws(self):
        # Node-fault draws come LAST in the generator: a pre-existing
        # control-plane-only profile's fault sequence must stay
        # byte-identical when node streams are added under the same seed.
        base = ChaosProfile(seed=11, duration=4.0)
        extended = ChaosProfile(seed=11, duration=4.0, node_flaps=3,
                                node_kills=1, domain_kills=1)
        pa, pb = ChaosGenerator(base).plan(), ChaosGenerator(extended).plan()
        assert pa.decisions == pb.decisions
        assert pa.spikes == pb.spikes
        assert pa.drops == pb.drops
        assert pa.stale == pb.stale
        assert pa.node_faults == ()
        kinds = {f.kind for f in pb.node_faults}
        assert kinds == {FAULT_NODE_FLAP, FAULT_NODE_DOWN, FAULT_DOMAIN_DOWN}
        # Flaps carry a bounded NotReady duration; permanent kills don't.
        for f in pb.node_faults:
            if f.kind == FAULT_NODE_FLAP:
                assert base.flap_down[0] <= f.down <= base.flap_down[1]
            else:
                assert f.down == 0.0

    def test_plan_digest_is_order_insensitive_to_dict_iteration(self):
        plan = ChaosGenerator(ChaosProfile(seed=3, node_flaps=2)).plan()
        assert plan.digest() == plan.digest()
        assert json.loads(plan.canonical())["node_faults"] == \
            [[f.at, f.kind, f.target, f.down] for f in plan.node_faults]


class TestNodeChaosFleetDeterminism:
    def test_same_seed_same_phase_counts_across_subprocesses(self):
        cmd = [sys.executable, "-m",
               "trainingjob_operator_tpu.fleet.harness",
               "--jobs", "8", "--seed", "13", "--duration", "1.0",
               "--replicas-min", "1", "--replicas-max", "2",
               "--pods-per-node", "2", "--nodes-per-slice", "2",
               "--workers", "4", "--node-chaos", "--node-flaps", "2",
               "--node-kills", "1", "--domain-kills", "1",
               "--converge-timeout", "90", "--quiet"]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TRAININGJOB_NODE_FLAP_GRACE_S="1.0")
        reports = []
        for _ in range(2):
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=300, cwd=REPO_ROOT, env=env)
            assert proc.returncode == 0, \
                (proc.stderr or proc.stdout)[-2000:]
            reports.append(json.loads(proc.stdout))
        a, b = reports
        for rep in reports:
            assert rep["converged"] and not rep["violations"]
            assert rep["unattributed_downtime_ms"] == 0.0
        assert a["chaos"]["plan_digest"] == b["chaos"]["plan_digest"]
        assert a["phase_counts"] == b["phase_counts"]


# -- verified-checkpoint fallback reasons -------------------------------------

class TestCorruptResumeFallback:
    TEMPLATE = {"step": 0, "x": np.arange(4)}

    def _image_path(self, tmp_path):
        return tmp_path / train._RESUME_IMAGE

    def _load(self, tmp_path, latest=3):
        train._LAST_RESUME_FALLBACK = ""
        return train._load_resume_image(str(tmp_path), latest, self.TEMPLATE)

    def _assert_reason(self, tmp_path, reason, latest=3):
        before = _counter("trainingjob_resume_image_fallbacks_total",
                          reason=reason)
        assert self._load(tmp_path, latest) is None
        assert train._LAST_RESUME_FALLBACK == reason
        assert _counter("trainingjob_resume_image_fallbacks_total",
                        reason=reason) == before + 1

    def test_missing_image_classified(self, tmp_path):
        self._assert_reason(tmp_path, "missing")

    def test_flipped_payload_byte_fails_sha_footer(self, tmp_path):
        train._write_resume_image(str(tmp_path), 3,
                                  {"step": 3, "x": np.arange(4)})
        image = self._image_path(tmp_path)
        raw = bytearray(image.read_bytes())
        raw[len(raw) // 2] ^= 0xFF  # one bit of payload, footer untouched
        image.write_bytes(bytes(raw))
        self._assert_reason(tmp_path, "corrupt")

    def test_truncated_image_classified_corrupt(self, tmp_path):
        self._image_path(tmp_path).write_bytes(b"\x00" * train._CKPT_SHA_LEN)
        self._assert_reason(tmp_path, "corrupt")

    def test_injection_knob_forces_corrupt_rung(self, tmp_path, monkeypatch):
        train._write_resume_image(str(tmp_path), 3,
                                  {"step": 3, "x": np.arange(4)})
        assert self._load(tmp_path) is not None  # image is genuinely valid
        monkeypatch.setenv(constants.CKPT_FAULT_ENV, "resume_image")
        self._assert_reason(tmp_path, "corrupt")

    def test_stale_image_classified(self, tmp_path):
        train._write_resume_image(str(tmp_path), 2,
                                  {"step": 2, "x": np.arange(4)})
        self._assert_reason(tmp_path, "stale", latest=3)

    def test_tree_shape_drift_classified(self, tmp_path):
        train._write_resume_image(str(tmp_path), 3,
                                  {"step": 3, "y": np.arange(4)})
        self._assert_reason(tmp_path, "structure_mismatch")

    def test_structured_reason_lands_on_incident_bundle(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        job = "default/incjob"
        rec.on_interruption(job, "ALL", constants.RESTARTING_REASON,
                            now=100.0)
        rec.record_event(job, constants.RESTARTING_REASON, "restarting",
                         ts=100.2)
        rec.on_running(job, now=102.0)
        rec.record_resume(job, restore_ms=300.0, compile_ms=500.0,
                          overlapped=True, now=102.9, fallback="corrupt")
        rec.record_step(job, step=5, ms=100.0, now=103.0)
        (bundle,) = rec.bundles(job)
        resume_entries = [e for e in bundle["timeline"]
                          if e["kind"] == "resume"]
        assert resume_entries == [{"ts": 102.9, "kind": "resume",
                                   "restore_ms": 300.0, "compile_ms": 500.0,
                                   "overlapped": True,
                                   "fallback": "corrupt"}]

    def test_happy_path_resume_entry_has_no_fallback_key(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        job = "default/incjob"
        rec.on_interruption(job, "ALL", constants.RESTARTING_REASON,
                            now=100.0)
        rec.record_event(job, constants.RESTARTING_REASON, "restarting",
                         ts=100.2)
        rec.on_running(job, now=102.0)
        rec.record_resume(job, restore_ms=300.0, compile_ms=500.0,
                          overlapped=True, now=102.9)
        rec.record_step(job, step=5, ms=100.0, now=103.0)
        (bundle,) = rec.bundles(job)
        (entry,) = [e for e in bundle["timeline"] if e["kind"] == "resume"]
        assert "fallback" not in entry
