"""API layer tests: types round-trip, defaults, validation, TPU topology.

Test strategy per SURVEY.md §4: the reference has zero tests; unit tests of the
schema/defaulting/validation layer are level (1) of the pyramid.
"""

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.api.defaults import set_defaults
from trainingjob_operator_tpu.api.tpu import (
    chips_in_topology,
    mesh_axes_for,
    parse_topology,
    resolve_slice_shape,
    total_hosts,
)
from trainingjob_operator_tpu.api.types import (
    CleanPodPolicy,
    EdlPolicy,
    EndingPolicy,
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUSpec,
    TPUTrainingJob,
    TrainingJobPhase,
    is_failed_phase,
)
from trainingjob_operator_tpu.api.validation import validate_job, validate_job_or_raise, ValidationError
from trainingjob_operator_tpu.core.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)

# A manifest in the reference's shape (example/paddle-mnist.yaml), retargeted.
MNIST_YAML = """
apiVersion: "tpu.trainingjob.dev/v1"
kind: "TPUTrainingJob"
metadata:
  name: "paddle-mnist"
spec:
  cleanPodPolicy: All
  restartingExitCode: 137,128
  replicaSpecs:
    trainer:
      replicas: 1
      completePolicy: All
      failPolicy: Rank0
      restartLimit: 1
      restartPolicy: OnNodeFailWithExitCode
      template:
        spec:
          hostNetwork: true
          restartPolicy: Never
          containers:
            - name: "aitj-trainer"
              image: "example/mnist"
              ports:
                - name: "aitj-24446"
                  containerPort: 24446
              command: ["/bin/bash"]
              args: ["-c", "python train.py"]
"""


def make_job(name="job", replicas=2, **spec_kw) -> TPUTrainingJob:
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.spec.replica_specs["trainer"] = ReplicaSpec(
        replicas=replicas,
        template=PodTemplateSpec(spec=PodSpec(containers=[
            Container(name="aitj-main", image="img",
                      ports=[ContainerPort(name="aitj-2222", container_port=2222)])
        ])),
        **spec_kw,
    )
    return job


class TestYamlRoundTrip:
    def test_parse_reference_shaped_manifest(self):
        job = TPUTrainingJob.from_yaml(MNIST_YAML)
        assert job.name == "paddle-mnist"
        assert job.spec.clean_pod_policy == CleanPodPolicy.ALL
        assert job.spec.restarting_exit_code == "137,128"
        trainer = job.spec.replica_specs["trainer"]
        assert trainer.replicas == 1
        assert trainer.fail_policy == EndingPolicy.RANK0
        assert trainer.restart_limit == 1
        assert trainer.restart_policy == RestartPolicy.ON_NODE_FAIL_WITH_EXIT_CODE
        assert trainer.template.spec.host_network is True
        c = trainer.template.spec.containers[0]
        assert c.name == "aitj-trainer"
        assert c.ports[0].container_port == 24446

    def test_round_trip_preserves_spec(self):
        job = TPUTrainingJob.from_yaml(MNIST_YAML)
        job2 = TPUTrainingJob.from_yaml(job.to_yaml())
        assert job2.to_dict() == job.to_dict()

    def test_accepts_reference_kind_spelling(self):
        job = TPUTrainingJob.from_dict(
            {"kind": "AITrainingJob", "metadata": {"name": "x"}, "spec": {}})
        assert job.name == "x"

    def test_rejects_unknown_kind(self):
        with pytest.raises(ValueError):
            TPUTrainingJob.from_dict({"kind": "Deployment", "metadata": {"name": "x"}})

    def test_status_round_trip(self):
        job = make_job()
        job.status.phase = TrainingJobPhase.RUNNING
        job.status.restart_counts["trainer"] = 3
        job.status.start_time = 1000.0
        d = job.to_dict()
        job2 = TPUTrainingJob.from_dict(d)
        assert job2.status.phase == TrainingJobPhase.RUNNING
        assert job2.status.restart_counts == {"trainer": 3}
        assert job2.status.start_time == 1000.0


class TestDefaults:
    def test_job_defaults(self):
        # Reference: defaults.go:34-53.
        job = make_job()
        set_defaults(job)
        assert job.spec.clean_pod_policy == CleanPodPolicy.ALL
        assert job.spec.fail_policy == EndingPolicy.ANY
        assert job.spec.complete_policy == EndingPolicy.ALL

    def test_replica_defaults(self):
        # Reference: defaults.go:15-31.
        job = TPUTrainingJob(metadata=ObjectMeta(name="j"))
        job.spec.replica_specs["w"] = ReplicaSpec(
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(name="c")])))
        set_defaults(job)
        w = job.spec.replica_specs["w"]
        assert w.replicas == 1
        assert w.restart_policy == RestartPolicy.NEVER
        assert w.restart_scope == RestartScope.ALL
        assert w.fail_policy == EndingPolicy.ANY
        assert w.complete_policy == EndingPolicy.ALL
        assert w.edl_policy == EdlPolicy.NEVER
        assert w.min_replicas == 1 and w.max_replicas == 1

    def test_defaults_do_not_override_explicit(self):
        job = make_job(replicas=4, restart_policy=RestartPolicy.ALWAYS,
                       restart_scope=RestartScope.POD,
                       fail_policy=EndingPolicy.ALL,
                       complete_policy=EndingPolicy.ANY,
                       min_replicas=2, max_replicas=8,
                       edl_policy=EdlPolicy.AUTO)
        set_defaults(job)
        t = job.spec.replica_specs["trainer"]
        assert (t.replicas, t.min_replicas, t.max_replicas) == (4, 2, 8)
        assert t.restart_policy == RestartPolicy.ALWAYS
        assert t.restart_scope == RestartScope.POD
        assert t.edl_policy == EdlPolicy.AUTO


class TestValidation:
    def test_valid_job_passes(self):
        job = set_defaults(make_job())
        assert validate_job(job) == []

    def test_missing_name(self):
        job = make_job(name="")
        assert any("metadata.name" in e for e in validate_job(job))

    def test_empty_replica_specs(self):
        job = TPUTrainingJob(metadata=ObjectMeta(name="j"))
        assert any("replicaSpecs" in e for e in validate_job(job))

    def test_empty_containers_rejected(self):
        # Reference intent: validation.go:17-19 (dead code there, real here).
        job = TPUTrainingJob(metadata=ObjectMeta(name="j"))
        job.spec.replica_specs["w"] = ReplicaSpec()
        assert any("containers" in e for e in validate_job(job))

    def test_image_required_mode(self):
        # Reference intent: validation.go:20-25.
        job = TPUTrainingJob(metadata=ObjectMeta(name="j"))
        job.spec.replica_specs["w"] = ReplicaSpec(
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(name="c")])))
        assert validate_job(job, require_image=False) == []
        assert any("no image" in e for e in validate_job(job, require_image=True))

    def test_bad_enums(self):
        job = make_job(restart_policy="Sometimes")
        job.spec.fail_policy = "Most"
        errs = validate_job(job)
        assert any("restartPolicy" in e for e in errs)
        assert any("failPolicy" in e for e in errs)

    def test_bad_exit_codes(self):
        job = make_job()
        job.spec.restarting_exit_code = "137,x"
        assert any("restartingExitCode" in e for e in validate_job(job))

    def test_min_max_consistency(self):
        job = make_job(replicas=4, min_replicas=6, max_replicas=5)
        errs = validate_job(job)
        assert any("minReplicas > maxReplicas" in e for e in errs)

    def test_raise_helper(self):
        with pytest.raises(ValidationError):
            validate_job_or_raise(TPUTrainingJob())

    def test_bad_topology(self):
        job = make_job()
        job.spec.replica_specs["trainer"].tpu = TPUSpec(topology="4xz")
        assert any("topology" in e for e in validate_job(job))


class TestPhases:
    def test_ending_phase_classification(self):
        # Reference: status.go:89-99 -- Succeeded is ending but not failed.
        assert not is_failed_phase(TrainingJobPhase.SUCCEEDED)
        assert is_failed_phase(TrainingJobPhase.FAILED)
        assert is_failed_phase(TrainingJobPhase.TIMEOUT)
        assert is_failed_phase(TrainingJobPhase.PREEMPTED)
        assert is_failed_phase(TrainingJobPhase.NODE_FAIL)
        assert not is_failed_phase(TrainingJobPhase.RUNNING)

    def test_succeeded_spelling_matches_reference(self):
        # Reference: types.go:111 spells the phase "Succeed".
        assert TrainingJobPhase.SUCCEEDED == "Succeed"


class TestTPUTopology:
    def test_parse(self):
        assert parse_topology("4x4") == (4, 4)
        assert parse_topology("2x2x4") == (2, 2, 4)
        with pytest.raises(ValueError):
            parse_topology("4")
        with pytest.raises(ValueError):
            parse_topology("4x0")

    def test_chips_and_hosts_v5e(self):
        # v5e: 4 chips per TPU-VM host.
        assert chips_in_topology("2x4") == 8
        s = resolve_slice_shape(TPUSpec(accelerator="tpu-v5-lite-podslice", topology="4x4"))
        assert s.chips == 16 and s.hosts == 4 and s.chips_per_host == 4
        s32 = resolve_slice_shape(TPUSpec(topology="4x8"))
        assert s32.chips == 32 and s32.hosts == 8

    def test_single_host_slice(self):
        s = resolve_slice_shape(TPUSpec(topology="2x2"))
        assert s.hosts == 1 and s.chips == 4

    def test_total_hosts_multislice(self):
        tpu = TPUSpec(topology="4x4", slice_count=4)
        assert total_hosts(tpu) == 16

    def test_node_selectors(self):
        s = resolve_slice_shape(TPUSpec(accelerator="tpu-v5-lite-podslice",
                                        topology="2x4"))
        sel = s.node_selectors(preemptible=True)
        assert sel[constants.GKE_TPU_ACCELERATOR_SELECTOR] == "tpu-v5-lite-podslice"
        assert sel[constants.GKE_TPU_TOPOLOGY_SELECTOR] == "2x4"
        assert sel[constants.GKE_SPOT_SELECTOR] == "true"
        assert s.tpu_resources() == {constants.TPU_RESOURCE: 4}

    def test_mesh_axes(self):
        axes = mesh_axes_for(TPUSpec(topology="4x4", slice_count=2))
        assert axes == [("slice", 2), ("host", 4), ("chip", 4)]
