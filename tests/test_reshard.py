"""Elastic-resize fast path: reshard plan arithmetic, the rendezvous
generation channel, the live redistribute executor, and the controller's
survivor-keepalive drain end to end against the sim cluster
(docs/ELASTIC.md).
"""

import json
import os
import time

import pytest

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.parallel import reshard
from trainingjob_operator_tpu.workloads import rendezvous

from conftest import wait_for  # noqa: E402


# -- plan arithmetic (pure) ---------------------------------------------------


class TestShardRanges:
    def test_even_split(self):
        assert reshard.shard_ranges(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_uneven_remainder_is_jax_style(self):
        # ceil chunking: every shard but the last holds ceil(10/4)=3.
        assert reshard.shard_ranges(10, 4) == [(0, 3), (3, 6), (6, 9), (9, 10)]

    def test_more_shards_than_elements(self):
        ranges = reshard.shard_ranges(2, 4)
        assert ranges == [(0, 1), (1, 2), (2, 2), (2, 2)]

    def test_rejects_bad_inputs(self):
        with pytest.raises(ValueError):
            reshard.shard_ranges(-1, 2)
        with pytest.raises(ValueError):
            reshard.shard_ranges(4, 0)


class TestPlanExchange:
    def test_wide_to_narrow_partitions_exactly(self):
        plan = reshard.plan_exchange(8, old_shards=4, new_shards=2)
        # Segments partition [0, n): every element accounted for once.
        covered = sorted((s.start, s.stop) for s in plan.segments)
        flat = []
        for a, b in covered:
            flat.extend(range(a, b))
        assert flat == list(range(8))
        assert plan.covered
        # Old shard 0 [0,2) lands inside new shard 0 [0,4): stationary.
        assert any(s.src == 0 and s.dst == 0 for s in plan.stationary)
        # Old shard 3 [6,8) must cross to new shard 1: a move.
        assert any(s.src == 3 and s.dst == 1 for s in plan.moves)

    def test_narrow_to_wide(self):
        plan = reshard.plan_exchange(8, old_shards=2, new_shards=4)
        assert plan.covered
        sizes = sum(s.size for s in plan.segments)
        assert sizes == 8
        # Only runs whose old and new shard INDEX coincide stay put: new
        # shard 0 keeps old shard 0's first half and new shard 1 receives
        # old shard... 0 again (indices differ) -- 6 of 8 bytes move.
        assert plan.bytes_moved(itemsize=1) == 6
        assert sum(s.size for s in plan.stationary) == 2

    def test_uneven_remainders_cover(self):
        plan = reshard.plan_exchange(10, old_shards=3, new_shards=4)
        assert plan.covered
        assert sum(s.size for s in plan.segments) == 10

    def test_lost_shard_yields_missing_segments(self):
        plan = reshard.plan_exchange(8, old_shards=4, new_shards=2, lost=[3])
        assert not plan.covered
        missing = plan.missing
        assert missing and all(s.src is None for s in missing)
        # The lost shard held [6,8): exactly those elements are missing.
        assert sorted((s.start, s.stop) for s in missing) == [(6, 8)]
        assert plan.stats(itemsize=1)["missing_bytes"] == 2

    def test_stationary_dominates_small_shrink(self):
        # 7->6 shards of a large axis: most bytes do not move at all --
        # the reason in-place reshard beats any checkpoint restore.
        plan = reshard.plan_exchange(4096, old_shards=7, new_shards=6)
        stats = plan.stats(itemsize=1)
        assert stats["moved_bytes"] < 4096
        assert stats["stationary_bytes"] > stats["moved_bytes"]


class TestPlanPytree:
    SHAPES = {"w1": (64, 16), "w2": (64,), "scalar": ()}

    def test_aggregates_and_scales_off_axis(self):
        agg = reshard.plan_pytree_exchange(self.SHAPES, 4, 2, itemsize=4)
        assert agg["covered"]
        assert set(agg["plans"]) == {"w1", "w2"}  # scalars skipped
        # w1's rows are 16 floats wide: its byte counts are 64x w2's.
        totals = (agg["moved_bytes"] + agg["stationary_bytes"]
                  + agg["missing_bytes"])
        assert totals == 64 * 16 * 4 + 64 * 4

    def test_lost_shard_uncovers_pytree(self):
        agg = reshard.plan_pytree_exchange(self.SHAPES, 4, 2, lost=[0])
        assert not agg["covered"]
        assert agg["missing_bytes"] > 0


# -- generation channel (rendezvous.py) --------------------------------------


def _write_doc(path, doc, bump_mtime=True):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    if bump_mtime:
        # Force a distinct mtime: same-second writes are invisible to the
        # watcher's stat gate on coarse filesystems.
        st = os.stat(path)
        os.utime(path, (st.st_atime, st.st_mtime + 1))


class TestGenerationChannel:
    def test_read_generation_roundtrip(self, tmp_path):
        path = str(tmp_path / "generation.json")
        doc = {"generation": 2, "world": [0, 2], "num_processes": 2}
        _write_doc(path, doc, bump_mtime=False)
        assert rendezvous.read_generation(path) == doc

    def test_read_generation_rejects_garble(self, tmp_path):
        path = str(tmp_path / "generation.json")
        assert rendezvous.read_generation(path) is None  # absent
        for bad in ("not json", json.dumps([1, 2]),
                    json.dumps({"generation": "2", "world": [0]}),
                    json.dumps({"generation": 0, "world": [0]}),
                    json.dumps({"generation": 2, "world": "0,1"})):
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(bad)
            assert rendezvous.read_generation(path) is None

    def test_watcher_ignores_birth_generation(self, tmp_path):
        path = str(tmp_path / "generation.json")
        _write_doc(path, {"generation": 3, "world": [0, 1]})
        w = rendezvous.GenerationWatcher(path=path, birth=3, interval=0.0)
        assert w.poll(now=0.0) is None  # born into generation 3: no react
        _write_doc(path, {"generation": 4, "world": [0]})
        doc = w.poll(now=1.0)
        assert doc is not None and doc["generation"] == 4

    def test_watcher_surfaces_each_generation_once(self, tmp_path):
        path = str(tmp_path / "generation.json")
        w = rendezvous.GenerationWatcher(path=path, birth=0, interval=0.0)
        assert w.poll(now=0.0) is None  # no file yet
        _write_doc(path, {"generation": 1, "world": [0, 2]})
        assert w.poll(now=1.0)["generation"] == 1
        assert w.poll(now=2.0) is None  # same doc: surfaced once

    def test_watcher_rate_limit(self, tmp_path):
        path = str(tmp_path / "generation.json")
        _write_doc(path, {"generation": 1, "world": [0]})
        w = rendezvous.GenerationWatcher(path=path, birth=0, interval=10.0)
        assert w.poll(now=0.0)["generation"] == 1
        _write_doc(path, {"generation": 2, "world": [0]})
        assert w.poll(now=5.0) is None  # inside the poll interval
        assert w.poll(now=11.0)["generation"] == 2

    def test_from_env_reads_resize_channel(self):
        rdv = rendezvous.from_env({
            constants.JOB_NAME_ENV: "j",
            constants.RESIZE_DIR_ENV: "/rdv/j",
            constants.RENDEZVOUS_GENERATION_ENV: "5",
        })
        assert rdv.resize_dir == "/rdv/j"
        assert rdv.rendezvous_generation == 5
        assert rdv.generation_path == os.path.join("/rdv/j",
                                                   "generation.json")


# -- live redistribute (virtual 8-device CPU mesh) ---------------------------


class TestRedistribute:
    def test_values_preserved_across_mesh_widths(self):
        jax = pytest.importorskip("jax")
        from conftest import apply_jax_platform_override
        apply_jax_platform_override()
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh

        old_mesh = make_mesh(MeshSpec.of(fsdp=4),
                             devices=jax.devices()[:4])
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        sharded = jax.device_put(
            x, NamedSharding(old_mesh, P("fsdp", None)))
        scalar = jax.device_put(
            np.float32(7.0), NamedSharding(old_mesh, P()))

        new_mesh = make_mesh(MeshSpec.of(fsdp=2),
                             devices=jax.devices()[:2])
        out = reshard.redistribute({"w": sharded, "c": scalar}, new_mesh)
        np.testing.assert_array_equal(np.asarray(out["w"]), x)
        assert float(out["c"]) == 7.0
        # The leaf's own spec survived the re-fit onto the narrower mesh.
        assert out["w"].sharding.mesh.shape["fsdp"] == 2
        assert tuple(out["w"].sharding.spec)[:1] == ("fsdp",)


# -- survivor-keepalive drain e2e (controller + sim) -------------------------


from trainingjob_operator_tpu.api.types import (  # noqa: E402
    ReplicaSpec,
    RestartPolicy,
    RestartScope,
    TPUTrainingJob,
    TrainingJobPhase,
)
from trainingjob_operator_tpu.client.clientset import Clientset  # noqa: E402
from trainingjob_operator_tpu.cmd.options import OperatorOptions  # noqa: E402
from trainingjob_operator_tpu.controller.controller import (  # noqa: E402
    TrainingJobController,
)
from trainingjob_operator_tpu.core.objects import (  # noqa: E402
    Container,
    ContainerPort,
    EnvVar,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
)
from trainingjob_operator_tpu.obs.incident import INCIDENTS  # noqa: E402
from trainingjob_operator_tpu.runtime.sim import (  # noqa: E402
    RUN_SECONDS_ANNOTATION,
    SimRuntime,
)


@pytest.fixture
def cluster():
    cs = Clientset()
    tc = TrainingJobController(cs, options=OperatorOptions(resync_period=0.05))
    sim = SimRuntime(cs)
    sim.start()
    tc.run(workers=2)
    yield cs, tc, sim
    tc.stop()
    sim.stop()


def resize_job(name, rdv_dir, replicas=3):
    job = TPUTrainingJob(metadata=ObjectMeta(name=name, namespace="default"))
    template = PodTemplateSpec(
        metadata=ObjectMeta(annotations={RUN_SECONDS_ANNOTATION: "30"}),
        spec=PodSpec(containers=[
            Container(name="aitj-main",
                      env=[EnvVar(name=constants.RESIZE_DIR_ENV,
                                  value=rdv_dir)],
                      ports=[ContainerPort(name="aitj-7777",
                                           container_port=7777)])]))
    job.spec.replica_specs["trainer"] = ReplicaSpec(
        replicas=replicas, min_replicas=1, template=template,
        restart_policy=RestartPolicy.EXIT_CODE,
        restart_scope=RestartScope.RESIZE)
    job.spec.restarting_exit_code = "137,143"
    return job


class TestResizeE2E:
    def test_kill_one_replica_keeps_survivors_alive(self, cluster, tmp_path):
        cs, tc, sim = cluster
        sim.add_node("n0")
        rdv_dir = str(tmp_path / "rdv")
        name = "ej"
        key = f"default/{name}"
        INCIDENTS.forget(key)
        cs.trainingjobs.create(resize_job(name, rdv_dir))

        def phase():
            return cs.trainingjobs.get("default", name).status.phase

        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10), phase()
        before = {p.metadata.name: p.metadata.uid
                  for p in cs.pods.list("default")}
        assert len(before) == 3

        sim.preempt_pod("default", f"{name}-trainer-1", exit_code=137)

        # The drain deletes only the failed replica; the job comes back
        # Running at width 2 with the survivors' pods untouched.
        assert wait_for(
            lambda: cs.trainingjobs.get("default", name)
            .status.lost_indices.get("trainer") == [1], 10)
        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10), phase()
        assert wait_for(
            lambda: len(cs.pods.list("default")) == 2, 10)
        after = {p.metadata.name: p.metadata.uid
                 for p in cs.pods.list("default")}
        assert after == {n: u for n, u in before.items()
                         if n != f"{name}-trainer-1"}  # same uids: kept alive

        job = cs.trainingjobs.get("default", name)
        assert job.status.rendezvous_generation == 1
        assert job.status.resize_replica_name == ""
        assert job.status.restart_counts.get("trainer") == 1

        # The bumped generation was republished for the survivors.
        doc = rendezvous.read_generation(
            os.path.join(rdv_dir, "generation.json"))
        assert doc is not None
        assert doc["generation"] == 1
        assert doc["world"] == [0, 2]
        assert doc["num_processes"] == 2
        assert len(doc["hosts"]) == 2

    def test_incident_bundle_attributes_reshard_not_teardown(
            self, cluster, tmp_path):
        cs, tc, sim = cluster
        sim.add_node("n0")
        name = "ej2"
        key = f"default/{name}"
        INCIDENTS.forget(key)
        cs.trainingjobs.create(resize_job(name, str(tmp_path / "rdv")))

        def phase():
            return cs.trainingjobs.get("default", name).status.phase

        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10)
        time.sleep(0.1)  # let the incident window open cleanly after Running
        sim.preempt_pod("default", f"{name}-trainer-2", exit_code=137)
        assert wait_for(
            lambda: cs.trainingjobs.get("default", name)
            .status.lost_indices.get("trainer") == [2], 10)
        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10)

        def bundle():
            bundles = INCIDENTS.bundles(key) or []
            return bundles[-1] if bundles else None

        assert wait_for(lambda: bundle() is not None, 10)
        b = bundle()
        assert b["kind"] == "resize"
        assert b["phases"]["teardown"] == 0.0  # survivors never tore down
        assert b["phases"].get("reshard", 0.0) >= 0.0
        assert "reshard" in b["phases"]
        # All downtime lands in the resize phases, nothing unattributed.
        assert b["phases"]["unknown"] == 0.0
        attributed = (b["phases"]["detect"] + b["phases"]["reshard"]
                      + b["phases"]["first_step"])
        assert attributed == pytest.approx(b["downtime_ms"], rel=1e-6)

    def test_floor_falls_back_to_restart_all(self, cluster, tmp_path):
        """A resize that would drop survivors below min_replicas restarts
        the world instead (ReshardFellBack)."""
        cs, tc, sim = cluster
        sim.add_node("n0")
        name = "ej3"
        INCIDENTS.forget(f"default/{name}")
        job = resize_job(name, str(tmp_path / "rdv"), replicas=1)
        cs.trainingjobs.create(job)

        def phase():
            return cs.trainingjobs.get("default", name).status.phase

        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10)
        uid = cs.pods.list("default")[0].metadata.uid
        sim.preempt_pod("default", f"{name}-trainer-0", exit_code=137)
        assert wait_for(
            lambda: cs.trainingjobs.get("default", name)
            .status.restart_counts.get("trainer", 0) == 1, 10)
        assert wait_for(lambda: phase() == TrainingJobPhase.RUNNING, 10)
        pods = cs.pods.list("default")
        assert len(pods) == 1
        assert pods[0].metadata.uid != uid  # restarted, not kept
        job = cs.trainingjobs.get("default", name)
        assert job.status.lost_indices.get("trainer") in (None, [])
        assert job.status.rendezvous_generation == 0
