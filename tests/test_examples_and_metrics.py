"""Example manifests parse/validate; metrics registry and HTTP exposition."""

import glob
import json
import os
import urllib.request

from trainingjob_operator_tpu.api.defaults import set_defaults
from trainingjob_operator_tpu.api.types import TPUTrainingJob
from trainingjob_operator_tpu.api.validation import validate_job
from trainingjob_operator_tpu.utils.metrics import (
    MetricsRegistry,
    serve_metrics,
)

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


class TestExamples:
    def test_all_examples_parse_validate_roundtrip(self):
        paths = sorted(glob.glob(os.path.join(EXAMPLES, "*.yaml")))
        assert len(paths) >= 5  # one per BASELINE config
        for path in paths:
            job = TPUTrainingJob.from_yaml(open(path).read())
            set_defaults(job)
            violations = validate_job(job)
            assert violations == [], f"{os.path.basename(path)}: {violations}"
            # Round-trip stability.
            again = TPUTrainingJob.from_dict(job.to_dict())
            assert again.to_dict() == job.to_dict(), path

    def test_volumes_survive_the_pod_model(self):
        # A user's corpus/checkpoint volumes must round-trip through the
        # template model -- a stripped mount would crash the workload at a
        # nonexistent path (the flagship example mounts /data).
        path = os.path.join(EXAMPLES, "llama2-7b-elastic-v5e32.yaml")
        job = TPUTrainingJob.from_yaml(open(path).read())
        tmpl = job.spec.replica_specs["trainer"].template
        assert tmpl.spec.volumes and tmpl.spec.volumes[0]["name"] == "corpus"
        mounts = tmpl.spec.containers[0].volume_mounts
        assert mounts and mounts[0]["mountPath"] == "/data"

    def test_elastic_example_declares_range(self):
        job = TPUTrainingJob.from_yaml(
            open(os.path.join(EXAMPLES, "llama2-7b-elastic-v5e32.yaml")).read())
        spec = job.spec.replica_specs["trainer"]
        assert spec.edl_policy == "Auto"
        assert spec.min_replicas < spec.replicas
        assert spec.tpu is not None and spec.tpu.preemptible

    def test_tpu_examples_geometry_consistent(self):
        from trainingjob_operator_tpu.api.tpu import resolve_slice_shape

        for name in ("resnet50-v5e8.yaml", "bert-v5e16.yaml",
                     "llama2-7b-elastic-v5e32.yaml"):
            job = TPUTrainingJob.from_yaml(
                open(os.path.join(EXAMPLES, name)).read())
            spec = job.spec.replica_specs["trainer"]
            shape = resolve_slice_shape(spec.tpu)
            assert shape.hosts * spec.tpu.slice_count == spec.replicas, name


class TestMetrics:
    def test_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.inc("ops_total")
        reg.inc("ops_total", 2)
        reg.gauge("depth", lambda: 7.0)
        for v in (0.002, 0.02, 0.2, 2.0):
            reg.observe("latency_seconds", v)
        snap = reg.snapshot()
        assert snap["ops_total"] == 3
        assert snap["depth"] == 7.0
        assert snap["latency_seconds_count"] == 4
        assert snap["latency_seconds_p50"] > 0

    def test_labels(self):
        reg = MetricsRegistry()
        reg.inc("restarts_total", rtype="trainer")
        reg.inc("restarts_total", rtype="pserver")
        snap = reg.snapshot()
        assert snap['restarts_total{rtype="trainer"}'] == 1

    def test_prometheus_rendering(self):
        reg = MetricsRegistry()
        reg.inc("a_total", 5)
        reg.observe("lat", 0.003)
        text = reg.render_prometheus()
        assert "a_total 5" in text
        assert 'lat_bucket{le="0.005"} 1' in text
        assert "lat_count 1" in text

    def test_http_endpoint(self):
        reg = MetricsRegistry()
        reg.inc("hits_total")
        server = serve_metrics(0, reg)
        try:
            port = server.server_address[1]
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics").read().decode()
            assert "hits_total 1" in text
            js = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json").read())
            assert js["hits_total"] == 1
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz").read()
            assert health == b"ok\n"
            dump = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/threads").read().decode()
            assert "metrics-http" in dump
        finally:
            server.shutdown()

    def test_controller_reports(self):
        from trainingjob_operator_tpu.client.clientset import Clientset
        from trainingjob_operator_tpu.controller.controller import (
            TrainingJobController)
        from trainingjob_operator_tpu.core.objects import (
            Container,
            ContainerPort,
            ObjectMeta,
            PodSpec,
            PodTemplateSpec,
        )
        from trainingjob_operator_tpu.api.types import ReplicaSpec

        cs = Clientset()
        tc = TrainingJobController(cs)
        job = TPUTrainingJob(metadata=ObjectMeta(name="m", namespace="default"))
        job.spec.replica_specs["trainer"] = ReplicaSpec(
            replicas=2,
            template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(name="aitj-main", image="img",
                          ports=[ContainerPort(name="aitj-1", container_port=1)])])))
        cs.trainingjobs.create(job)
        before = tc.metrics.snapshot().get("trainingjob_pods_created_total", 0)
        tc.sync_handler("default/m")
        snap = tc.metrics.snapshot()
        assert snap["trainingjob_pods_created_total"] >= before + 2
        assert snap["trainingjob_reconcile_seconds_count"] >= 1
        # Gauges register on run() and deregister on stop() (a stopped
        # controller must not shadow a running one in the global registry).
        tc.run(workers=1)
        assert tc.metrics.snapshot()["trainingjob_jobs"] >= 1.0
        tc.stop()
        assert "trainingjob_jobs" not in tc.metrics.snapshot()


class TestPrometheusExposition:
    """The text-format code path the seed's metrics.py:147 SyntaxError lived
    in: histogram bucket lines with escaped ``le="..."`` labels, label
    sorting, the +Inf bucket, and label-value escaping."""

    def test_labeled_histogram_bucket_lines(self):
        reg = MetricsRegistry()
        reg.observe("sync_seconds", 0.003, component="controller")
        reg.observe("sync_seconds", 0.7, component="controller")
        text = reg.render_prometheus()
        # le= is appended inside the existing label braces, comma-separated.
        assert 'sync_seconds_bucket{component="controller",le="0.005"} 1' in text
        assert 'sync_seconds_bucket{component="controller",le="1.0"} 2' in text
        assert 'sync_seconds_sum{component="controller"} 0.703' in text
        assert 'sync_seconds_count{component="controller"} 2' in text

    def test_bucket_counts_are_cumulative(self):
        reg = MetricsRegistry()
        for v in (0.002, 0.002, 0.02, 2.0):
            reg.observe("lat", v)
        text = reg.render_prometheus()
        assert 'lat_bucket{le="0.005"} 2' in text   # both 2ms observations
        assert 'lat_bucket{le="0.05"} 3' in text    # + the 20ms one
        assert 'lat_bucket{le="30.0"} 4' in text    # + the 2s one

    def test_plus_inf_bucket_always_equals_count(self):
        reg = MetricsRegistry()
        reg.observe("lat", 0.001)
        reg.observe("lat", 1e9)  # beyond every finite bucket
        text = reg.render_prometheus()
        assert 'lat_bucket{le="+Inf"} 2' in text
        assert "lat_count 2" in text

    def test_label_keys_sorted(self):
        reg = MetricsRegistry()
        reg.inc("c_total", zone="a", alpha="b", mid="c")
        text = reg.render_prometheus()
        assert 'c_total{alpha="b",mid="c",zone="a"} 1.0' in text

    def test_label_value_escaping(self):
        # Prometheus text format: backslash, double quote, and newline must
        # be escaped inside label values.
        reg = MetricsRegistry()
        reg.inc("err_total", msg='pod "a\\b"\nfailed')
        text = reg.render_prometheus()
        assert 'err_total{msg="pod \\"a\\\\b\\"\\nfailed"} 1.0' in text

    def test_labeled_histogram_survives_http_roundtrip(self):
        reg = MetricsRegistry()
        reg.observe("lat", 0.003, job="default/a")
        server = serve_metrics(0, reg)
        try:
            port = server.server_address[1]
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics").read().decode()
            assert 'lat_bucket{job="default/a",le="+Inf"} 1' in text
        finally:
            server.shutdown()
