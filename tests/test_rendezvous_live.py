"""Live re-rendezvous unit layer (docs/ELASTIC.md "Live re-rendezvous").

The coordinator-rebootstrap machinery in workloads/rendezvous.py -- fault
knob parsing, the barrier probe, the GenerationWatcher's re-entry
lifecycle, rebootstrap_jax_distributed's phase errors -- plus the
fallback ladder's observability contract: the rendezvous wire record
through telemetry ingest and the incident recorder's rung stamp / phase
split.  The end-to-end ladder (real llama_elastic survivors, one injected
fault per rung) is driven by ``make resize-smoke``.
"""

import socket
import threading

import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override

apply_jax_platform_override()

from trainingjob_operator_tpu.api import constants
from trainingjob_operator_tpu.obs.goodput import GoodputTracker
from trainingjob_operator_tpu.obs.incident import IncidentRecorder
from trainingjob_operator_tpu.obs.telemetry import TelemetryAggregator
from trainingjob_operator_tpu.utils.metrics import MetricsRegistry
from trainingjob_operator_tpu.workloads import rendezvous
from trainingjob_operator_tpu.workloads.rendezvous import (
    GenerationWatcher,
    RebootstrapError,
    Rendezvous,
)

JOB = "default/rdvjob"


def free_port():
    """A port nothing listens on (bound briefly, then released)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- fault knob ---------------------------------------------------------------

class TestResizeFaults:
    def test_empty_and_absent(self):
        assert rendezvous.resize_faults({}) == {}
        assert rendezvous.resize_faults(
            {constants.RESIZE_FAULT_ENV: ""}) == {}

    def test_unpinned_and_pinned(self):
        spec = rendezvous.resize_faults(
            {constants.RESIZE_FAULT_ENV: "barrier@3, persist"})
        assert spec == {"barrier": 3, "persist": None}

    def test_unknown_phase_ignored(self):
        spec = rendezvous.resize_faults(
            {constants.RESIZE_FAULT_ENV: "warpcore,barrier"})
        assert spec == {"barrier": None}

    def test_garbled_pin_ignored(self):
        spec = rendezvous.resize_faults(
            {constants.RESIZE_FAULT_ENV: "barrier@soon,reinit@2"})
        assert spec == {"reinit": 2}

    def test_check_fault_unpinned_fires_every_generation(self):
        for gen in (1, 7):
            with pytest.raises(RebootstrapError) as ei:
                rendezvous.check_fault("barrier", gen,
                                       faults={"barrier": None})
            assert ei.value.phase == "barrier"
            assert ei.value.injected is True

    def test_check_fault_pinned_fires_only_at_its_generation(self):
        rendezvous.check_fault("barrier", 1, faults={"barrier": 2})
        with pytest.raises(RebootstrapError):
            rendezvous.check_fault("barrier", 2, faults={"barrier": 2})

    def test_check_fault_unarmed_phase_is_silent(self):
        rendezvous.check_fault("reinit", 1, faults={"barrier": None})
        rendezvous.check_fault("reinit", 1, faults={})


# -- coordinator barrier ------------------------------------------------------

class TestCoordinatorBarrier:
    def test_timeout_default_floor_and_garbage(self):
        assert rendezvous.barrier_timeout_s({}) == 30.0
        assert rendezvous.barrier_timeout_s(
            {constants.RESIZE_BARRIER_ENV: "5.5"}) == 5.5
        assert rendezvous.barrier_timeout_s(
            {constants.RESIZE_BARRIER_ENV: "0.0001"}) == 0.1
        assert rendezvous.barrier_timeout_s(
            {constants.RESIZE_BARRIER_ENV: "soon"}) == 30.0

    def test_unreachable_coordinator_is_a_barrier_error(self):
        with pytest.raises(RebootstrapError) as ei:
            rendezvous._await_coordinator(f"127.0.0.1:{free_port()}",
                                          timeout=0.2,
                                          sleep=lambda _d: None)
        assert ei.value.phase == "barrier"

    def test_unparseable_address_is_a_barrier_error(self):
        with pytest.raises(RebootstrapError) as ei:
            rendezvous._await_coordinator("not-an-address", timeout=0.2)
        assert ei.value.phase == "barrier"

    def test_live_coordinator_passes(self):
        with socket.socket() as srv:
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            addr = "127.0.0.1:%d" % srv.getsockname()[1]
            rendezvous._await_coordinator(addr, timeout=2.0)

    def test_late_coordinator_caught_by_backoff(self):
        srv = socket.socket()
        try:
            srv.bind(("127.0.0.1", 0))
            addr = "127.0.0.1:%d" % srv.getsockname()[1]
            t = threading.Timer(0.1, srv.listen, args=(1,))
            t.start()
            try:
                rendezvous._await_coordinator(addr, timeout=5.0)
            finally:
                t.cancel()
        finally:
            srv.close()


# -- GenerationWatcher re-entry lifecycle -------------------------------------

class TestWatcherReentry:
    def _write(self, path, generation, world, mtime):
        path.write_text('{"generation": %d, "world": %s}'
                        % (generation, list(world)))
        import os
        os.utime(path, (mtime, mtime))

    def test_second_bump_in_same_lifetime_surfaces(self, tmp_path):
        p = tmp_path / "generation.json"
        w = GenerationWatcher(path=str(p), birth=0, interval=0.0)
        self._write(p, 1, [0, 1, 2], mtime=100.0)
        doc = w.poll(now=1.0)
        assert doc is not None and doc["generation"] == 1

        w.reenter(1)
        assert w.pending is None
        self._write(p, 2, [0, 1], mtime=200.0)
        doc = w.poll(now=2.0)
        assert doc is not None and doc["generation"] == 2
        assert doc["world"] == [0, 1]

    def test_replayed_doc_at_or_below_reentered_epoch_is_stale(self,
                                                               tmp_path):
        p = tmp_path / "generation.json"
        w = GenerationWatcher(path=str(p), birth=0, interval=0.0)
        self._write(p, 1, [0, 1], mtime=100.0)
        assert w.poll(now=1.0)["generation"] == 1
        w.reenter(1)
        # A slow-NFS replay rewrites the SAME doc with a fresh mtime: the
        # rebootstrap it triggered already happened, it must not re-fire.
        self._write(p, 1, [0, 1], mtime=300.0)
        assert w.poll(now=2.0) is None
        self._write(p, 0, [0], mtime=400.0)  # garbage epoch
        assert w.poll(now=3.0) is None

    def test_reenter_never_rewinds_the_epoch(self, tmp_path):
        p = tmp_path / "generation.json"
        w = GenerationWatcher(path=str(p), birth=5, interval=0.0)
        w.reenter(2)
        assert w.seen == 5
        self._write(p, 4, [0], mtime=100.0)
        assert w.poll(now=1.0) is None


# -- rebootstrap phases -------------------------------------------------------

class TestRebootstrap:
    def test_single_process_passthrough(self):
        rdv = Rendezvous(num_processes=1, process_id=0,
                         rendezvous_generation=0, elastic_replicas=4)
        doc = {"generation": 1, "world": [0, 1]}
        new, timings = rendezvous.rebootstrap_jax_distributed(rdv, doc)
        assert new.rendezvous_generation == 1
        assert new.elastic_replicas == 2
        assert new.num_processes == 1 and new.process_id == 0
        assert set(timings) == {"shutdown_ms", "barrier_ms", "reinit_ms"}

    def test_survivor_absent_from_world_degrades_at_reinit(self):
        rdv = Rendezvous(num_processes=2, process_id=1,
                         coordinator_address="127.0.0.1:1")
        doc = {"generation": 1, "world": [0]}
        with pytest.raises(RebootstrapError) as ei:
            rendezvous.rebootstrap_jax_distributed(rdv, doc,
                                                   old_world=[0, 1])
        assert ei.value.phase == "reinit"

    def test_dead_coordinator_degrades_at_barrier(self, monkeypatch):
        monkeypatch.setenv(constants.RESIZE_BARRIER_ENV, "0.3")
        rdv = Rendezvous(num_processes=2, process_id=1,
                         coordinator_address=f"127.0.0.1:{free_port()}")
        doc = {"generation": 1, "world": [0, 1]}
        with pytest.raises(RebootstrapError) as ei:
            rendezvous.rebootstrap_jax_distributed(
                rdv, doc, old_world=[0, 1], sleep=lambda _d: None)
        assert ei.value.phase == "barrier"
        assert ei.value.injected is False

    @pytest.mark.parametrize("phase", ["shutdown", "barrier", "reinit"])
    def test_injected_fault_fires_even_single_process(self, monkeypatch,
                                                      phase):
        monkeypatch.setenv(constants.RESIZE_FAULT_ENV, phase)
        rdv = Rendezvous(num_processes=1)
        with pytest.raises(RebootstrapError) as ei:
            rendezvous.rebootstrap_jax_distributed(
                rdv, {"generation": 1, "world": [0]})
        assert ei.value.phase == phase
        assert ei.value.injected is True

    def test_fault_pinned_to_other_generation_passes(self, monkeypatch):
        monkeypatch.setenv(constants.RESIZE_FAULT_ENV, "barrier@7")
        rdv = Rendezvous(num_processes=1)
        new, _ = rendezvous.rebootstrap_jax_distributed(
            rdv, {"generation": 1, "world": [0]})
        assert new.rendezvous_generation == 1


# -- the fallback ladder through the real workload ----------------------------

class TestFallbackLadder:
    """llama_elastic's resize cycle end to end, in process: an injected
    fault must land on the documented rung (and only degrade one rung per
    fault).  The subprocess counterpart -- including the live rung's rc 0
    -- is ``make resize-smoke``."""

    def _run(self, monkeypatch, tmp_path, fault):
        import json as _json
        import os as _os

        from trainingjob_operator_tpu.workloads import llama_elastic

        rdv_dir = tmp_path / "rdv"
        rdv_dir.mkdir()
        (rdv_dir / "generation.json").write_text(
            _json.dumps({"generation": 1, "world": [0, 1]}))
        monkeypatch.setenv("LLAMA_STEPS", "6")
        monkeypatch.setenv("LLAMA_CKPT_EVERY", "2")
        monkeypatch.setenv("LLAMA_BATCH", "8")
        monkeypatch.setenv("LLAMA_SEQ", "32")
        monkeypatch.setenv(constants.CHECKPOINT_DIR_ENV,
                           str(tmp_path / "ckpt"))
        monkeypatch.setenv(constants.ELASTIC_REPLICAS_ENV, "4")
        monkeypatch.setenv(constants.RESIZE_DIR_ENV, str(rdv_dir))
        monkeypatch.setenv(constants.RESIZE_POLL_ENV, "0")
        monkeypatch.setenv(constants.RESIZE_FAULT_ENV, fault)
        _os.environ.pop(constants.RENDEZVOUS_GENERATION_ENV, None)
        return llama_elastic.main()

    def test_barrier_fault_forces_checkpoint_rung(self, monkeypatch,
                                                  tmp_path, capsys):
        rc = self._run(monkeypatch, tmp_path, fault="barrier")
        out = capsys.readouterr().out
        assert rc == 143
        assert ("resize_rung generation=1 rung=checkpoint phase=barrier "
                "injected=1") in out
        assert "rung=restart_all" not in out  # degraded exactly one rung

    def test_persist_fault_degrades_to_restart_all_in_order(self,
                                                            monkeypatch,
                                                            tmp_path,
                                                            capsys):
        rc = self._run(monkeypatch, tmp_path, fault="barrier,persist")
        out = capsys.readouterr().out
        assert rc == 143
        assert out.index("rung=checkpoint phase=barrier") < out.index(
            "rung=restart_all phase=persist")


# -- incident attribution of the rung -----------------------------------------

def _resize_window(rec, t0=100.0):
    rec.on_interruption(JOB, "Resize", constants.RESIZE_STARTED_REASON,
                        now=t0)
    rec.record_event(JOB, constants.RESIZE_STARTED_REASON, "shrink",
                     ts=t0 + 0.2)
    rec.on_running(JOB, now=t0 + 1.0)


class TestRungAttribution:
    def _rec(self):
        return IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)

    def test_live_rung_splits_rendezvous_and_reshard(self):
        rec = self._rec()
        _resize_window(rec, t0=100.0)
        rec.record_rendezvous(JOB, total_ms=600.0, rung="live",
                              phases={"shutdown": 100.0, "barrier": 450.0,
                                      "reinit": 50.0}, now=100.6)
        rec.record_step(JOB, step=7, ms=100.0, now=101.8)
        (bundle,) = rec.bundles(JOB)
        assert bundle["kind"] == "resize"
        assert bundle["rung"] == "live"
        # detect runs to the corrective ResizeStarted event (+0.2), the
        # rendezvous segment from there to the record's timestamp (+0.6).
        assert bundle["phases"]["detect"] == pytest.approx(200.0)
        assert bundle["phases"]["rendezvous"] == pytest.approx(400.0)
        assert bundle["phases"]["reshard"] == pytest.approx(1100.0)
        assert bundle["phases"]["first_step"] == pytest.approx(100.0)
        assert bundle["phases"]["teardown"] == 0.0
        assert bundle["phases"]["unknown"] == 0.0
        (entry,) = [t for t in bundle["timeline"]
                    if t["kind"] == "rendezvous"]
        assert entry["rung"] == "live"
        assert dict(entry["phase_ms"])["barrier"] == pytest.approx(450.0)

    def test_degraded_rung_falls_through_to_generic_attribution(self):
        rec = self._rec()
        _resize_window(rec, t0=200.0)
        rec.record_rendezvous(JOB, total_ms=900.0, rung="checkpoint",
                              reason="barrier: injected", now=200.6)
        rec.record_step(JOB, step=7, ms=100.0, now=201.8)
        (bundle,) = rec.bundles(JOB)
        assert bundle["rung"] == "checkpoint"
        # Pods really restarted: no reshard segment, the generic
        # teardown/reschedule/rendezvous split owns the window.
        assert bundle["phases"]["reshard"] == 0.0
        assert bundle["phases"]["unknown"] == 0.0
        assert sum(bundle["phases"].values()) == pytest.approx(
            bundle["downtime_ms"])

    def test_latest_record_in_window_wins(self):
        rec = self._rec()
        _resize_window(rec, t0=300.0)
        rec.record_rendezvous(JOB, total_ms=100.0, rung="live", now=300.4)
        rec.record_rendezvous(JOB, total_ms=900.0, rung="checkpoint",
                              reason="reshard: non-divisible", now=300.8)
        rec.record_step(JOB, step=7, ms=100.0, now=301.8)
        (bundle,) = rec.bundles(JOB)
        assert bundle["rung"] == "checkpoint"

    def test_rung_stamped_before_first_step(self):
        rec = self._rec()
        _resize_window(rec, t0=400.0)
        rec.record_rendezvous(JOB, total_ms=50.0, rung="live", now=400.6)
        (bundle,) = rec.bundles(JOB)
        assert bundle["rung"] == "live"
        assert bundle["phases"]["rendezvous"] > 0.0

    def test_reassembly_is_deterministic(self):
        rec = self._rec()
        _resize_window(rec, t0=500.0)
        rec.record_rendezvous(JOB, total_ms=600.0, rung="live",
                              phases={"barrier": 450.0}, now=500.6)
        rec.record_step(JOB, step=7, ms=100.0, now=501.8)
        first = rec.bundle_json(JOB)
        assert first is not None
        assert rec.reassemble(JOB) == first
        assert rec.reassemble(JOB) == first


# -- telemetry wire record ----------------------------------------------------

class TestRendezvousIngest:
    def _agg(self, **kw):
        kw.setdefault("metrics", MetricsRegistry())
        kw.setdefault("goodput", GoodputTracker(metrics=kw["metrics"]))
        return TelemetryAggregator(**kw)

    def test_rendezvous_record_routes_to_incidents(self):
        rec = IncidentRecorder(metrics=MetricsRegistry(), ring=64, keep=4)
        agg = self._agg(incidents=rec)
        _resize_window(rec, t0=100.0)
        assert agg.ingest({"v": 1, "job": JOB, "rtype": "trainer",
                           "rank": 0, "rendezvous_ms": 600.0,
                           "rendezvous_rung": "live",
                           "rendezvous_phase_ms": {"barrier": 450.0}},
                          now=100.6)
        (bundle,) = rec.bundles(JOB)
        assert bundle["rung"] == "live"

    def test_malformed_rendezvous_records_counted(self):
        reg = MetricsRegistry()
        agg = self._agg(metrics=reg)
        bad = [
            {"job": JOB, "rendezvous_ms": -1.0,
             "rendezvous_rung": "live"},            # negative duration
            {"job": JOB, "rendezvous_ms": 5.0,
             "rendezvous_rung": "sideways"},        # unknown rung
            {"job": "noslash", "rendezvous_ms": 5.0,
             "rendezvous_rung": "live"},            # job not ns/name
            {"job": JOB, "rendezvous_ms": "soon",
             "rendezvous_rung": "live"},            # non-numeric
        ]
        for record in bad:
            assert agg.ingest(record, now=1.0) is False
        snap = reg.snapshot()
        assert snap["trainingjob_telemetry_malformed_total"] == len(bad)
