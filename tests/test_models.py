"""Model family tests (tiny configs, virtual 8-device CPU mesh)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from conftest import apply_jax_platform_override

apply_jax_platform_override()
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from trainingjob_operator_tpu.models import bert, llama, resnet
from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh
from trainingjob_operator_tpu.parallel.sharding import (
    batch_spec,
    shard_pytree,
)


class TestLlama:
    def test_forward_shape_and_finite(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        logits = llama.forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_loss_decreases(self):
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                    cfg.vocab_size)
        batch = {"tokens": tokens}
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            loss, g = jax.value_and_grad(llama.loss_fn)(p, batch, cfg)
            u, o = tx.update(g, o, p)
            return optax.apply_updates(p, u), o, loss

        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_remat_policies_agree(self):
        # Every remat policy is a memory/recompute trade, never a math
        # change: loss identical, grads equal up to bf16 reassociation.
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)
        ref = None
        for pol in [False, True, "full", "attn", "dots", "none"]:
            l, g = jax.value_and_grad(
                lambda pp: llama.loss_fn(pp, {"tokens": tokens}, cfg,
                                         remat=pol))(params)
            gn = float(jax.tree.reduce(
                lambda a, b: a + jnp.sum(b.astype(jnp.float32) ** 2), g, 0.0))
            if ref is None:
                ref = (float(l), gn)
            assert abs(float(l) - ref[0]) < 1e-5, pol
            assert abs(gn - ref[1]) / ref[1] < 2e-2, (pol, gn, ref[1])
        with pytest.raises(ValueError):
            llama.forward(params, tokens[:, :-1], cfg, remat="bogus")

    def test_chunked_ce_is_exact(self):
        # Chunked head+CE trades peak HBM for recompute, never math: loss
        # and grads match the monolithic-logits path exactly in f32.
        cfg = llama.LlamaConfig(**{**llama.LlamaConfig.tiny().__dict__,
                                   "dtype": "float32"})
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)

        def loss(pp, chunk):
            return llama.loss_fn(pp, {"tokens": tokens}, cfg,
                                 ce_chunk=chunk)

        l0, g0 = jax.value_and_grad(loss)(params, 0)
        for chunk in (8, 16, 32):
            l1, g1 = jax.value_and_grad(loss)(params, chunk)
            assert abs(float(l0) - float(l1)) < 1e-6
            for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-6)
        # A chunk that cannot be honored is refused, not silently ignored.
        with pytest.raises(ValueError, match="does not divide"):
            loss(params, 7)

    def test_attn_policy_skips_attention_recompute(self):
        # The trade "attn" sells is structural, not just numeric: the grad
        # jaxpr must not re-run the quadratic attention forward (its [B, H,
        # T, T] score tensors appear only in the fwd + bwd kernels, as under
        # remat "none"), while "full" recomputes them once more per layer.
        import re

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)

        def scores(pol):
            f = jax.grad(lambda pp: llama.loss_fn(
                pp, {"tokens": tokens}, cfg, remat=pol))
            txt = str(jax.make_jaxpr(f)(params))
            return len(re.findall(r"\[2,4,32,32\]", txt))

        none, attn, full = scores("none"), scores("attn"), scores("full")
        assert attn == none, (attn, none)
        assert full > attn, (full, attn)

    def test_param_count_7b(self):
        # Llama-2-7B ~= 6.74e9 params.
        n = llama.num_params(llama.LlamaConfig.llama2_7b())
        assert 6.5e9 < n < 7.0e9

    def test_sequence_parallel_matches_dense(self):
        cfg = llama.LlamaConfig.tiny(n_kv_heads=4)  # MHA for exactness
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        mesh = make_mesh(MeshSpec.of(dp=2, sp=4))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        dense = llama.forward(params, tokens, cfg)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
        ring = llama.forward(params, tok_sh, cfg, mesh=mesh,
                             sequence_parallel=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                                   rtol=3e-2, atol=3e-2)  # bf16 compute

    def test_sharded_train_step_dp_fsdp_tp(self):
        """The multi-chip path: params sharded by rules, batch by dp/fsdp,
        one jitted update step on the virtual mesh."""
        cfg = llama.LlamaConfig.tiny()
        mesh = make_mesh(MeshSpec.of(dp=2, fsdp=2, tp=2))
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        params = shard_pytree(params, llama.SHARDING_RULES, mesh)
        tx = optax.adam(1e-3)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, toks):
            loss, g = jax.value_and_grad(llama.loss_fn)(p, {"tokens": toks}, cfg)
            u, o = tx.update(g, o, p)
            return optax.apply_updates(p, u), o, loss

        params2, opt, loss = step(params, opt, tokens)
        assert bool(jnp.isfinite(loss))
        # Sharding preserved through the step (no silent full replication).
        emb = params2["tok_embed"]
        # (jit normalizes away the trailing None)
        assert emb.sharding.spec in (P(("tp", "fsdp")),
                                     P(("tp", "fsdp"), None))


class TestBert:
    def test_mlm_loss_decreases(self):
        cfg = bert.BertConfig.tiny()
        params = bert.init_params(cfg, jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(key, (4, 32), 0, cfg.vocab_size)
        mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15, (4, 32))
        batch = {"tokens": jnp.where(mask, 103, tokens), "targets": tokens,
                 "mask": mask.astype(jnp.int32)}
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(p, o):
            loss, g = jax.value_and_grad(bert.loss_fn)(p, batch, cfg)
            u, o = tx.update(g, o, p)
            return optax.apply_updates(p, u), o, loss

        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_attention_mask_blocks_padding(self):
        cfg = bert.BertConfig.tiny()
        params = bert.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                    cfg.vocab_size)
        full = bert.forward(params, tokens, cfg)
        # Garbage in padding positions must not change unmasked outputs.
        mask = jnp.array([[True] * 8 + [False] * 8])
        corrupted = tokens.at[0, 8:].set(7)
        a = bert.forward(params, tokens, cfg, attention_mask=mask)
        b = bert.forward(params, corrupted, cfg, attention_mask=mask)
        np.testing.assert_allclose(np.asarray(a[0, :8]), np.asarray(b[0, :8]),
                                   rtol=1e-3, atol=1e-3)
        assert not np.allclose(np.asarray(full[0, :8]), np.asarray(a[0, :8]),
                               atol=1e-4)  # mask actually does something


class TestResNet:
    def test_forward_and_loss_step(self):
        cfg = resnet.ResNetConfig.tiny()
        params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits, new_stats = resnet.forward(params, stats, images, cfg)
        assert logits.shape == (2, cfg.num_classes)
        assert bool(jnp.isfinite(logits).all())
        # Running stats updated in train mode.
        assert not np.allclose(np.asarray(new_stats["stem"]["mean"]),
                               np.asarray(stats["stem"]["mean"]))

    def test_train_loss_decreases_dp_mesh(self):
        cfg = resnet.ResNetConfig.tiny()
        mesh = make_mesh(MeshSpec.of(dp=8))
        params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1), (16, 32, 32, 3))
        labels = jax.random.randint(jax.random.PRNGKey(2), (16,), 0,
                                    cfg.num_classes)
        images = jax.device_put(images, NamedSharding(mesh, P("dp")))
        labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))
        tx = optax.sgd(0.1, momentum=0.9)
        opt = tx.init(params)

        @jax.jit
        def step(p, st, o):
            (loss, new_st), g = jax.value_and_grad(
                resnet.loss_fn, has_aux=True)(
                    p, st, {"images": images, "labels": labels}, cfg)
            u, o = tx.update(g, o, p)
            return optax.apply_updates(p, u), new_st, o, loss

        losses = []
        for _ in range(6):
            params, stats, opt, loss = step(params, stats, opt)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_eval_mode_uses_running_stats(self):
        cfg = resnet.ResNetConfig.tiny()
        params, stats = resnet.init_params(cfg, jax.random.PRNGKey(0))
        images = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        _, st2 = resnet.forward(params, stats, images, cfg, train=False)
        assert np.allclose(np.asarray(st2["stem"]["mean"]),
                           np.asarray(stats["stem"]["mean"]))


class TestGQARing:
    def test_gqa_ring_matches_dense_and_keeps_kv_narrow(self):
        """Regression: ring attention accepts un-repeated GQA kv (narrow
        blocks travel the ring) and matches the dense repeat-based path."""
        cfg = llama.LlamaConfig.tiny()  # n_heads=4, n_kv_heads=2
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        mesh = make_mesh(MeshSpec.of(dp=2, sp=4))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                    cfg.vocab_size)
        dense = llama.forward(params, tokens, cfg)
        tok_sh = jax.device_put(tokens, NamedSharding(mesh, P("dp", "sp")))
        ring = llama.forward(params, tok_sh, cfg, mesh=mesh,
                             sequence_parallel=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                                   rtol=3e-2, atol=3e-2)


class TestMoE:
    """Mixtral-style MoE (models/moe.py): routing correctness vs a naive
    per-token mixture, capacity drops, ep-sharded training."""

    def _layer(self, cfg, key):
        import jax

        from trainingjob_operator_tpu.models import moe

        params = moe.init_params(cfg, key)
        # Unstack layer 0 for direct _moe_mlp calls.
        return jax.tree.map(lambda x: x[0], params["layers"])

    def test_forward_shape_and_finite_loss(self):
        import jax
        import jax.numpy as jnp

        from trainingjob_operator_tpu.models import moe

        cfg = moe.MoEConfig.tiny()
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)
        logits, aux = moe.forward(params, tokens[:, :-1], cfg)
        assert logits.shape == (2, 32, cfg.vocab_size)
        assert bool(jnp.isfinite(aux)) and float(aux) > 0
        loss = moe.loss_fn(params, {"tokens": tokens}, cfg)
        assert bool(jnp.isfinite(loss))

    def test_routing_matches_naive_mixture_with_ample_capacity(self):
        # With capacity >= T*k no token drops: the dense-dispatch einsum
        # formulation must equal the obvious per-token top-k mixture.
        import jax
        import jax.numpy as jnp
        import numpy as np

        from trainingjob_operator_tpu.models import moe

        cfg = moe.MoEConfig.tiny(dim=16, ffn_dim=32, n_experts=4,
                                 experts_per_token=2)
        cfg = moe.MoEConfig(**{**cfg.__dict__, "capacity_factor": 100.0,
                               "dtype": "float32"})
        layer = self._layer(cfg, jax.random.PRNGKey(0))
        h = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        y, _ = moe._moe_mlp(h, layer, cfg, jnp.float32)

        # Naive reference: loop tokens in numpy.
        w = {k: np.asarray(v) for k, v in layer["moe"].items()}
        hn = np.asarray(h, np.float32)
        expect = np.zeros_like(hn)
        for b in range(hn.shape[0]):
            for t in range(hn.shape[1]):
                x = hn[b, t]
                logits = x @ w["router"]
                probs = np.exp(logits - logits.max())
                probs /= probs.sum()
                top = np.argsort(-probs)[:cfg.experts_per_token]
                gates = probs[top] / probs[top].sum()
                for g, e in zip(gates, top):
                    gate = x @ w["w_gate"][e]
                    act = gate / (1 + np.exp(-gate)) * (x @ w["w_up"][e])
                    expect[b, t] += g * (act @ w["w_down"][e])
        np.testing.assert_allclose(np.asarray(y), expect, rtol=2e-4,
                                   atol=2e-4)

    def test_capacity_drops_lowest_priority_tokens(self):
        import jax
        import jax.numpy as jnp

        from trainingjob_operator_tpu.models import moe

        # All probability mass on expert 0 -> with capacity C only C tokens
        # get dispatched per row.
        B, T, E, C = 1, 6, 4, 2
        probs = jnp.zeros((B, T, E)).at[:, :, 0].set(1.0)
        dispatch, combine = moe._dispatch_combine(probs, k=1, capacity=C)
        assert float(dispatch.sum()) == B * C
        # The first C tokens won the slots (priority order is token order).
        assert float(dispatch[0, :C].sum()) == C
        assert float(combine[0, C:].sum()) == 0.0

    def test_ep_sharded_train_step(self):
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import NamedSharding

        from trainingjob_operator_tpu.models import moe
        from trainingjob_operator_tpu.parallel.mesh import MeshSpec, make_mesh
        from trainingjob_operator_tpu.parallel.sharding import (
            batch_spec,
            shard_pytree,
        )

        cfg = moe.MoEConfig.tiny()
        spec = MeshSpec.of(fsdp=2, tp=2, ep=2)
        mesh = make_mesh(spec)
        params = shard_pytree(moe.init_params(cfg, jax.random.PRNGKey(0)),
                              moe.SHARDING_RULES, mesh)
        # Expert weights actually carry the ep axis.
        w_gate = params["layers"]["moe"]["w_gate"]
        assert "ep" in str(w_gate.sharding.spec)
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                    cfg.vocab_size)
        tokens = jax.device_put(tokens, NamedSharding(mesh, batch_spec(mesh)))

        @jax.jit
        def step(p, o, t):
            l, g = jax.value_and_grad(
                lambda pp: moe.loss_fn(pp, {"tokens": t}, cfg, mesh=mesh))(p)
            u, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, u), o2, l

        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt, tokens)
            losses.append(float(loss))
        assert all(jnp.isfinite(jnp.asarray(losses)))
        assert losses[-1] < losses[0]  # memorizes the fixed batch

    def test_param_counts(self):
        from trainingjob_operator_tpu.models import moe

        cfg = moe.MoEConfig.mixtral_8x7b()
        total = moe.num_params(cfg)
        active = moe.active_params(cfg)
        assert 45e9 < total < 50e9       # Mixtral-8x7B ~46.7B
        assert 12e9 < active < 14e9      # ~12.9B active per token
        assert active < total


class TestMoEChunkedCE:
    def test_chunked_matches_monolithic(self):
        import jax

        from trainingjob_operator_tpu.models import moe

        cfg = moe.MoEConfig.tiny()
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype="float32")
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1),
                                              (2, 33), 0, cfg.vocab_size)}
        full = float(moe.loss_fn(params, batch, cfg))
        chunked = float(moe.loss_fn(params, batch, cfg, ce_chunk=8))
        assert abs(full - chunked) < 1e-4

    def test_bad_chunk_raises(self):
        import jax
        import pytest as _pytest

        from trainingjob_operator_tpu.models import moe

        cfg = moe.MoEConfig.tiny()
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1),
                                              (2, 33), 0, cfg.vocab_size)}
        with _pytest.raises(ValueError, match="ce_chunk"):
            moe.loss_fn(params, batch, cfg, ce_chunk=7)


class TestQuantizedDecode:
    """Weight-only int8 decode (models/quant.py): decode streams every
    weight per token, so int8 halves the HBM bytes that bound throughput;
    correctness = quantized logits track fp logits closely."""

    def _setup(self):
        from trainingjob_operator_tpu.models import decode

        cfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                    cfg.vocab_size)
        return decode, cfg, params, prompt

    def test_weights_are_int8_with_small_error(self):
        from trainingjob_operator_tpu.models import quant

        cfg = llama.LlamaConfig.tiny(n_layers=2)
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        qp = quant.quantize_weights(params)
        assert qp["layers"]["attn"]["wq"]["q"].dtype == jnp.int8
        assert qp["tok_embed"]["q"].dtype == jnp.int8
        # Norm scales stay fp.
        assert qp["layers"]["attn_norm"].dtype == jnp.float32
        errs = quant.quantization_error(params)
        assert errs and all(e < 0.02 for e in errs.values()), errs

    def test_quantized_decode_logits_track_fp(self):
        decode, cfg, params, prompt = self._setup()
        from trainingjob_operator_tpu.models import quant

        _, cache = decode.prefill(params, prompt, cfg, max_len=16)
        token = prompt[:, -1]
        t = jnp.int32(prompt.shape[1] - 1)
        fp_logits, _ = decode.decode_step(params, cache, token, t, cfg)
        q_logits, _ = decode.decode_step(quant.quantize_weights(params),
                                         cache, token, t, cfg)
        a = np.asarray(fp_logits, np.float64)
        b = np.asarray(q_logits, np.float64)
        cos = (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.99, cos

    def test_generate_quantized_runs(self):
        decode, cfg, params, prompt = self._setup()
        q = np.asarray(decode.generate(params, prompt, cfg, steps=8,
                                       quantize=True))
        assert q.shape == (2, 8)
        assert q.min() >= 0 and q.max() < cfg.vocab_size
        # (Token-level agreement with fp is NOT asserted: a random-init
        # tiny model has near-uniform logits, and one near-tie argmax flip
        # diverges the whole autoregressive rollout; the logit-cosine test
        # above is the correctness check.)


class TestMoESlidingWindow:
    def test_windowed_moe_trains(self, monkeypatch):
        import dataclasses

        from trainingjob_operator_tpu.models import moe

        monkeypatch.setenv("TRAININGJOB_PALLAS", "interpret")
        cfg = dataclasses.replace(moe.MoEConfig.tiny(), sliding_window=8)
        params = moe.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                    cfg.vocab_size)
        loss, grads = jax.value_and_grad(lambda p: moe.loss_fn(
            p, {"tokens": tokens}, cfg))(params)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g)))
                   for g in jax.tree.leaves(grads))
        # The window changes the attention pattern (different loss than
        # full causal).
        full = float(moe.loss_fn(params, {"tokens": tokens},
                                 dataclasses.replace(cfg, sliding_window=0)))
        assert abs(float(loss) - full) > 1e-6
