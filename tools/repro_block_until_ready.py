#!/usr/bin/env python
"""Repro: on the axon PJRT runtime, ``jax.block_until_ready`` may return
without waiting for device execution, making naive timed loops measure
dispatch overhead instead of step time (VERDICT r3, Missing #1).

Times the same jitted matmul chain three ways:
  1. loop + block_until_ready        (the broken r1-r3 bench pattern)
  2. loop + float(x) device-to-host  (forces a real sync)
  3. per-iteration float(x)          (fully synchronous lower bound)

If (1) << (2), block_until_ready is not synchronizing on this runtime.
Prints one JSON line with all three per-step times.
"""
import json
import time

import jax
import jax.numpy as jnp


def main():
    n, steps = 4096, 20

    @jax.jit
    def f(x):
        for _ in range(8):
            x = jnp.tanh(x @ x)
        return x

    x = jnp.ones((n, n), jnp.bfloat16)
    f(x).block_until_ready()  # compile

    t0 = time.perf_counter()
    y = x
    for _ in range(steps):
        # analyzer: allow[donation-discipline] deliberately undonated: the
        # repro times the dispatch chain as-is; aliasing would change the
        # measured allocation behaviour this script exists to compare.
        y = f(y)
    jax.block_until_ready(y)
    t_bur = (time.perf_counter() - t0) / steps

    t0 = time.perf_counter()
    y = x
    for _ in range(steps):
        y = f(y)
    float(y[0, 0])  # device-to-host transfer: cannot complete early
    t_d2h = (time.perf_counter() - t0) / steps

    t0 = time.perf_counter()
    y = x
    for _ in range(steps):
        y = f(y)
        # analyzer: allow[host-sync-in-hot-loop] the per-iteration D2H IS
        # the experiment: this loop measures the fully synchronous lower
        # bound that block_until_ready is compared against.
        float(y[0, 0])
    t_sync = (time.perf_counter() - t0) / steps

    print(json.dumps({
        "platform": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "step_ms_block_until_ready": round(t_bur * 1e3, 3),
        "step_ms_loop_then_d2h": round(t_d2h * 1e3, 3),
        "step_ms_per_iter_d2h": round(t_sync * 1e3, 3),
        "block_until_ready_broken": t_bur < 0.5 * t_d2h,
    }))


if __name__ == "__main__":
    main()
