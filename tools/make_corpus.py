"""Build a .tokens corpus (data/tokens.py format) from text files.

Byte-level tokenization (vocab 256) by default -- zero external tokenizer
dependencies, reversible, and enough to train/sample real text end to end:

    python tools/make_corpus.py out.tokens input1.txt input2.txt ...
    LLAMA_DATA=out.tokens python -m trainingjob_operator_tpu.workloads.llama_elastic

With --vocab-from-json VOCAB.json (a {"token": id} map, e.g. an exported BPE
vocab) input must be pre-tokenized ids, one sequence of space-separated ints
per line.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    import numpy as np

    from trainingjob_operator_tpu.data import write_tokens

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("output", help="path for the .tokens file")
    ap.add_argument("inputs", nargs="+", help="text files (utf-8)")
    ap.add_argument("--vocab-from-json", default=None,
                    help="treat inputs as space-separated ids; vocab size "
                         "is taken from this {token: id} json map")
    args = ap.parse_args(argv)

    if args.vocab_from_json:
        with open(args.vocab_from_json) as f:
            # Ids need not be dense 0..len-1 (pads, reserved, gaps): the
            # vocab size is the highest id + 1.
            vocab = max(json.load(f).values()) + 1
        ids = []
        for path in args.inputs:
            with open(path) as f:
                for line in f:
                    ids.extend(int(x) for x in line.split())
        tokens = np.asarray(ids, np.int64)
    else:
        vocab = 256
        chunks = []
        for path in args.inputs:
            with open(path, "rb") as f:
                chunks.append(np.frombuffer(f.read(), np.uint8))
        tokens = np.concatenate(chunks).astype(np.int64)

    n = write_tokens(args.output, tokens, vocab_size=vocab)
    print(f"{args.output}: {n} tokens, vocab {vocab}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
