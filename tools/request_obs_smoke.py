"""Seeded request-plane smoke: zero orphans, stanza coverage, zero footprint.

The ``make request-obs-smoke`` driver (wired into ``make ci``): one
in-process serving arm plus two subprocess fleet arms exercising the
request-lifecycle plane (obs/reqtrace.py, docs/SERVING.md).

- **wire**: a real DecodeService pushes request records over the TCP
  telemetry wire -- completed requests, an over-capacity rejection, and a
  mid-flight ``drain_abort`` eviction.  Every submitted id must reach a
  terminal outcome: ``reconcile()`` files ZERO orphans, and TTFT/TPOT
  percentiles materialize from the completed spans.
- **fleet / plane on** (``--chaos --request-obs``): churn includes
  mid-flight CR deletes (scale-in drain) and exit-137 pod kills with
  restart; the run must converge with zero violations -- which bundles in
  the two plane invariants: zero orphaned requests after reconcile, and
  every restart incident's bundle carrying a ``requests`` stanza.
- **fleet / plane off**: same churn + chaos seeds without the plane.  The
  chaos plan digest and final phase counts must be byte-identical to the
  plane-on arm, and the report's ``requests`` field must be null --
  auditing the fleet must not perturb it.

Usage::

    python -m tools.request_obs_smoke [--jobs 24] [--seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _wire_arm() -> int:
    """DecodeService -> TCP sink -> aggregator -> ledger, end to end."""
    import jax

    from trainingjob_operator_tpu.api import constants
    from trainingjob_operator_tpu.models import llama
    from trainingjob_operator_tpu.obs.reqtrace import REQTRACE
    from trainingjob_operator_tpu.obs.telemetry import (
        TelemetryEmitter,
        TelemetrySink,
    )
    from trainingjob_operator_tpu.workloads import serve

    job = "smoke/reqobs"
    os.environ[constants.JOB_NAMESPACE_ENV] = "smoke"
    os.environ[constants.JOB_NAME_ENV] = "reqobs"
    REQTRACE.reset()
    REQTRACE.start()
    sink = TelemetrySink(publish=False).start()
    try:
        emitter = TelemetryEmitter(addr=sink.address)
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        svc = serve.DecodeService(params, cfg, slots=2, prefill_chunk=4,
                                  queue_cap=6, emitter=emitter)
        for _ in range(4):
            svc.submit([1, 2, 3, 4], 3)
        for _ in range(6):
            svc.step()
        # Overflow: fill whatever queue room is left, then one more must
        # be rejected -- a terminal outcome on the wire, not a lost id.
        try:
            for _ in range(svc.queue_cap + 1 - len(svc.queue)):
                svc.submit([1, 2, 3], 2)
        except serve.QueueFull:
            pass
        else:
            print("overflow never raised QueueFull", file=sys.stderr)
            return 1
        # Scale-in analogue: abort everything still queued or decoding.
        evicted = svc.drain_abort()
        submitted = svc._next_rid
        deadline = time.monotonic() + 10.0
        summary = None
        while time.monotonic() < deadline:
            summary = REQTRACE.job_summary(job)
            if summary and summary["records_total"] >= submitted:
                break
            time.sleep(0.05)
        if not summary or summary["records_total"] < submitted:
            print(f"wire arm: only {summary and summary['records_total']} "
                  f"of {submitted} records reached the ledger",
                  file=sys.stderr)
            return 1
        orphans = REQTRACE.reconcile(time.time())
        summary = REQTRACE.job_summary(job) or {}
        outcomes = summary.get("outcomes", {})
        print(f"wire: submitted={submitted} outcomes={outcomes} "
              f"evicted_by_drain={len(evicted)} orphans={orphans} "
              f"ttft_p99={summary.get('ttft_ms_p99')}")
        if orphans:
            print(f"wire arm: {orphans} orphaned request(s) despite every "
                  f"id reaching a terminal state", file=sys.stderr)
            return 1
        for outcome in ("completed", "rejected", "evicted"):
            if not outcomes.get(outcome):
                print(f"wire arm: no {outcome!r} outcome recorded",
                      file=sys.stderr)
                return 1
        if summary.get("ttft_ms_p99") is None:
            print("wire arm: completed spans but no TTFT percentiles",
                  file=sys.stderr)
            return 1
    finally:
        sink.stop()
        REQTRACE.stop()
    return 0


def _run(args: argparse.Namespace, extra=()) -> dict:
    cmd = [sys.executable, "-m", "trainingjob_operator_tpu.fleet.harness",
           "--jobs", str(args.jobs),
           "--seed", str(args.seed),
           "--duration", str(args.duration),
           "--replicas-min", "1", "--replicas-max", "3",
           "--workers", "4", "--chaos",
           "--chaos-seed", str(args.chaos_seed),
           "--converge-timeout", str(args.converge_timeout), "--quiet"]
    cmd += list(extra)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-8:]
        raise SystemExit("request-obs fleet run failed (rc=%d):\n%s"
                         % (proc.returncode, "\n".join(tail)))
    return json.loads(proc.stdout)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("request-obs-smoke")
    parser.add_argument("--jobs", type=int, default=24)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=1.5)
    parser.add_argument("--converge-timeout", type=float, default=120.0)
    args = parser.parse_args(argv)

    # -- Arm 1: real service over the real wire ----------------------------
    rc = _wire_arm()
    if rc:
        return rc

    # -- Arm 2: fleet churn with the plane on ------------------------------
    on = _run(args, extra=["--request-obs"])
    req = on.get("requests") or {}
    print(f"fleet on: converged={on['converged']} "
          f"records={req.get('records_total')} "
          f"orphans={req.get('orphaned_after_reconcile')} "
          f"bundles={req.get('incident_bundles')} "
          f"with_stanza={req.get('bundles_with_requests')}")
    if not on["converged"] or on["violations"]:
        print("plane-on fleet run did not converge cleanly:\n"
              + "\n".join(on["violations"][:10]), file=sys.stderr)
        return 1
    if not req.get("records_total"):
        print("plane on but no request records reached the ledger",
              file=sys.stderr)
        return 1
    if req.get("orphaned_after_reconcile") != 0:
        print(f"{req.get('orphaned_after_reconcile')} orphaned request(s) "
              f"after scale-in drains and exit-137 restarts",
              file=sys.stderr)
        return 1
    if not req.get("bundles_with_requests"):
        print("no incident bundle carries a requests stanza",
              file=sys.stderr)
        return 1

    # -- Arm 3: same seeds, plane off -- the plane must not perturb --------
    off = _run(args)
    if (on["chaos"]["plan_digest"] != off["chaos"]["plan_digest"]
            or on["phase_counts"] != off["phase_counts"]):
        print("request plane perturbed the fleet:\n"
              f"  digest  on={on['chaos']['plan_digest']}\n"
              f"          off={off['chaos']['plan_digest']}\n"
              f"  phases  on={on['phase_counts']}\n"
              f"          off={off['phase_counts']}", file=sys.stderr)
        return 1
    if off.get("requests") is not None:
        print("plane-off report unexpectedly carries a requests rollup",
              file=sys.stderr)
        return 1

    print(f"request-obs smoke ok: plan {on['chaos']['plan_digest'][:12]} "
          f"records={req['records_total']} orphans=0 "
          f"stanza_bundles={req['bundles_with_requests']} "
          f"phase_counts={on['phase_counts']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
