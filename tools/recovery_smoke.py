"""Recovery fast-path smoke: cold run, serial warm resume, overlapped warm
resume, at tiny shapes (CI-speed; the measured 124M version is bench.py's
``time_to_resume_training`` leg).

Three llama_elastic subprocess runs against one checkpoint dir:

1. COLD (fresh dir): trains 2 steps, seeds the checkpoint and the persistent
   compile cache.
2. WARM SERIAL (``TRAININGJOB_RESUME_OVERLAP=0``,
   ``TRAININGJOB_CKPT_SNAPSHOT=0``): must resume at step 2 and report
   ``resume_overlap=0`` plus a ``ckpt_stall mode=sync`` line -- the A/B
   baseline path stays alive.
3. WARM OVERLAPPED (defaults): must resume at step 4 and report
   ``resume_overlap=1`` plus ``ckpt_stall mode=snapshot``, and its
   restore/compile wall must not exceed their sum (overlap sanity; the
   speedup itself is asserted only at 124M where phases dwarf noise).

Exits non-zero on any violation, so ``make recovery-smoke`` is a real CI
gate for the resume pipeline, not a smoke signal.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import tempfile


def _run(env_extra, timeout=300.0):
    env = dict(os.environ, **env_extra)
    proc = subprocess.run(
        [sys.executable, "-m",
         "trainingjob_operator_tpu.workloads.llama_elastic"],
        env=env, capture_output=True, text=True, timeout=timeout)
    if proc.returncode != 0:
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:], file=sys.stderr)
        raise SystemExit(f"llama_elastic rc={proc.returncode}")
    return proc.stdout


def _phases(out):
    return {k: float(v) for k, v in re.findall(r"(\w+_s)=([0-9.]+)", out)}


def _check(cond, message):
    if not cond:
        raise SystemExit(f"recovery-smoke FAILED: {message}")
    print(f"ok: {message}", flush=True)


def main() -> int:
    ckpt = tempfile.mkdtemp(prefix="recovery-smoke-")
    base = {"TRAININGJOB_CHECKPOINT_DIR": ckpt,
            "TRAININGJOB_JAX_PLATFORM": "cpu",
            "LLAMA_CKPT_EVERY": "2", "LLAMA_BATCH": "2", "LLAMA_SEQ": "32"}

    cold = _run(dict(base, LLAMA_STEPS="2"))
    _check("recovery_timing" in cold and "first_step_s" in cold,
           "cold run prints the recovery_timing breakdown")

    serial = _run(dict(base, LLAMA_STEPS="4",
                       TRAININGJOB_RESUME_OVERLAP="0",
                       TRAININGJOB_CKPT_SNAPSHOT="0"))
    _check("resumed at step 2" in serial, "serial warm run resumed at step 2")
    _check("resume_overlap=0" in serial, "serial run reports resume_overlap=0")
    _check("ckpt_stall mode=sync" in serial,
           "sync-handoff save path reports its stall line")

    warm = _run(dict(base, LLAMA_STEPS="6"))
    _check("resumed at step 4" in warm, "overlapped warm run resumed at step 4")
    _check("resume_overlap=1" in warm, "overlapped run reports resume_overlap=1")
    _check("ckpt_stall mode=snapshot" in warm,
           "snapshot-donate save path reports its stall line")
    p = _phases(warm)
    _check({"restore_s", "compile_s", "resume_phases_wall_s"} <= set(p),
           "overlapped run itemizes restore/compile/wall")
    # Overlap sanity at tiny scale: the wall may not exceed running the two
    # phases back to back (plus scheduler slack on a loaded 1-core box).
    _check(p["resume_phases_wall_s"] <= p["restore_s"] + p["compile_s"] + 2.0,
           f"resume wall {p['resume_phases_wall_s']:.2f}s <= "
           f"restore {p['restore_s']:.2f} + compile {p['compile_s']:.2f} "
           f"+ slack")
    print("recovery-smoke PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
