"""Seeded SLO-plane smoke: no false alarms, real alarms, zero footprint.

The ``make slo-smoke`` driver (wired into ``make ci``): three subprocess
runs of the fleet harness exercising the fleet SLO plane (docs/SLO.md).
Subprocesses, not in-process runs: the tsdb, SLO engine, profiler,
incident recorder and metrics registry are process-global singletons, so
only a fresh interpreter gives each arm a clean slate.

- **healthy / plane on** (``--chaos --slo --profile``): the default
  objectives must hold under the stock chaos magnitudes -- ANY breach on
  this arm is a false positive.  The profiler must attribute >= 90% of
  busy worker samples to spans under ``sync_job`` and cost < 5% of wall.
- **healthy / plane off**: same churn + chaos seeds without the plane.
  The chaos plan digest and final phase counts must be byte-identical to
  the plane-on arm -- observing the fleet must not perturb it.
- **degraded**: same harness with per-write API latency injected and the
  event->visible objective tightened below it (env overrides, tight
  burn-rate windows so the breach fires inside the run).  The engine must
  raise >= 1 breach, the breach must land as an ``SLOBreach`` event, and
  at least one incident bundle must carry the breached objective.

Usage::

    python -m tools.slo_smoke [--jobs 40] [--seed 0] [--chaos-seed 0]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def _run(args: argparse.Namespace, extra=(), env_overrides=None,
         jobs=None) -> dict:
    cmd = [sys.executable, "-m", "trainingjob_operator_tpu.fleet.harness",
           "--jobs", str(jobs if jobs is not None else args.jobs),
           "--seed", str(args.seed),
           "--duration", str(args.duration),
           "--replicas-min", "1", "--replicas-max", "3",
           "--workers", "4", "--chaos",
           "--chaos-seed", str(args.chaos_seed),
           "--converge-timeout", str(args.converge_timeout), "--quiet"]
    cmd += list(extra)
    env = dict(os.environ)
    if env_overrides:
        env.update(env_overrides)
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=600, env=env)
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-8:]
        raise SystemExit("slo fleet run failed (rc=%d):\n%s"
                         % (proc.returncode, "\n".join(tail)))
    return json.loads(proc.stdout)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("slo-smoke")
    parser.add_argument("--jobs", type=int, default=40)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=2.0)
    parser.add_argument("--converge-timeout", type=float, default=120.0)
    args = parser.parse_args(argv)

    # -- Arm 1: healthy fleet, plane on -- zero false breaches -------------
    on = _run(args, extra=["--slo", "--profile"])
    verdicts = on.get("slo_verdicts") or {}
    prof = on.get("profile_top") or {}
    attribution = (prof.get("span_attribution") or {}).get("ratio")
    overhead = prof.get("overhead_ratio")
    print(f"healthy: converged={on['converged']} "
          f"breaches={verdicts.get('breaches_total')} "
          f"profiler_samples={prof.get('samples_total')} "
          f"attribution={attribution} overhead={overhead}")
    if not on["converged"] or on["violations"]:
        print("healthy plane-on run did not converge cleanly:\n"
              + "\n".join(on["violations"][:10]), file=sys.stderr)
        return 1
    if verdicts.get("breaches_total") != 0:
        print(f"healthy fleet raised {verdicts.get('breaches_total')} "
              f"breach(es) -- false positive: {verdicts.get('slos')}",
              file=sys.stderr)
        return 1
    if not prof.get("samples_total"):
        print("profiler collected no samples", file=sys.stderr)
        return 1
    if attribution is None or attribution < 0.9:
        print(f"span attribution {attribution} < 0.9: the profiler lost "
              f"the reconcile path (top: {prof.get('top')})",
              file=sys.stderr)
        return 1
    if overhead is None or overhead >= 0.05:
        print(f"profiler overhead {overhead} >= 5% of wall",
              file=sys.stderr)
        return 1

    # -- Arm 2: same seeds, plane off -- the plane must not perturb --------
    off = _run(args)
    if (on["chaos"]["plan_digest"] != off["chaos"]["plan_digest"]
            or on["phase_counts"] != off["phase_counts"]):
        print("SLO plane perturbed the fleet:\n"
              f"  digest  on={on['chaos']['plan_digest']}\n"
              f"          off={off['chaos']['plan_digest']}\n"
              f"  phases  on={on['phase_counts']}\n"
              f"          off={off['phase_counts']}", file=sys.stderr)
        return 1

    # -- Arm 3: degraded fleet -- the alarm must actually fire -------------
    # 250 ms injected per controller write vs a 100 ms event->visible
    # objective; fast sweep/eval cadence and sub-second burn windows so
    # multi-window confirmation lands inside the run.
    degraded = _run(
        args, extra=["--slo", "--api-latency", "0.25"], jobs=20,
        env_overrides={
            "TRAININGJOB_SLO_EVENT_P99_MS": "100",
            "TRAININGJOB_TSDB_INTERVAL_S": "0.1",
            "TRAININGJOB_SLO_EVAL_S": "0.2",
            "TRAININGJOB_SLO_WINDOWS": "0.5:1.5",
        })
    dv = degraded.get("slo_verdicts") or {}
    print(f"degraded: converged={degraded['converged']} "
          f"breaches={dv.get('breaches_total')} "
          f"breach_events={dv.get('breach_events')} "
          f"stamped_bundles={dv.get('stamped_bundles')}")
    if not dv.get("breaches_total"):
        print(f"degraded fleet raised no breach -- the engine is blind: "
              f"{dv.get('slos')}", file=sys.stderr)
        return 1
    if not dv.get("breach_events"):
        print("breach fired but no SLOBreach event reached the recorder",
              file=sys.stderr)
        return 1
    if not dv.get("stamped_bundles"):
        print("breach fired but no incident bundle carries slo_breaches",
              file=sys.stderr)
        return 1

    print(f"slo smoke ok: plan {on['chaos']['plan_digest'][:12]} "
          f"healthy breaches=0 degraded breaches="
          f"{dv['breaches_total']} phase_counts={on['phase_counts']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
